"""MetaService: the cluster's coordination brain (meta node role).

Reference counterparts, collapsed into one object:

- ``ClusterController`` worker registry + heartbeat expiry
  (src/meta/src/manager/cluster.rs) — workers register, beat, and are
  declared dead after ``heartbeat_timeout_s`` of silence;
- ``DdlController`` + streaming job placement
  (src/meta/src/rpc/ddl_controller.rs) — DDL lands in the durable
  catalog log, streaming jobs are scheduled onto compute workers
  (job-level placement: least-loaded live worker, MV-on-MV co-located
  with its upstream job);
- ``GlobalBarrierWorker`` (src/meta/src/barrier/worker.rs:378) — the
  global checkpoint protocol: one *round* injects a barrier into every
  job on every worker, collects per-job epoch seals, and only when ALL
  jobs sealed the round commits ONE cluster epoch through the
  versioned manifest (storage/hummock/version.py) — so a snapshot
  read pinned at that commit sees every MV at the same round;
- recovery (SURVEY.md §3.5) — on missed heartbeats the worker is
  marked dead, its jobs are reassigned to survivors and recovered
  from their last durable checkpoint; counter-addressed sources make
  the replay exact, so the cluster converges to the byte-identical
  result of an undisturbed run.

Pacing contract: compute workers have NO self-ticker — every chunk
and barrier a job processes is driven by a meta ``tick()`` round.
That makes the meta the global serializer for checkpoint-store
commits (one barrier RPC in flight at a time), which is what keeps
the shared manifest single-writer without a distributed lock.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from dataclasses import dataclass, field

from risingwave_tpu.cluster.rpc import (
    RpcClient,
    RpcError,
    RpcServer,
)
from risingwave_tpu.common.faults import RetryPolicy, get_fabric
from risingwave_tpu.common.metrics import MetricsRegistry, merge_prometheus
from risingwave_tpu.common.trace import (
    GLOBAL_TRACE,
    merge_dumps,
    round_ids,
    spans_for_round,
    tree_check,
)
from risingwave_tpu.meta.store import MetaStore


@dataclass
class WorkerInfo:
    """One registered compute worker (ref WorkerNode)."""

    worker_id: int
    host: str
    port: int
    pid: int | None = None
    alive: bool = True
    last_seen: float = field(default_factory=time.monotonic)
    #: job names assigned to this worker
    jobs: set = field(default_factory=set)
    client: RpcClient | None = None
    #: SST keys allocated to this worker for MV exports, not yet
    #: returned in a barrier seal (released as orphans on death)
    sst_keys: set = field(default_factory=set)

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class ServingReplicaInfo:
    """One registered serving replica (the stateless read tier).

    ``pins`` maps manifest vid → meta-side pin id: the replica's HELD
    version and its latest GRANT both stay pinned in the meta's
    ``VersionManager``, so vacuum counts them in its keep-set — a
    serving read can never lose an SST underneath it.  The lease
    advances on heartbeats (the replica reports the vid it holds; the
    meta releases older pins and pins the current version as the next
    grant) and is reaped wholesale when the replica's heartbeat
    expires."""

    replica_id: int
    host: str
    port: int
    pid: int | None = None
    alive: bool = True
    last_seen: float = field(default_factory=time.monotonic)
    client: RpcClient | None = None
    #: manifest vid -> VersionManager pin id
    pins: dict = field(default_factory=dict)
    granted_vid: int = 0

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class PartitionInfo:
    """One vnode partition of a partitioned job (the scale plane's
    barrier unit).  ``lineage`` is the partition's checkpoint key in
    the SHARED store — it survives worker moves (failover re-adopts
    the lineage on a new worker; scale-in slices it into recipients).
    Field names mirror ``JobInfo`` so the round protocol drives jobs
    and partitions through one code path."""

    lineage: str
    worker_id: int | None = None
    #: owned vnode ids (current map)
    vnodes: list = field(default_factory=list)
    #: lost its last vnode in a handover: no longer a barrier unit,
    #: but keeps serving reads pinned at PRE-handover rounds until the
    #: first post-handover commit publishes a new serve plan — then it
    #: is dropped and released
    retiring: bool = False
    #: cluster round this partition has sealed up to
    rounds: int = 0
    #: (round, epoch_value) per sealed barrier, round-ascending
    seal_log: list = field(default_factory=list)
    pinned_epoch: int = 0
    #: vnode set at the last cluster commit — reads pinned at that
    #: round route with THIS set, so a mid-handover read still sees
    #: every row exactly once
    pinned_vnodes: list = field(default_factory=list)
    durable_epoch: int = 0

    @property
    def name(self) -> str:  # unit key in seal records / pending SSTs
        return self.lineage


@dataclass
class JobInfo:
    """One placed streaming job (ref TableFragments / StreamingJob).

    ``mvs`` lists every MV/sink riding the job (MV-on-MV attaches to
    its upstream's job, exactly like the engine merges DagJobs).
    ``seal_log`` records (round, committed_epoch) per successful
    barrier — the map recovery uses to translate a recovered epoch
    back into a round position.
    """

    name: str
    ddl: list = field(default_factory=list)
    mvs: list = field(default_factory=list)
    worker_id: int | None = None
    #: cluster round this job has sealed up to
    rounds: int = 0
    #: (round, epoch_value) per sealed barrier, round-ascending
    seal_log: list = field(default_factory=list)
    #: epoch value serving reads pin for this job (last CLUSTER commit)
    pinned_epoch: int = 0
    #: last durable (upload-acked) epoch the worker reported — the
    #: cluster epoch commits only when this catches the round's seal
    durable_epoch: int = 0
    #: vnode partitions (scale plane) — None = whole-job placement;
    #: keyed by checkpoint lineage, ONE partition per owning worker
    partitions: "dict[str, PartitionInfo] | None" = None
    #: DML tables the job's source reads (exchanged worker↔worker)
    dml_tables: list = field(default_factory=list)
    #: Exchange-lite: raw source column each DML table routes by
    #: (absent/None = untraceable → the table's edge replicates)
    shuffle_cols: dict = field(default_factory=dict)
    #: edge taxonomy per table ("source" ingest / "join" side)
    edge_kinds: dict = field(default_factory=dict)
    #: MV-on-MV attach edges riding this job: (upstream, downstream)
    attach_edges: list = field(default_factory=list)
    #: read-routing plan published ATOMICALLY at each cluster commit:
    #: [(worker_id, pinned_epoch, vnodes)] — all entries from the SAME
    #: round, so a fan-out read sees every vnode exactly once even
    #: while a handover is reshaping the live partition set
    serve_plan: list | None = None


#: SQL aggregate names (the serve router refuses to union these
#: across partitions — per-partition partials are not the answer)
_AGG_FUNCS = frozenset({
    "count", "sum", "sum0", "min", "max", "avg", "stddev_pop",
    "stddev_samp", "var_pop", "var_samp", "bool_and", "bool_or",
    "string_agg", "approx_count_distinct",
})


def _sql_literal(v) -> str:
    """Render one pk value as a SQL literal (the multi-get owner
    fallback synthesizes per-pk SELECTs)."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    return "'" + str(v).replace("'", "''") + "'"


def _select_needs_engine_merge(sel) -> bool:
    """True when a SELECT over a partitioned MV cannot be answered by
    unioning per-partition rows (aggregates / GROUP BY / DISTINCT
    merge rows ACROSS partitions)."""
    from risingwave_tpu.sql import ast

    if sel.group_by or sel.having is not None \
            or getattr(sel, "distinct", False):
        return True

    def has_agg(e) -> bool:
        if isinstance(e, ast.FuncCall) and e.name in _AGG_FUNCS:
            return True
        for a in ("left", "right", "operand", "expr"):
            v = getattr(e, a, None)
            if v is not None and has_agg(v):
                return True
        return any(has_agg(x) for x in getattr(e, "args", ())
                   if not isinstance(x, ast.Star))

    return any(has_agg(item.expr) for item in sel.items
               if not isinstance(item.expr, ast.Star))


class MetaService:
    """The meta node.  ``start()`` brings up the RPC server and the
    heartbeat monitor; tests may also drive every method in-process."""

    def __init__(self, data_dir: str, heartbeat_timeout_s: float = 3.0,
                 metrics: MetricsRegistry | None = None,
                 serve_retry_timeout_s: float = 60.0,
                 rpc_timeout_s: float = 180.0,
                 durable_wait_s: float = 15.0,
                 retry_max_attempts: int = 4,
                 retry_base_delay_s: float = 0.05,
                 retry_max_delay_s: float = 0.5,
                 n_vnodes: int = 64,
                 scale_partitioning: bool = False,
                 scrub_interval_s: float = 30.0,
                 shuffle_ingest: bool = True):
        from risingwave_tpu.storage.hummock import (
            CompactorService,
            HummockStorage,
            LocalFsObjectStore,
            ScrubberService,
        )

        self.data_dir = data_dir
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.serve_retry_timeout_s = serve_retry_timeout_s
        self.rpc_timeout_s = rpc_timeout_s
        #: how long one tick() waits for the round's checkpoint
        #: uploads to ack before returning the round uncommitted
        #: (retried by the next tick — rounds never commit past a
        #: non-durable seal)
        self.durable_wait_s = durable_wait_s
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        #: durable DDL log — the same store a single node replays, so a
        #: restarted meta (or a single-node takeover) can rebuild the
        #: cluster catalog
        self.store = MetaStore(data_dir)
        #: the meta-owned storage service over the shared data_dir:
        #: the version manifest (meta is its SINGLE writer — workers
        #: upload SST objects under meta-allocated keys and hand the
        #: descriptors back through barrier seals), the background
        #: compactor, and pin-aware vacuum.  ``versions`` stays the
        #: cluster-epoch commit point it always was.
        self.hummock = HummockStorage(
            LocalFsObjectStore(os.path.join(data_dir, "hummock")),
            metrics=self.metrics,
        )
        self.versions = self.hummock.versions
        # gentler poll than the embedded default: the meta shares its
        # core with the barrier loop and the RPC server
        self.compactor = CompactorService(self.hummock,
                                          poll_interval_s=0.05)
        # -- integrity: scrub + quarantine + self-healing repair -------
        #: corrupt objects currently under repair (dedups concurrent
        #: detections of the same object)
        self._repairing: set = set()
        self._repair_lock = threading.Lock()
        self.repairs = {"sst": 0, "checkpoint": 0}
        #: corrupt SST keys workers surfaced through barrier responses
        #: (repaired after the round, outside the tick lock)
        self._corrupt_reports: list = []
        #: every detection point routes here: compaction reads, scrub
        #: walks, serving-replica reports — quarantine + repair, off
        #: the latency path
        self.hummock.on_corruption = self._on_corruption
        #: the background scrubber (meta-owned, a compactor sibling):
        #: paced off-barrier verification of every pinned-version SST
        #: and retained checkpoint lineage over the SHARED data_dir
        self.scrubber = ScrubberService(
            self.hummock,
            ckpt_object_store=LocalFsObjectStore(data_dir),
            metrics=self.metrics,
            interval_s=scrub_interval_s,
            on_corruption=self._on_corruption,
        )
        self._lock = threading.RLock()
        #: serializes barrier rounds AND failover reassignment: a job
        #: is never adopted while one of its barrier RPCs is in flight
        self._tick_lock = threading.Lock()
        #: single-flights _assign_pending: the monitor loop, DDL
        #: placement, and registration all drive it — two assigners
        #: interleaving their adopt probes would point a worker's
        #: checkpoint lineage somewhere the registry never records
        self._assign_lock = threading.Lock()
        self.workers: dict[int, WorkerInfo] = {}
        #: registered serving replicas (the stateless read tier)
        self.serving: dict[int, ServingReplicaInfo] = {}
        self._next_replica = 1
        #: round-robin cursor for serving-read routing
        self._serve_rr = 0
        #: (job_name, round) -> uploaded-but-uncommitted MV export SST
        #: descriptors; committed into the manifest with the round's
        #: cluster epoch, replaced when a failover re-seals the round
        self._pending_ssts: dict[tuple, list] = {}
        #: pushdown plane: expiry-policy docs staged by barrier
        #: responses (table → doc, None = DROP), committed into the
        #: same manifest delta as the round's export SSTs
        self._pending_policies: dict = {}
        self.jobs: dict[str, JobInfo] = {}
        #: mv/sink name -> owning JobInfo name
        self._mv_to_job: dict[str, str] = {}
        #: secondary indexes: index name → upstream MV name (an MV
        #: with live indexes refuses DROP until they are dropped)
        self._indexes: dict[str, str] = {}
        #: non-job DDL in arrival order (sources/tables/SETs/functions)
        #: — shipped to a worker the first time a job needs them
        self.prelude: list[str] = []
        self._next_worker = 1
        #: committed cluster epoch (round number, 0 = nothing committed)
        self.cluster_epoch = 0
        self.failovers = 0
        # -- trace-lite (common/trace.py) round-trace state ------------
        #: the round whose root span ``_trace_root_ctx`` belongs to: a
        #: RETRIED round (previous tick didn't commit) parents its new
        #: attempt under the ORIGINAL root, so trace "round-N" keeps
        #: exactly one root span however many ticks the round takes
        self._trace_round = 0
        self._trace_root_ctx: tuple | None = None
        #: last COMMITTED round's root ctx — piggybacked on serving
        #: lease grants so sampled replica read spans join the round
        #: tree of the epoch they actually read
        self._last_round_ctx: tuple | None = None
        #: unified backoff for every retry-safe control RPC the meta
        #: issues (barrier/job_epochs/adopt are idempotent or
        #: round-guarded; RpcError — the peer REFUSED — never retries)
        self.retry = RetryPolicy(
            max_attempts=retry_max_attempts,
            base_delay_s=retry_base_delay_s,
            max_delay_s=retry_max_delay_s,
            metrics=self.metrics, op="meta",
        )
        self._server: RpcServer | None = None
        self._monitor: threading.Thread | None = None
        self._stop = threading.Event()
        # -- elastic scale plane (cluster/scale) -----------------------
        #: ring size of the global vnode keyspace
        self.n_vnodes = int(n_vnodes)
        #: opt-in: place ELIGIBLE jobs as vnode partitions over the
        #: active worker set (``ctl cluster scale N`` then moves only
        #: vnodes).  Off = whole-job placement (the pre-scale plane).
        self.scale_partitioning = bool(scale_partitioning)
        #: Exchange-lite sliced ingest (default ON).  Off = the PR-7
        #: replicate-everything fan-out — kept as the A/B baseline the
        #: scale_stress throughput gate measures against, and the
        #: escape hatch if a traced shuffle key misbehaves in the
        #: field.  Flipping it re-pushes the choreography.
        self.shuffle_ingest = bool(shuffle_ingest)
        #: vnode → worker_id (None until the first map is cut)
        self.vnode_map: list[int] | None = None
        #: the ACTIVE worker set (capacity follows ``scale N``, not
        #: registration — spare workers idle until scaled in)
        self.active_workers: list[int] = []
        self._next_lineage = 1
        self._routing_version = 0
        self.scale_ops = 0
        #: per-round DML fence cache (a retried round reuses the fence
        #: its survivors sealed with — cursor alignment across retries)
        self._fence_round = 0
        self._fence_cache: dict[str, int] = {}
        #: True when this meta rebuilt jobs from a durable catalog (a
        #: restart) — introspection for operators and chaos asserts
        self.recovered = False
        self._recover_from_store()
        self._set_worker_gauges()

    # -- crash recovery ---------------------------------------------------
    def _recover_from_store(self) -> None:
        """Meta restart: rebuild the cluster catalog (jobs, MV→job map,
        prelude) by replaying the durable DDL log, then restore the
        round position from the last committed-round record.  Every
        job comes back UNASSIGNED — workers detect the dead meta
        through heartbeat errors, re-register with backoff, and
        ``_assign_pending`` re-adopts their jobs from the last durable
        checkpoint; ``_rewind_job`` translates each recovered epoch
        back into a round (crediting a round the old meta sealed but
        never committed — the in-flight round re-seals, it never
        re-runs).  No operator action anywhere on this path."""
        ddl = self.store.ddl_log()
        if not ddl:
            return
        self.recovered = True
        for sql in ddl:
            self.execute_ddl(sql, replay=True)
        # scale plane: the last scale event restores the vnode map and
        # each partitioned job's lineage layout.  Worker ids in the map
        # are STALE (a restarted meta hands out fresh ids) — every
        # partition comes back unassigned and ``_assign_pending``
        # re-adopts its lineage (recover=True) on re-registered
        # workers, re-pointing the map as it goes.
        ev = self.store.last_scale_event()
        if ev is not None:
            self.scale_partitioning = True
            self.n_vnodes = int(ev.get("n_vnodes", self.n_vnodes))
            self.vnode_map = [int(w) for w in ev["map"]] \
                if ev.get("map") else None
            self._next_lineage = int(ev.get("next_lineage", 1))
            for jname, parts in (ev.get("partitions") or {}).items():
                job = self.jobs.get(jname)
                if job is None:
                    continue
                job.partitions = {
                    p["lineage"]: PartitionInfo(
                        lineage=p["lineage"],
                        worker_id=None,
                        vnodes=[int(v) for v in p["vnodes"]],
                    )
                    for p in parts
                }
                job.dml_tables = list(ev.get("dml_tables", {})
                                      .get(jname, []))
                job.shuffle_cols = {
                    t: (int(c) if c is not None else None)
                    for t, c in (ev.get("shuffle_cols", {})
                                 .get(jname, {})).items()
                }
                job.edge_kinds = dict(ev.get("edge_kinds", {})
                                      .get(jname, {}))
                job.attach_edges = [
                    tuple(e) for e in (ev.get("attach_edges", {})
                                       .get(jname, []))
                ]
        rec = self.store.last_cluster_commit()
        if rec is None:
            return
        self.cluster_epoch = int(rec["round"])
        for job in self.jobs.values():
            job.rounds = self.cluster_epoch
            for unit in (job.partitions.values() if job.partitions
                         else [job]):
                seal = rec["seals"].get(unit.name)
                unit.rounds = self.cluster_epoch
                if seal is not None:
                    unit.seal_log = [(self.cluster_epoch, int(seal))]
                    unit.pinned_epoch = int(seal)
                    if unit is not job:
                        unit.pinned_vnodes = list(unit.vnodes)
        self.metrics.set_gauge("cluster_epoch_committed",
                               self.cluster_epoch)
        self.metrics.set_gauge("cluster_manifest_epoch",
                               self.versions.max_committed_epoch)

    # -- lifecycle ------------------------------------------------------
    @property
    def rpc_port(self) -> int:
        return self._server.port if self._server is not None else 0

    def start(self, host: str = "127.0.0.1", port: int = 0,
              monitor: bool = True, compactor: bool = True,
              scrubber: bool = True) -> "MetaService":
        self._stop.clear()
        self._server = RpcServer(self, host, port).start()
        if monitor:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="meta-monitor",
                daemon=True,
            )
            self._monitor.start()
        if compactor:
            # the shared-storage compactor rides the meta process (the
            # manifest's single writer); in-process tests may pass
            # compactor=False and drive hummock.compact_once directly
            self.compactor.start()
        if scrubber:
            # the scrub walk is read-only + paced; repairs go through
            # the same quarantine pipeline every detection point uses
            self.scrubber.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.scrubber.stop()
        self.compactor.stop()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        with self._lock:
            for w in self.workers.values():
                if w.client is not None:
                    w.client.close()
            for r in self.serving.values():
                if r.client is not None:
                    r.client.close()

    # -- worker registry / heartbeats -----------------------------------
    def rpc_register_worker(self, host: str, port: int,
                            pid: int | None = None) -> dict:
        with self._lock:
            wid = self._next_worker
            self._next_worker += 1
            w = WorkerInfo(wid, host, int(port), pid)
            w.client = RpcClient(host, int(port),
                                 timeout=self.rpc_timeout_s,
                                 src="meta", dst=f"worker{wid}")
            self.workers[wid] = w
            self._set_worker_gauges()
        # a fresh worker can pick up any stranded jobs immediately
        self._assign_pending()
        return {"worker_id": wid, "cluster_epoch": self.cluster_epoch}

    def rpc_heartbeat(self, worker_id: int) -> dict:
        with self._lock:
            w = self.workers.get(int(worker_id))
            if w is None or not w.alive:
                # a dead-marked worker must re-register: its jobs may
                # already run elsewhere (ref: expired workers rejoin
                # through the registration path)
                raise ValueError(f"unknown or expired worker {worker_id}")
            w.last_seen = time.monotonic()
        return {"ok": True, "cluster_epoch": self.cluster_epoch}

    def rpc_unregister_worker(self, worker_id: int) -> dict:
        """Graceful deregistration (scale-in decommission, orderly
        shutdown): the worker leaves the registry ENTIRELY — jobs
        reassign exactly like a death, and every per-worker metric
        series is retired so the scrape surface reflects the live
        membership, not tombstones."""
        with self._lock:
            w = self.workers.get(int(worker_id))
        if w is None:
            return {"ok": True, "known": False}
        self._on_worker_dead(w)
        with self._lock:
            self.workers.pop(w.worker_id, None)
            self._remove_worker_series(w.worker_id)
            self._set_worker_gauges()
        self._push_routing()
        return {"ok": True, "known": True}

    def _remove_worker_series(self, worker_id: int) -> None:
        """Retire EVERY per-worker labeled series of one worker (death
        or deregistration) — stale gauges must not linger forever on
        the scrape surface."""
        for name in ("cluster_worker_heartbeat_age_seconds",
                     "cluster_worker_vnodes"):
            self.metrics.remove_series(name, worker=str(worker_id))
        if worker_id in getattr(self, "_exchange_series", set()):
            self._exchange_series.discard(worker_id)
            for k in ("rows_out", "rows_in", "batches_out",
                      "batches_in", "send_failures"):
                self.metrics.remove_series(
                    f"cluster_worker_exchange_{k}",
                    worker=str(worker_id),
                )

    def live_workers(self) -> list[WorkerInfo]:
        with self._lock:
            return [w for w in self.workers.values() if w.alive]

    def _set_worker_gauges(self) -> None:
        self.metrics.set_gauge(
            "cluster_live_workers",
            sum(1 for w in self.workers.values() if w.alive),
        )
        self.metrics.set_gauge("cluster_jobs", len(self.jobs))
        self.metrics.set_gauge(
            "cluster_serving_replicas",
            sum(1 for r in self.serving.values() if r.alive),
        )
        self.metrics.set_gauge(
            "cluster_serving_pins",
            sum(len(r.pins) for r in self.serving.values()),
        )

    def _monitor_loop(self) -> None:
        interval = min(self.heartbeat_timeout_s / 4, 0.5)
        while not self._stop.wait(interval):
            self.check_heartbeats()

    def check_heartbeats(self) -> None:
        """One monitor pass: refresh age gauges, expire silent workers,
        reassign their jobs (also called directly by tests).  Serving
        replicas expire on the same cadence — a dead replica's epoch
        pin lease is reaped immediately so it can never block vacuum
        forever."""
        now = time.monotonic()
        expired: list[WorkerInfo] = []
        stale_serving: list[ServingReplicaInfo] = []
        with self._lock:
            for w in self.workers.values():
                if not w.alive:
                    continue
                age = now - w.last_seen
                self.metrics.set_gauge(
                    "cluster_worker_heartbeat_age_seconds", age,
                    worker=str(w.worker_id),
                )
                if age > self.heartbeat_timeout_s:
                    expired.append(w)
            for r in self.serving.values():
                if not r.alive:
                    continue
                self.metrics.set_gauge(
                    "cluster_serving_heartbeat_age_seconds",
                    now - r.last_seen, replica=str(r.replica_id),
                )
                if now - r.last_seen > self.heartbeat_timeout_s:
                    stale_serving.append(r)
        for w in expired:
            self._on_worker_dead(w)
        for r in stale_serving:
            self._on_serving_dead(r)
        pending = any(
            (j.worker_id is None if j.partitions is None
             else any(p.worker_id is None
                      for p in j.partitions.values()))
            for j in self.jobs.values()
        )
        if expired or pending:
            self._assign_pending()

    def _on_serving_dead(self, r: ServingReplicaInfo) -> None:
        """Reap one serving replica: drop it from routing, release
        every pin of its lease (stale leases must not hold GC keep-set
        entries for a process that will never read again), and RETIRE
        its per-replica metric series — a reaped replica must not
        leave frozen gauges on the scrape surface (mirrors the
        per-worker retirement)."""
        with self._lock:
            if not r.alive:
                return
            r.alive = False
            for pin_id in r.pins.values():
                self.versions.unpin(pin_id)
            r.pins.clear()
            if r.client is not None:
                r.client.close()
            self.serving.pop(r.replica_id, None)
            self._remove_serving_series(r.replica_id)
            self._set_worker_gauges()

    def _remove_serving_series(self, replica_id: int) -> None:
        """Retire EVERY per-replica labeled series of one serving
        replica (lease reaped or deregistered)."""
        for name in ("cluster_serving_heartbeat_age_seconds",
                     "cluster_serving_granted_vid"):
            self.metrics.remove_series(name, replica=str(replica_id))

    def _on_worker_dead(self, w: WorkerInfo) -> None:
        # under the tick lock: never declare dead / reassign while one
        # of the worker's barrier RPCs is still in flight (a stale
        # barrier finishing late must not interleave checkpoint writes
        # with the new owner's)
        with self._tick_lock:
            with self._lock:
                if not w.alive:
                    return
                w.alive = False
                self.failovers += 1
                self.metrics.inc("cluster_failovers_total")
                self._remove_worker_series(w.worker_id)
                for name in list(w.jobs):
                    job = self.jobs[name]
                    if job.partitions:
                        # the partition's LINEAGE survives in the
                        # shared store; _assign_pending re-adopts it
                        # (state + vnodes) on a free worker
                        for p in job.partitions.values():
                            if p.worker_id == w.worker_id:
                                p.worker_id = None
                    else:
                        job.worker_id = None
                w.jobs.clear()
                # allocated-but-never-sealed export keys become
                # vacuumable orphans; keys already riding a sealed
                # round stay protected in _pending_ssts
                pending = {s["key"] for ssts in
                           self._pending_ssts.values() for s in ssts}
                for key in w.sst_keys - pending:
                    self.hummock.release_external_sst_key(key)
                w.sst_keys.clear()
                if w.client is not None:
                    w.client.close()
                self._set_worker_gauges()

    # -- serving replicas: registry + epoch pin leases -------------------
    def rpc_register_serving(self, host: str, port: int,
                             pid: int | None = None) -> dict:
        """Register a serving replica and grant its FIRST epoch pin
        lease: the current manifest version is pinned meta-side BEFORE
        the grant leaves, so every SST the replica can reach stays in
        the vacuum keep-set from the very first read."""
        with self._lock:
            rid = self._next_replica
            self._next_replica += 1
            r = ServingReplicaInfo(rid, host, int(port), pid)
            # pooled connections: concurrent serving-read routers must
            # not serialize behind one in-flight batch frame
            r.client = RpcClient(host, int(port),
                                 timeout=self.rpc_timeout_s,
                                 src="meta", dst=f"serving{rid}",
                                 pool=4)
            pin_id, version = self.versions.pin()
            r.pins[version.vid] = pin_id
            r.granted_vid = version.vid
            self.serving[rid] = r
            self.metrics.set_gauge("cluster_serving_granted_vid",
                                   r.granted_vid, replica=str(rid))
            self._set_worker_gauges()
        self.hummock._update_gauges()
        return {
            "replica_id": rid,
            "granted_vid": r.granted_vid,
            "cluster_epoch": self.cluster_epoch,
            "manifest_epoch": self.versions.max_committed_epoch,
            "trace_ctx": list(self._last_round_ctx)
            if self._last_round_ctx else None,
        }

    def rpc_serving_heartbeat(self, replica_id: int,
                              vid: int = 0) -> dict:
        """One lease round-trip: the replica reports the manifest vid
        it HOLDS (acking older grants), the meta releases pins below
        it, pins the current version as the next grant, and returns
        the grant.  The replica only ever advances to granted vids, so
        its held version is pinned at all times — vacuum can never
        reap an SST under a live serving read."""
        with self._lock:
            r = self.serving.get(int(replica_id))
            if r is None or not r.alive:
                raise ValueError(
                    f"unknown or expired serving replica {replica_id}"
                )
            r.last_seen = time.monotonic()
            held = int(vid)
            pin_id, version = self.versions.pin()
            if version.vid in r.pins:
                self.versions.unpin(pin_id)
            else:
                r.pins[version.vid] = pin_id
            r.granted_vid = version.vid
            # keep exactly the held version and the fresh grant; every
            # pin in between was a grant the replica skipped past
            keep = {held, version.vid}
            for pv in [p for p in r.pins if p not in keep]:
                self.versions.unpin(r.pins.pop(pv))
            self.metrics.set_gauge(
                "cluster_serving_granted_vid", r.granted_vid,
                replica=str(r.replica_id),
            )
            self._set_worker_gauges()
        return {
            "ok": True,
            "granted_vid": r.granted_vid,
            "cluster_epoch": self.cluster_epoch,
            "manifest_epoch": self.versions.max_committed_epoch,
            # last committed round's root span ctx: the replica tags
            # its SAMPLED read spans with it, so each round trace
            # carries the reads served at that epoch
            "trace_ctx": list(self._last_round_ctx)
            if self._last_round_ctx else None,
        }

    def rpc_unregister_serving(self, replica_id: int) -> dict:
        with self._lock:
            r = self.serving.get(int(replica_id))
        if r is not None:
            self._on_serving_dead(r)
        return {"ok": True}

    # -- external SST allocation (worker MV exports) ---------------------
    def rpc_alloc_sst(self, worker_id: int) -> dict:
        """Allocate one vacuum-protected SST key for a worker's MV
        export upload (the single allocator keeps keys collision-free
        across worker processes)."""
        with self._lock:
            w = self.workers.get(int(worker_id))
            if w is None or not w.alive:
                raise ValueError(f"unknown or expired worker {worker_id}")
        key = self.hummock.alloc_external_sst_key()
        with self._lock:
            w.sst_keys.add(key)
        return {"key": key}

    # -- storage service (vacuum rides the meta) -------------------------
    def storage_vacuum(self) -> dict:
        """GC pass over the shared store: deletes SST objects
        unreferenced by the current version, any serving pin lease, or
        an in-flight allocation."""
        deleted = self.hummock.vacuum()
        return {"deleted_objects": deleted,
                "remaining_objects": self.hummock.stats()["objects"]}

    def rpc_storage_vacuum(self) -> dict:
        return self.storage_vacuum()

    # -- integrity: corruption reports, quarantine, self-healing repair --
    def _on_corruption(self, kind: str, key: str,
                       context: "dict | None" = None) -> None:
        """Sink for every meta-side detection point (scrub walk,
        compaction read).  Repairs run synchronously on the calling
        background thread — both are already off the latency path."""
        self.report_corruption(key, kind=kind,
                               reason=(context or {}).get("error", ""),
                               by="scrubber", sync=True)

    def rpc_report_corruption(self, key: str, kind: str = "sst",
                              reason: str = "", by: str = "") -> dict:
        """A peer (serving replica, compute worker) hit corrupt shared
        bytes: quarantine immediately, repair in the background so the
        reporter's read path is never blocked on the repair."""
        return self.report_corruption(key, kind=kind, reason=reason,
                                      by=by, sync=False)

    def report_corruption(self, key: str, kind: str = "sst",
                          reason: str = "", by: str = "",
                          sync: bool = True) -> dict:
        self.metrics.inc("integrity_errors_total", kind=kind)
        with self._repair_lock:
            if key in self._repairing:
                return {"ok": True, "repair": "in_progress"}
            self._repairing.add(key)

        def _run() -> dict:
            try:
                if kind in ("sst", "sst_block", "sst_footer"):
                    self.hummock.quarantine_sst(
                        key, reason or "reported", by=by or "report")
                    repaired = self._repair_sst(key)
                    cat = "sst"
                elif kind == "checkpoint":
                    repaired = self._repair_checkpoint(key)
                    cat = "checkpoint"
                else:
                    # manifest chain damage has no re-derivable source:
                    # durable note + loud metric, operator escalation
                    from risingwave_tpu.storage.integrity import (
                        quarantine,
                    )
                    quarantine(self.hummock.store, key,
                               reason or "manifest corruption",
                               by=by or "report",
                               metrics=self.metrics)
                    return {"ok": True, "repair": "quarantined"}
                if repaired is True:
                    with self._repair_lock:
                        self.repairs[cat] = self.repairs.get(cat, 0) + 1
                    self.metrics.inc("integrity_repairs_total",
                                     kind=cat)
                return {"ok": True,
                        "repair": "done" if repaired else "pending"}
            finally:
                with self._repair_lock:
                    self._repairing.discard(key)

        if sync:
            return _run()
        threading.Thread(target=_run, name="integrity-repair",
                         daemon=True).start()
        return {"ok": True, "repair": "scheduled"}

    def _mvs_overlapping(self, info) -> list[str]:
        """MV names whose storage key range intersects one SstInfo —
        the owners whose rows a corrupt export SST may carry."""
        from risingwave_tpu.serve.reader import mv_key_range

        out = []
        with self._lock:
            mvs = list(self._mv_to_job)
        for mv in mvs:
            lo, hi = mv_key_range(mv)
            if info.last_key >= lo and info.first_key < hi:
                out.append(mv)
        return out

    def _repair_sst(self, key: str) -> bool:
        """Self-heal one corrupt MV-export SST: every owning job's live
        worker re-exports the affected MVs IN FULL (diff base re-seeded
        from the manifest minus the corrupt object, so shadowed
        tombstones re-emit), then ONE version delta atomically swaps
        the corrupt SST for the fresh exports — readers never see a
        window with the rows missing.  Owners that are dead/unassigned
        leave the repair pending; the next scrub cycle retries."""
        with self._tick_lock:
            v = self.hummock.versions.current
            info = next((s for lv in v.levels for s in lv
                         if s.key == key), None)
            if info is None:
                # already swapped out (or never committed): nothing to
                # repair — truthy so the caller stops retrying, but
                # distinct so it is not COUNTED as a repair
                return "noop"
            jobs = sorted({self._mv_to_job[m]
                           for m in self._mvs_overlapping(info)
                           if m in self._mv_to_job})
            targets: list = []
            with self._lock:
                for jname in jobs:
                    job = self.jobs.get(jname)
                    if job is None:
                        continue
                    units = list(job.partitions.values()) \
                        if job.partitions else [job]
                    for u in units:
                        if getattr(u, "retiring", False):
                            continue
                        w = self.workers.get(u.worker_id) \
                            if u.worker_id is not None else None
                        if w is None or not w.alive:
                            return False  # owner mid-failover: retry
                        targets.append((jname, w))
            from risingwave_tpu.storage.hummock.version import SstInfo

            fresh: list[SstInfo] = []
            for jname, w in targets:
                try:
                    res = self.retry.run(
                        lambda w=w, jname=jname: w.client.call(
                            "reexport", job=jname, exclude=[key]),
                        label="reexport",
                    )
                except (RpcError, ConnectionError, OSError):
                    return False  # keep the corrupt SST until healed
                for s in res.get("ssts") or []:
                    fresh.append(SstInfo(
                        key=s["key"],
                        first_key=bytes.fromhex(s["first_key"]),
                        last_key=bytes.fromhex(s["last_key"]),
                        n_records=int(s["n_records"]),
                        size=int(s["size"]),
                    ))
            self.hummock.replace_sst(key, fresh)
            return True

    def _repair_checkpoint(self, key: str) -> bool:
        """Route a corrupt checkpoint epoch object to its OWNING worker
        for lineage repair (quarantine + truncate to the last verified
        epoch — the worker holds the manifest lock for its own
        commits).  An ownerless lineage self-heals at its next
        adoption: the verified load rewinds past the corruption."""
        lineage = key.split("/epoch_")[0].split("@spill")[0]
        with self._lock:
            target = None
            for j in self.jobs.values():
                if j.partitions:
                    p = j.partitions.get(lineage)
                    if p is not None and p.worker_id is not None:
                        target = (self.workers.get(p.worker_id), j.name)
                elif j.name == lineage and j.worker_id is not None:
                    target = (self.workers.get(j.worker_id), j.name)
        if target is None or target[0] is None or not target[0].alive:
            return False
        w, _jname = target
        try:
            res = self.retry.run(
                lambda: w.client.call("repair_checkpoint",
                                      lineage=lineage),
                label="repair_checkpoint",
            )
        except (RpcError, ConnectionError, OSError):
            return False
        return bool(res.get("ok"))

    def _drain_corrupt_reports(self) -> None:
        """Repair corrupt SSTs workers surfaced in barrier responses
        (collected under the tick lock, repaired outside it)."""
        with self._lock:
            due, self._corrupt_reports = self._corrupt_reports, []
        for key in due:
            self.report_corruption(key, kind="sst",
                                   reason="worker export seam",
                                   by="worker", sync=True)

    def rpc_cluster_scrub(self) -> dict:
        return self.cluster_scrub()

    def cluster_scrub(self) -> dict:
        """``ctl cluster scrub``: ONE full synchronous scrub cycle over
        every pinned-version SST and retained checkpoint lineage, with
        the quarantine/repair pipeline armed — plus the integrity
        bookkeeping an operator needs."""
        from risingwave_tpu.storage.integrity import quarantine_list

        report = self.scrubber.run_once()
        report["quarantined"] = [
            n.get("key") for n in quarantine_list(self.hummock.store)
        ]
        if self.scrubber.ckpt_store is not None:
            # checkpoint quarantine notes live in the checkpoint root
            # (written by the owning worker's lineage repair)
            report["quarantined"] += [
                n.get("key")
                for n in quarantine_list(self.scrubber.ckpt_store)
            ]
        with self._repair_lock:
            report["repairs"] = dict(self.repairs)
        return report

    # -- DDL / placement -------------------------------------------------
    def rpc_execute_ddl(self, sql: str) -> dict:
        return self.execute_ddl(sql)

    def execute_ddl(self, sql: str, replay: bool = False) -> dict:
        """Apply one or more statements at the cluster level: job DDL
        places a streaming job, everything else joins the prelude all
        future jobs replay.  ``replay=True`` (meta crash recovery)
        rebuilds the in-memory catalog from the already-durable log:
        nothing is re-appended, no worker is called, no job assigned
        (workers re-register and re-adopt on their own schedule)."""
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse_with_text

        placed: list[str] = []
        for text, stmt in parse_with_text(sql):
            if isinstance(stmt, (ast.CreateMaterializedView,
                                 ast.CreateSink)):
                self._place_job(text, stmt.name, replay=replay)
                placed.append(stmt.name)
            elif isinstance(stmt, ast.CreateIndex):
                # a secondary-index MV rides its upstream's job (the
                # engine attaches it MV-on-MV and exports it into the
                # shared serving keyspace like any MV)
                self._place_job(text, stmt.name, replay=replay,
                                upstream_mv=stmt.table)
                self._indexes[stmt.name] = stmt.table
                placed.append(stmt.name)
            elif isinstance(stmt, ast.DropStatement) \
                    and stmt.kind in ("materialized view", "index"):
                self._drop_mv(text, stmt, replay=replay)
            elif isinstance(stmt, (ast.Insert, ast.Delete,
                                   ast.Update)):
                # never reaches the DDL log; forwarded rows (marked
                # marker-tail for DELETE; UPDATE desugars to the
                # retraction pair on the owning worker) live in the
                # workers' durable table history + checkpoints
                if not replay:
                    self._forward_dml(text, stmt.table)
            else:
                if not replay:
                    self.store.append_ddl(text)
                self.prelude.append(text)
        return {"ok": True, "placed": placed,
                "cluster_epoch": self.cluster_epoch}

    def _co_located_job(self, text: str) -> "JobInfo | None":
        """MV-on-MV placement: a query referencing an existing MV must
        land on that MV's job (the engine attaches it to the same
        DagJob there)."""
        import re

        for mv, jname in self._mv_to_job.items():
            if re.search(rf"\b{re.escape(mv)}\b", text):
                # partitioned upstreams attach too (Exchange-lite):
                # every partition worker adopts the same delta; the
                # engine validates the attach-edge exchange is the
                # identity choreography and refuses reduced-key shapes
                return self.jobs[jname]
        return None

    def _place_job(self, text: str, name: str,
                   replay: bool = False,
                   upstream_mv: str | None = None) -> None:
        import re

        if name in self._mv_to_job:
            raise ValueError(f"{name!r} already exists")
        if upstream_mv is not None:
            # an index ALWAYS co-locates onto its upstream's job
            # (validated BEFORE the durable append so a refused
            # statement can never poison the replay log)
            if upstream_mv not in self._mv_to_job:
                raise ValueError(
                    f"CREATE INDEX on {upstream_mv!r}: "
                    f"{upstream_mv!r} does not exist"
                )
            upstream = self.jobs[self._mv_to_job[upstream_mv]]
            if upstream.partitions:
                raise ValueError(
                    f"CREATE INDEX over partitioned job "
                    f"{upstream.name!r}: next round (attach would "
                    "need a cross-partition exchange)"
                )
        else:
            upstream = self._co_located_job(text)
        if not replay:
            self.store.append_ddl(text)
        if upstream is not None:
            # ship only the prelude delta the job hasn't seen yet plus
            # the new statement; the worker attaches it to the live job
            sent = len(upstream.ddl) - len(upstream.mvs)
            delta = self.prelude[sent:] + [text]
            if upstream.partitions:
                # partitioned upstream: EVERY partition worker attaches
                # the same chain (the engine's _plan_partition_attach
                # proves the attach edge needs no cross-partition row
                # movement).  Probe the FIRST partition before
                # mutating any meta state — a refused plan must leave
                # the catalog (and the durable log position) untouched
                with self._lock:
                    ws = [self.workers[p.worker_id]
                          for p in upstream.partitions.values()
                          if p.worker_id is not None
                          and not p.retiring]
                if not replay:
                    if not ws:
                        raise ValueError(
                            f"MV-on-MV over {upstream.name!r}: no "
                            "live partition worker to attach on"
                        )
                    self.retry.run(
                        lambda: ws[0].client.call(
                            "adopt", ddl=delta, name=upstream.name,
                            recover=False),
                        label="adopt",
                    )
                    for w in ws[1:]:
                        self.retry.run(
                            lambda w=w: w.client.call(
                                "adopt", ddl=delta,
                                name=upstream.name, recover=False),
                            label="adopt",
                        )
                upstream.ddl.extend(delta)
                upstream.mvs.append(name)
                with self._lock:
                    self._mv_to_job[name] = upstream.name
                    up_mv = next(
                        (m for m in self._mv_to_job
                         if m != name and re.search(
                             rf"\b{re.escape(m)}\b", text)
                         and self._mv_to_job[m] == upstream.name),
                        upstream.name,
                    )
                    upstream.attach_edges.append((up_mv, name))
                self._push_routing()
                return
            upstream.ddl.extend(delta)
            upstream.mvs.append(name)
            with self._lock:
                self._mv_to_job[name] = upstream.name
            if not replay and upstream.worker_id is not None:
                w = self.workers[upstream.worker_id]
                self.retry.run(
                    lambda: w.client.call("adopt", ddl=delta,
                                          name=upstream.name,
                                          recover=False),
                    label="adopt",
                )
            return
        job = JobInfo(name=name, ddl=list(self.prelude) + [text],
                      mvs=[name])
        # a job created after commits joins at the current round: it
        # seals the NEXT round with everyone else
        job.rounds = self.cluster_epoch
        with self._lock:
            self.jobs[name] = job
            self._mv_to_job[name] = name
            self._set_worker_gauges()
        if not replay:
            self._assign_pending()

    def _drop_mv(self, text: str, stmt, replay: bool = False) -> None:
        """DROP MATERIALIZED VIEW / DROP INDEX at the cluster level:
        the owning worker drops it from its engine (the DROP also
        joins ``job.ddl`` so future adopts replay it), the meta
        unplaces it (last MV ⇒ the job leaves the round protocol),
        writes TOMBSTONES for every exported row in one delta, and
        deletes the serve-schema doc — serving answers "does not
        exist" instead of stale rows (ROADMAP round-8 follow-up).

        Ordering matters for replicas: schema docs are rewritten
        BEFORE the tombstone delta commits, so a replica pinned at a
        pre-drop version still sees consistent doc+data, and one that
        refreshes past the tombstones reloads the rewritten docs
        (its schema cache clears on every vid advance)."""
        import json as _json

        from risingwave_tpu.serve.reader import (
            mv_key_range,
            schema_key,
        )
        from risingwave_tpu.storage.hummock.object_store import (
            ObjectError,
        )

        name = stmt.name
        with self._lock:
            jname = self._mv_to_job.get(name)
        if jname is None:
            if stmt.if_exists:
                return
            raise ValueError(f"{name!r} does not exist")
        if stmt.kind == "index" and name not in self._indexes:
            raise ValueError(f"{name!r} is not an index")
        deps = sorted(ix for ix, mv in self._indexes.items()
                      if mv == name)
        if deps:
            raise ValueError(
                f"cannot drop {name!r}: indexes {deps} depend on it "
                "(DROP INDEX first)"
            )
        if not replay:
            self.store.append_ddl(text)
        with self._tick_lock:
            with self._lock:
                job = self.jobs[jname]
                w = self.workers.get(job.worker_id) \
                    if job.worker_id is not None else None
            job.ddl.append(text)
            if not replay and w is not None and w.alive:
                self.retry.run(
                    lambda: w.client.call("execute", sql=text),
                    label="drop",
                )
            with self._lock:
                if name in job.mvs:
                    job.mvs.remove(name)
                self._mv_to_job.pop(name, None)
                upstream_of = self._indexes.pop(name, None)
                if not job.mvs:
                    # last MV gone: the job leaves the round protocol
                    self.jobs.pop(jname, None)
                    self._pending_ssts.pop(jname, None)
                    if w is not None:
                        w.jobs.discard(jname)
                self._set_worker_gauges()
            if replay:
                return  # storage already holds the tombstones
            if upstream_of is not None:
                # the upstream's doc must stop advertising the index
                # BEFORE its rows are tombstoned (a replica reloading
                # the doc post-tombstone must not plan through it)
                try:
                    doc = _json.loads(
                        self.hummock.store.get(schema_key(upstream_of))
                    )
                    doc["indexes"] = [
                        e for e in doc.get("indexes", [])
                        if e.get("name") != name
                    ]
                    if not doc["indexes"]:
                        doc.pop("indexes")
                    self.hummock.store.put(
                        schema_key(upstream_of),
                        _json.dumps(doc).encode(),
                    )
                except ObjectError:
                    pass  # upstream never exported
            try:
                self.hummock.store.delete(schema_key(name))
            except ObjectError:
                pass  # never exported
            lo, hi = mv_key_range(name)
            keys = [k for k, _ in self.hummock.scan(lo, hi)]
            if keys:
                self.hummock.delete_batch(
                    keys, epoch=self.versions.max_committed_epoch
                )
            self.metrics.inc("cluster_mv_drops_total")

    def _forward_dml(self, text: str, table: str) -> None:
        """INSERTs fan out to every worker whose catalog has the table
        (each job's private reader consumes its worker-local history —
        the same per-job readers a single node plans).  A table a
        PARTITIONED job reads routes to its ingest LEADER instead —
        the leader fans the position-stamped batch out worker↔worker,
        so the meta stays one control hop, never the data path."""
        self.metrics.inc("cluster_dml_forward_total")
        leader = self._table_leader(table)
        if leader is not None:
            with self._lock:
                w = self.workers.get(leader)
            if w is None or not w.alive:
                raise ValueError(
                    f"INSERT into {table!r}: ingest leader "
                    f"{leader} is not live"
                )
            w.client.call("execute", sql=text)
            self.store.append_dml_sql(text)
            return
        delivered = 0
        for w in self.live_workers():
            try:
                w.client.call("execute", sql=text)
                delivered += 1
            except RpcError as e:
                # a worker without the table answers KeyError("relation
                # ... does not exist") — that worker just isn't a host
                if "does not exist" in str(e):
                    continue
                raise
            except (ConnectionError, OSError):
                continue  # heartbeat monitor will expire it
        if delivered == 0:
            raise ValueError(
                f"INSERT into {table!r}: no live worker has the table "
                "(create it and place a job first)"
            )
        # durable only once at least one host accepted it (rejected
        # statements must not resurrect at replay)
        self.store.append_dml_sql(text)

    def _assign_pending(self) -> None:
        """Place pending barrier units: unassigned vnode PARTITIONS
        re-adopt their checkpoint lineage on a free worker (failover /
        meta restart — state AND vnode ownership follow the lineage),
        fresh jobs take partitioned placement over the vnode map when
        the scale plane is on and the plan is eligible, and everything
        else lands whole on the least-loaded live worker.  ONE
        assigner at a time: concurrent assigners (monitor + DDL path)
        would interleave adopt probes and desynchronize worker-side
        checkpoint lineages from the registry."""
        with self._assign_lock:
            self._assign_pending_locked()

    def _assign_pending_locked(self) -> None:
        while True:
            with self._lock:
                live = [w for w in self.workers.values() if w.alive]
                part_pending = [
                    (j, p) for j in self.jobs.values() if j.partitions
                    for p in j.partitions.values()
                    if p.worker_id is None and not p.retiring
                ]
                job_pending = [j for j in self.jobs.values()
                               if j.partitions is None
                               and j.worker_id is None]
                if not live or not (part_pending or job_pending):
                    return
            if part_pending:
                res = self._assign_partition(*part_pending[0])
                if res == "no_host":
                    # no spare worker can host the dead partition's
                    # lineage: merge its vnodes into a survivor via
                    # the scale-in slice-transplant path instead of
                    # stalling the round forever
                    if self._merge_dead_partition(*part_pending[0]):
                        continue
                    return
                if not res:
                    return
                continue
            job = job_pending[0]
            if self.scale_partitioning:
                placed = self._try_partition_place(job)
                if placed:
                    continue
                with self._lock:
                    if job.worker_id is not None or job.partitions:
                        continue
            with self._lock:
                live = [w for w in self.workers.values() if w.alive]
                if not live:
                    return
                # capacity follows the ACTIVE set once a map was cut
                if self.active_workers:
                    active = [w for w in live
                              if w.worker_id in self.active_workers]
                    live = active or live
                target = min(live,
                             key=lambda w: (len(w.jobs), w.worker_id))
            try:
                # adopt is idempotent (already-present DDL is skipped,
                # recovery rewinds to the same durable epoch) — safe to
                # retry through transient drops
                res = self.retry.run(
                    lambda: target.client.call(
                        "adopt", ddl=job.ddl, name=job.name,
                        recover=True,
                    ),
                    label="adopt",
                )
            except (RpcError, ConnectionError, OSError):
                # adoption failed: leave unassigned; the monitor loop
                # retries (and may expire the worker first)
                return
            recovered = int(res.get("committed_epoch", 0))
            with self._lock:
                if job.worker_id is not None:
                    continue  # raced with another assigner
                job.worker_id = target.worker_id
                target.jobs.add(job.name)
                self._rewind_job(job, recovered)

    def _assign_partition(self, job: JobInfo,
                          p: "PartitionInfo") -> bool:
        """Re-adopt one unassigned partition's LINEAGE on a live
        worker not already hosting this job: the worker recovers the
        partition's state + cursors from the shared checkpoint store
        and the vnode map re-points — failover is lineage migration,
        no state is recomputed."""
        with self._lock:
            taken = {q.worker_id for q in job.partitions.values()
                     if q.worker_id is not None}
            cands = [w for w in self.workers.values()
                     if w.alive and w.worker_id not in taken]
            if not cands:
                return "no_host"  # every live worker already hosts one
            target = min(cands, key=lambda w: (len(w.jobs),
                                               w.worker_id))
        try:
            res = self.retry.run(
                lambda: target.client.call(
                    "adopt", ddl=job.ddl, name=job.name,
                    recover=True, vnodes=sorted(p.vnodes),
                    n_vnodes=self.n_vnodes, ckpt_key=p.lineage,
                ),
                label="adopt",
            )
        except (RpcError, ConnectionError, OSError):
            return False
        if not res.get("partitioned"):
            return False  # deterministic plans: should not happen
        with self._lock:
            if p.worker_id is not None:
                return True  # raced
            p.worker_id = target.worker_id
            target.jobs.add(job.name)
            if self.vnode_map is not None:
                for v in p.vnodes:
                    self.vnode_map[v] = target.worker_id
            if res.get("dml_tables"):
                job.dml_tables = list(res["dml_tables"])
            if res.get("shuffle_cols"):
                job.shuffle_cols = {
                    t: (int(c) if c is not None else None)
                    for t, c in res["shuffle_cols"].items()
                }
            if res.get("edge_kinds"):
                job.edge_kinds = dict(res["edge_kinds"])
            self._rewind_job(p, int(res.get("committed_epoch", 0)))
        self._push_routing()
        self._set_vnode_gauges()
        return True

    def _merge_dead_partition(self, job: JobInfo,
                              p: "PartitionInfo") -> bool:
        """Merge-failover (the ROADMAP remaining item): a partitioned
        job's worker died and NO spare worker can host its lineage —
        instead of stalling the round forever, merge the dead
        partition's vnodes into a surviving partition through the
        scale-in slice-transplant path: the recipient rewinds to its
        own checkpoint at the last COMMITTED round, transplants the
        dead lineage's slice at that same round (all partitions sealed
        it durably — the commit required the acks), and widens its
        mask.  Capacity shrinks; correctness doesn't."""
        # non-blocking tick-lock acquire: a scale op mid-flight calls
        # _assign_pending with the lock held — defer to the monitor's
        # next pass rather than deadlocking
        if not self._tick_lock.acquire(blocking=False):
            return False
        try:
            round_c = self.cluster_epoch
            if round_c <= 0:
                return False
            with self._lock:
                epoch_p = next((e for r, e in reversed(p.seal_log)
                                if r == round_c), None)
                cands = [
                    q for q in job.partitions.values()
                    if q is not p and not q.retiring
                    and q.worker_id is not None
                    and (w := self.workers.get(q.worker_id)) is not None
                    and w.alive
                ]
                if not cands:
                    return False
                q = min(cands, key=lambda x: (len(x.vnodes), x.lineage))
                epoch_q = next((e for r, e in reversed(q.seal_log)
                                if r == round_c), None)
                w = self.workers[q.worker_id]
            if epoch_q is None or (p.vnodes and epoch_p is None):
                return False
            merged = sorted(set(q.vnodes) | set(p.vnodes))
            transfers = [{"ckpt": p.lineage, "epoch": epoch_p,
                          "vnodes": sorted(p.vnodes)}] if p.vnodes \
                else []
            try:
                self.retry.run(
                    lambda: w.client.call(
                        "repartition", job=job.name, vnodes=merged,
                        transfers=transfers, rewind_epoch=epoch_q,
                    ),
                    label="repartition",
                )
            except (RpcError, ConnectionError, OSError):
                return False
            with self._lock:
                q.vnodes = merged
                # the recipient rewound to the committed round: drop
                # any later (uncommitted) seal so the next round
                # re-seals against the merged state
                q.seal_log = [(r, e) for r, e in q.seal_log
                              if r <= round_c]
                q.rounds = round_c
                q.durable_epoch = epoch_q
                job.partitions.pop(p.lineage, None)
                if self.vnode_map is not None:
                    for v in p.vnodes:
                        self.vnode_map[v] = q.worker_id
                    self.active_workers = sorted(set(self.vnode_map))
                self.metrics.inc("cluster_merge_failovers_total")
            self._log_scale_event()
            self._push_routing()
            self._set_vnode_gauges()
            return True
        finally:
            self._tick_lock.release()

    def _try_partition_place(self, job: JobInfo) -> bool:
        """Fresh partitioned placement: adopt one partition per vnode
        map owner.  The FIRST owner probes plan eligibility — a
        refusal falls back to whole-job placement on that worker (the
        job is already adopted there)."""
        from risingwave_tpu.cluster.scale.vnode import (
            initial_map,
            owned_vnodes,
        )

        with self._lock:
            if job.partitions is not None or job.worker_id is not None:
                return True  # raced with another assigner
            live = {w.worker_id: w for w in self.workers.values()
                    if w.alive}
            if not live:
                return False
            if self.vnode_map is None:
                self.active_workers = sorted(live)
                self.vnode_map = initial_map(self.active_workers,
                                             self.n_vnodes)
            owners = sorted(set(self.vnode_map))
            if any(o not in live for o in owners):
                return False  # owner mid-failover: retry later
            vmap = list(self.vnode_map)
        placements = []
        for wid in owners:
            with self._lock:
                lineage = f"{job.name}::p{self._next_lineage}"
                self._next_lineage += 1
            placements.append((wid, lineage, owned_vnodes(vmap, wid)))
        first_wid, first_lineage, first_vns = placements[0]
        first_w = live[first_wid]
        try:
            res = self.retry.run(
                lambda: first_w.client.call(
                    "adopt", ddl=job.ddl, name=job.name,
                    recover=False, vnodes=first_vns,
                    n_vnodes=self.n_vnodes, ckpt_key=first_lineage,
                ),
                label="adopt",
            )
        except (RpcError, ConnectionError, OSError):
            return False
        if not res.get("partitioned"):
            # plan not scale-eligible: the probe adoption IS a valid
            # whole-job placement — keep it
            with self._lock:
                job.worker_id = first_wid
                first_w.jobs.add(job.name)
            return True
        with self._lock:
            if job.partitions is not None:
                return True  # raced: the other assigner's layout wins
            job.partitions = {
                first_lineage: PartitionInfo(
                    lineage=first_lineage, worker_id=first_wid,
                    vnodes=list(first_vns), rounds=self.cluster_epoch,
                )
            }
            job.dml_tables = list(res.get("dml_tables") or [])
            job.shuffle_cols = {
                t: (int(c) if c is not None else None)
                for t, c in (res.get("shuffle_cols") or {}).items()
            }
            job.edge_kinds = dict(res.get("edge_kinds") or {})
            first_w.jobs.add(job.name)
        for wid, lineage, vns in placements[1:]:
            w = live[wid]
            with self._lock:
                job.partitions[lineage] = PartitionInfo(
                    lineage=lineage, worker_id=None,
                    vnodes=list(vns), rounds=self.cluster_epoch,
                )
            try:
                self.retry.run(
                    lambda w=w, vns=vns, lineage=lineage:
                    w.client.call(
                        "adopt", ddl=job.ddl, name=job.name,
                        recover=False, vnodes=vns,
                        n_vnodes=self.n_vnodes, ckpt_key=lineage,
                    ),
                    label="adopt",
                )
            except (RpcError, ConnectionError, OSError):
                continue  # stays unassigned; _assign_pending retries
            with self._lock:
                job.partitions[lineage].worker_id = wid
                job.partitions[lineage].rounds = self.cluster_epoch
                w.jobs.add(job.name)
        self._log_scale_event()
        self._push_routing()
        self._set_vnode_gauges()
        return True

    def _rewind_job(self, job: JobInfo, epoch: int) -> None:
        """Translate a recovered committed epoch back into the round
        the job actually reached (its checkpoint may include a round
        meta never saw acknowledged)."""
        # the recovered epoch IS durable (adoption loads the manifest)
        job.durable_epoch = max(epoch, 0)
        epochs = [e for _, e in job.seal_log]
        if epoch <= 0:
            # no durable checkpoint: the job replays every round it
            # was credited with (fresh state, sources at zero)
            if job.seal_log:
                job.rounds = job.seal_log[0][0] - 1
            else:
                job.rounds = min(job.rounds, self.cluster_epoch)
            job.seal_log = []
            return
        i = bisect.bisect_right(epochs, epoch)
        if i > 0 and epochs[i - 1] == epoch:
            job.seal_log = job.seal_log[:i]
            job.rounds = job.seal_log[-1][0]
        elif i == len(epochs):
            # sealed + checkpointed, died before acking: credit the
            # in-flight round
            round_ = (job.seal_log[-1][0] + 1) if job.seal_log \
                else job.rounds + 1
            job.seal_log.append((round_, epoch))
            job.rounds = round_
        else:
            # an epoch meta never recorded, older than later seals —
            # cannot happen with meta-serialized rounds; resync hard
            job.seal_log = job.seal_log[:i]
            job.rounds = job.seal_log[-1][0] if job.seal_log else 0

    # -- the elastic scale plane ------------------------------------------
    def rpc_cluster_scale(self, n: int) -> dict:
        return self.scale(int(n))

    def scale(self, n: int) -> dict:
        """``ctl cluster scale N``: resize the ACTIVE worker set to the
        N lowest-id live workers and rebalance the vnode map minimally
        (only moved vnodes — and the state behind them — transfer).

        Protocol, under the tick lock (no rounds in flight):

        1. drive one COMMITTED round — every partition is sealed AND
           durable at the handover epoch, and since nothing runs
           between that commit and the handover, live state == the
           checkpoint at that epoch everywhere;
        2. compute the new map (``scale.vnode.rebalance``: ±1
           balanced, minimal movement, deterministic);
        3. per partitioned job: recipients transplant each donor's
           checkpoint SLICE (only moved vnodes leave disk), donors
           narrow their gate mask, empty donors are released;
        4. durably log the scale event, re-push peer routing;
        5. drive one more committed round so serving pins (and their
           pinned vnode sets) move past the handover — reads stay
           zero-error throughout.

        Retry-safe: a failed handover leaves the map uncut; re-running
        ``scale`` re-applies the same transfers against the same
        checkpoints."""
        with self._tick_lock:
            return self._scale_locked(int(n))

    def _scale_locked(self, n: int) -> dict:
        from risingwave_tpu.cluster.scale.vnode import (
            initial_map,
            moved_vnodes,
            rebalance,
        )

        with self._lock:
            live = sorted(w.worker_id for w in self.workers.values()
                          if w.alive)
        if n < 1 or n > len(live):
            raise ValueError(
                f"scale {n}: cluster has {len(live)} live workers "
                "(register more first)"
            )
        active = live[:n]
        if self.vnode_map is None:
            # first scale cuts the initial map; jobs placed afterwards
            # partition over it
            self.scale_partitioning = True
            self.vnode_map = initial_map(active, self.n_vnodes)
            self.active_workers = active
            self._log_scale_event()
            self._push_routing()
            self._set_vnode_gauges()
            return {"active": active, "moved_vnodes": 0,
                    "map_initialized": True}
        # 1. the handover anchor round
        self._drive_committed_round()
        handover_round = self.cluster_epoch
        old_map = list(self.vnode_map)
        new_map = rebalance(old_map, active, self.n_vnodes)
        moved = moved_vnodes(old_map, new_map)
        transfers = []
        with self._lock:
            part_jobs = [j for j in self.jobs.values() if j.partitions]
        for job in part_jobs:
            transfers += self._handover_job(job, new_map, moved,
                                            handover_round)
        self.vnode_map = new_map
        self.active_workers = active
        self.scale_ops += 1
        moved_count = sum(len(v) for v in moved.values())
        self.metrics.inc("cluster_scale_ops_total")
        self.metrics.inc("cluster_scale_moved_vnodes_total",
                         moved_count)
        self._log_scale_event()
        self._push_routing()
        self._set_vnode_gauges()
        # 2. move whole (non-partitioned) jobs off inactive workers
        self._evacuate_inactive(set(active))
        # 3. serving pins move past the handover
        post = self._drive_committed_round()
        return {
            "active": active,
            "handover_round": handover_round,
            "committed_round": post["cluster_epoch"],
            "moved_vnodes": moved_count,
            "moved": {f"{s}>{d}": len(v)
                      for (s, d), v in moved.items()},
            "transfers": transfers,
        }

    def _drive_committed_round(self, timeout_s: float = 120.0) -> dict:
        deadline = time.monotonic() + timeout_s
        while True:
            res = self._tick_locked(1)
            if res["committed"] or res.get("units", res["jobs"]) == 0:
                return res
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"scale: round {res['round']} never committed "
                    f"({res['sealed']}/{res.get('units')} sealed)"
                )
            time.sleep(0.05)

    def _handover_job(self, job: JobInfo, new_map: list[int],
                      moved: dict, handover_round: int) -> list[dict]:
        """Apply one scale step to a partitioned job: transplant moved
        slices into recipients (existing partitions merge in place;
        fresh workers adopt a NEW lineage built purely from
        transfers), then narrow/release donors."""
        from risingwave_tpu.cluster.scale.vnode import owned_vnodes

        with self._lock:
            by_worker = {p.worker_id: p
                         for p in job.partitions.values()
                         if not p.retiring}
            seal_at = {}
            for p in by_worker.values():
                if not p.seal_log \
                        or p.seal_log[-1][0] != handover_round:
                    raise RuntimeError(
                        f"scale: partition {p.lineage} not sealed at "
                        f"round {handover_round}"
                    )
                seal_at[p.worker_id] = p.seal_log[-1][1]
        gains: dict[int, list] = {}
        for (src, dst), vns in moved.items():
            if src not in by_worker:
                continue  # vnode owned by a worker without this job
            gains.setdefault(dst, []).append((src, vns))
        stats = []
        for dst, srcs in gains.items():
            new_set = owned_vnodes(new_map, dst)
            xfers = [{"ckpt": by_worker[src].lineage,
                      "epoch": seal_at[src], "vnodes": vns}
                     for src, vns in srcs]
            with self._lock:
                w = self.workers.get(dst)
            if w is None or not w.alive:
                raise RuntimeError(f"scale: recipient {dst} is dead")
            p = by_worker.get(dst)
            if p is None:
                with self._lock:
                    lineage = f"{job.name}::p{self._next_lineage}"
                    self._next_lineage += 1
                self.retry.run(
                    lambda: w.client.call(
                        "adopt", ddl=job.ddl, name=job.name,
                        recover=False, vnodes=[],
                        n_vnodes=self.n_vnodes, ckpt_key=lineage,
                    ),
                    label="adopt",
                )
                p = PartitionInfo(lineage=lineage, worker_id=dst,
                                  rounds=handover_round)
                with self._lock:
                    job.partitions[lineage] = p
                    w.jobs.add(job.name)
            res = self.retry.run(
                lambda: w.client.call(
                    "repartition", job=job.name, vnodes=new_set,
                    transfers=xfers,
                ),
                label="repartition",
            )
            with self._lock:
                p.vnodes = list(new_set)
            stats.append({"job": job.name, "worker": dst,
                          "gained": sum(len(v) for _, v in srcs),
                          "entries": sum(t["entries"]
                                         for t in res["transfers"]),
                          "transfers": res["transfers"]})
        # donors narrow (or RETIRE: keep serving pre-handover pins
        # until the post-handover commit publishes the new serve plan)
        donor_ids = {src for (src, _dst) in moved if src in by_worker}
        for src in sorted(donor_ids):
            p = by_worker[src]
            new_set = owned_vnodes(new_map, src)
            with self._lock:
                w = self.workers.get(src)
            if not new_set:
                with self._lock:
                    p.retiring = True
                continue
            if w is None or not w.alive:
                raise RuntimeError(f"scale: donor {src} is dead")
            self.retry.run(
                lambda: w.client.call(
                    "repartition", job=job.name, vnodes=new_set,
                    transfers=[],
                ),
                label="repartition",
            )
            with self._lock:
                p.vnodes = list(new_set)
        return stats

    def _evacuate_inactive(self, active: set[int]) -> None:
        """Whole-job placements follow capacity too: jobs on workers
        outside the active set go back to pending and re-adopt (from
        their durable checkpoint) on an active worker."""
        with self._lock:
            for job in self.jobs.values():
                if job.partitions is not None \
                        or job.worker_id is None \
                        or job.worker_id in active:
                    continue
                w = self.workers.get(job.worker_id)
                if w is not None:
                    w.jobs.discard(job.name)
                job.worker_id = None
        self._assign_pending()

    def _log_scale_event(self) -> None:
        """Durably record the scale plane's layout (map + partition
        lineages) — a restarted meta replays the tail event and
        re-adopts every lineage (see ``_recover_from_store``)."""
        with self._lock:
            ev = {
                "round": self.cluster_epoch,
                "n_vnodes": self.n_vnodes,
                "map": list(self.vnode_map or []),
                "active": list(self.active_workers),
                "next_lineage": self._next_lineage,
                "partitions": {
                    j.name: [{"lineage": p.lineage,
                              "vnodes": list(p.vnodes)}
                             for p in j.partitions.values()
                             if not p.retiring]
                    for j in self.jobs.values() if j.partitions
                },
                "dml_tables": {
                    j.name: list(j.dml_tables)
                    for j in self.jobs.values() if j.partitions
                },
                "shuffle_cols": {
                    j.name: dict(j.shuffle_cols)
                    for j in self.jobs.values() if j.partitions
                },
                "edge_kinds": {
                    j.name: dict(j.edge_kinds)
                    for j in self.jobs.values() if j.partitions
                },
                "attach_edges": {
                    j.name: [list(e) for e in j.attach_edges]
                    for j in self.jobs.values() if j.partitions
                },
            }
        self.store.append_scale_event(ev)

    def _push_routing(self) -> None:
        """Push the placement choreography to every live worker: peer
        addresses, per-replicated-table hosts + ingest leader, AND the
        compiled Exchange-lite choreography (per-table shuffle key,
        vnode slices, standby, edge specs).  The per-chunk exchange
        then flows worker↔worker — the meta's only involvement with
        the data path is this control push (compile once, execute
        forever: the Suki discipline)."""
        from risingwave_tpu.cluster.exchange import ExchangePlanner

        with self._lock:
            self._routing_version += 1
            version = self._routing_version
            peers = {w.worker_id: [w.host, w.port]
                     for w in self.workers.values() if w.alive}
            tables: dict[str, dict] = {}
            plan_jobs: list[dict] = []
            for j in self.jobs.values():
                if not j.partitions:
                    continue
                hosts = sorted({p.worker_id
                                for p in j.partitions.values()
                                if p.worker_id is not None})
                if not hosts:
                    continue
                for t in j.dml_tables:
                    cur = tables.setdefault(
                        t, {"leader": hosts[0], "hosts": []}
                    )
                    cur["hosts"] = sorted(set(cur["hosts"]) | set(hosts))
                    cur["leader"] = min(cur["hosts"])
                owners: dict[int, list] = {}
                for p in j.partitions.values():
                    if p.worker_id is not None and not p.retiring:
                        owners.setdefault(p.worker_id, [])
                        owners[p.worker_id] = sorted(
                            set(owners[p.worker_id]) | set(p.vnodes)
                        )
                plan_jobs.append({
                    "name": j.name,
                    "dml_tables": list(j.dml_tables),
                    "shuffle_cols": dict(j.shuffle_cols)
                    if self.shuffle_ingest else {},
                    "kinds": dict(j.edge_kinds),
                    "attach_edges": list(j.attach_edges),
                    "owners": owners,
                })
            targets = [w for w in self.workers.values() if w.alive]
        choreo = ExchangePlanner.compile(
            plan_jobs, self.n_vnodes, version=version
        ).to_doc()
        self._choreography = choreo
        for w in targets:
            try:
                w.client.call("update_routing", version=version,
                              peers=peers, tables=tables,
                              exchange=choreo)
            except (RpcError, ConnectionError, OSError):
                pass  # it pulls fresh routing at re-registration

    def _set_vnode_gauges(self) -> None:
        with self._lock:
            vmap = self.vnode_map or []
            counts: dict[int, int] = {}
            for wid in vmap:
                counts[wid] = counts.get(wid, 0) + 1
            for w in self.workers.values():
                if w.alive:
                    self.metrics.set_gauge(
                        "cluster_worker_vnodes",
                        counts.get(w.worker_id, 0),
                        worker=str(w.worker_id),
                    )

    # -- the global checkpoint protocol ---------------------------------
    def rpc_tick(self, chunks_per_barrier: int = 1) -> dict:
        return self.tick(chunks_per_barrier)

    def _barrier_units(self, jobs: list[JobInfo]):
        """The round's barrier units: (job, unit) pairs where ``unit``
        is the JobInfo itself (whole-job placement) or each of its
        vnode partitions — both carry the same round-protocol fields,
        so the seal/durable/commit path below drives either."""
        units = []
        for job in jobs:
            if job.partitions:
                units += [(job, p) for p in job.partitions.values()
                          if not p.retiring]
            else:
                units.append((job, job))
        return units

    def _round_fences(self, jobs: list[JobInfo]) -> dict:
        """Per-table consumption fences for this round: the ingest
        leader's current history position.  Every partition of a job
        consumes the IDENTICAL prefix up to the fence, so source
        cursors stay aligned across workers (what makes
        checkpoint-slice handover exact).  One control RPC per
        replicated table per round — the per-chunk data path stays
        worker↔worker."""
        fences: dict[str, int] = {}
        for job in jobs:
            if not job.partitions:
                continue
            for t in job.dml_tables:
                if t in fences:
                    continue
                cached = self._fence_cache.get(t)
                if cached is not None:
                    fences[t] = cached
                    continue
                leader = self._table_leader(t)
                w = self.workers.get(leader) \
                    if leader is not None else None
                if w is None or not w.alive:
                    continue
                try:
                    res = self.retry.run(
                        lambda: w.client.call("table_len", table=t),
                        label="table_len",
                    )
                    fences[t] = int(res["len"])
                except (RpcError, ConnectionError, OSError):
                    continue  # round stalls for this job's partitions
        return fences

    def _table_leader(self, table: str) -> int | None:
        with self._lock:
            hosts = sorted({
                p.worker_id
                for j in self.jobs.values() if j.partitions
                and table in j.dml_tables
                for p in j.partitions.values()
                if p.worker_id is not None
            })
        return hosts[0] if hosts else None

    def tick(self, chunks_per_barrier: int = 1) -> dict:
        with self._tick_lock:
            res = self._tick_locked(chunks_per_barrier)
        # corrupt SSTs surfaced by worker export seams during the
        # round repair OUTSIDE the tick lock (repair re-enters it)
        self._drain_corrupt_reports()
        return res

    def _tick_locked(self, chunks_per_barrier: int = 1) -> dict:
        """Drive ONE global barrier round: every barrier unit (job or
        vnode partition) SEALS round ``cluster_epoch + 1`` (the
        barrier RPC returns as soon as the epoch is sealed — its
        checkpoint upload runs in the worker's background uploader);
        the cluster epoch commits through the versioned manifest only
        when every unit's upload has ACKED the sealed epoch.
        Incomplete rounds (dead/unassigned workers, uploads still in
        flight) commit nothing — the cluster epoch never moves past a
        hole, and survivors run at most one round ahead."""
        t0 = time.perf_counter()
        target = self.cluster_epoch + 1
        with self._lock:
            jobs = list(self.jobs.values())
        units = self._barrier_units(jobs)
        if not units:
            return {"round": target, "committed": False,
                    "jobs": 0, "sealed": 0}
        self.metrics.set_gauge("cluster_epoch_in_flight", target)
        # trace-lite: ONE root span per round trace, however many tick
        # attempts the round takes — an attempt that didn't commit
        # leaves ``_trace_root_ctx`` in place, and the retry parents a
        # child "attempt" span under the ORIGINAL root instead of
        # opening a second root (tree_check requires exactly one)
        if self._trace_round != target or self._trace_root_ctx is None:
            self._trace_round = target
            tick_span = GLOBAL_TRACE.span(
                "round", trace_id=f"round-{target}",
                epoch=target, units=len(units),
            )
            self._trace_root_ctx = tick_span.ctx
        else:
            tick_span = GLOBAL_TRACE.span(
                "attempt", ctx=self._trace_root_ctx, epoch=target,
            )
        with tick_span as rspan:
            res = self._tick_attempt(
                target, jobs, units, chunks_per_barrier, t0,
                rspan.ctx,
            )
            rspan.set(committed=res["committed"],
                      sealed=res["sealed"])
        if res["committed"]:
            # serving lease grants piggyback this ctx so sampled
            # replica reads join the round tree they actually read
            self._last_round_ctx = self._trace_root_ctx
        self._export_fault_gauges()
        return res

    def _tick_attempt(self, target: int, jobs, units,
                      chunks_per_barrier: int, t0: float,
                      rctx: "tuple | None") -> dict:
        """One tick attempt at round ``target`` (the body of
        ``_tick_locked``, running under that round's trace span —
        ``rctx`` is passed EXPLICITLY into the per-worker fan-out
        threads, whose thread-local trace stacks are empty)."""
        # consumption fences are PER ROUND: a retried round (worker
        # failure mid-round) reuses the fence its survivors already
        # sealed with, so a re-adopted partition consumes the same
        # prefix and cursors stay aligned
        if self._fence_round != target:
            self._fence_round = target
            self._fence_cache = {}
        fences = self._round_fences(jobs)
        self._fence_cache.update(fences)
        sealed = 0
        by_worker: dict[int, list] = {}
        for job, unit in units:
            if unit.rounds >= target:
                sealed += 1
                continue
            with self._lock:
                w = self.workers.get(unit.worker_id) \
                    if unit.worker_id is not None else None
            if w is None or not w.alive:
                continue
            limits = {t: fences[t] for t in job.dml_tables
                      if t in fences} if job.partitions else None
            if job.partitions and job.dml_tables and not limits:
                continue  # fence unavailable: stall, never diverge
            by_worker.setdefault(w.worker_id, []).append(
                (job, unit, w, limits)
            )

        def _barrier_one(job, unit, w, limits) -> bool:
            try:
                # round-tagged: the worker caches each job's last
                # (round, seal) and answers a replay from the
                # cache, so retrying after a lost RESPONSE cannot
                # run the round twice (epoch-guarded idempotence)
                with GLOBAL_TRACE.span("barrier", ctx=rctx,
                                       job=job.name, unit=unit.name,
                                       worker=w.worker_id):
                    res = self.retry.run(
                        lambda: w.client.call(
                            "barrier", job=job.name,
                            chunks=int(chunks_per_barrier),
                            round=target, limits=limits,
                        ),
                        label="barrier",
                    )
            except (RpcError, ConnectionError, OSError):
                return False  # monitor expires the worker; stall
            epoch = int(res.get("sealed_epoch",
                                res["committed_epoch"]))
            ssts = res.get("ssts") or []
            if res.get("corrupt"):
                with self._lock:
                    self._corrupt_reports.extend(res["corrupt"])
            self._mirror_exchange_gauges(w.worker_id,
                                         res.get("exchange"))
            with self._lock:
                unit.rounds = target
                unit.seal_log.append((target, epoch))
                unit.durable_epoch = int(
                    res.get("durable_epoch", epoch)
                )
                # a failover re-seal replaces the dead attempt's
                # pending export (same round, recomputed bytes)
                for s in self._pending_ssts.pop((unit.name, target),
                                                []):
                    self.hummock.release_external_sst_key(s["key"])
                if ssts:
                    self._pending_ssts[(unit.name, target)] = ssts
                    w.sst_keys.difference_update(
                        {s["key"] for s in ssts}
                    )
                for table, doc in (res.get("policies") or {}).items():
                    self._pending_policies[table] = doc
            return True

        # barrier RPCs fan out PER WORKER (units on one worker stay
        # serial — its engine lock serializes anyway; units on
        # DIFFERENT workers run their chunks concurrently).  This is
        # what lets a shuffled round's wall time track the SLOWEST
        # partition instead of the SUM of partitions — the other half
        # of "ingest throughput tracks worker count".  Checkpoint
        # uploads stay safe: each partition writes its own lineage
        # keys, export SSTs ride meta-allocated collision-free keys,
        # and this thread alone commits the manifest afterwards.
        groups = list(by_worker.values())
        if len(groups) == 1:
            sealed += sum(_barrier_one(*item) for item in groups[0])
        elif groups:
            results: list[int] = [0] * len(groups)

            def _run_group(gi: int, items) -> None:
                results[gi] = sum(_barrier_one(*item)
                                  for item in items)

            threads = [
                threading.Thread(target=_run_group, args=(gi, items),
                                 name=f"meta-barrier-w{gi}")
                for gi, items in enumerate(groups)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            sealed += sum(results)
        committed = sealed == len(units)
        if committed:
            with GLOBAL_TRACE.span("await_durable", epoch=target):
                committed = self._await_durable(units, target)
        if committed:
            with GLOBAL_TRACE.span("commit", epoch=target):
                self._commit_cluster_epoch(target, units)
            from risingwave_tpu.common.metrics import (
                WIDE_SECONDS_BUCKETS,
            )
            self.metrics.observe(
                "cluster_barrier_commit_seconds",
                time.perf_counter() - t0,
                buckets=WIDE_SECONDS_BUCKETS,
            )
        return {"round": target, "committed": committed,
                "jobs": len(jobs), "units": len(units),
                "sealed": sealed,
                "cluster_epoch": self.cluster_epoch}

    def _mirror_exchange_gauges(self, worker_id: int,
                                ex: "dict | None") -> None:
        """Mirror a worker's exchange counters as per-worker gauges
        (cheap piggyback on the barrier response).  Tracked so
        ``_remove_worker_series`` retires them with the worker —
        exactly the PR-7/PR-10 per-peer gauge discipline."""
        if not ex:
            return
        if not hasattr(self, "_exchange_series"):
            self._exchange_series = set()
        for k in ("rows_out", "rows_in", "batches_out",
                  "batches_in", "send_failures"):
            self.metrics.set_gauge(
                f"cluster_worker_exchange_{k}",
                int(ex.get(k, 0)), worker=str(worker_id),
            )
        self._exchange_series.add(worker_id)

    def _await_durable(self, units, target: int) -> bool:
        """The seal-vs-ack split: poll each sealed unit's worker until
        its durable (upload-acked) epoch reaches the round's seal, or
        the bounded wait expires (round retried by the next tick).
        Workers poll in PARALLEL (their uploads already run in
        parallel background threads) — the wait is bounded by the
        slowest worker, not the sum."""
        by_worker: dict = {}
        for job, unit in units:
            by_worker.setdefault(unit.worker_id, []).append(
                (job, unit)
            )
        if len(by_worker) <= 1:
            return self._await_durable_units(units, target)
        results: list[bool] = [False] * len(by_worker)
        groups = list(by_worker.values())

        def _run(gi: int, items) -> None:
            results[gi] = self._await_durable_units(items, target)

        threads = [
            threading.Thread(target=_run, args=(gi, items),
                             name=f"meta-durable-{gi}")
            for gi, items in enumerate(groups)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return all(results)

    def _await_durable_units(self, units, target: int) -> bool:
        deadline = time.monotonic() + self.durable_wait_s
        for job, unit in units:
            with self._lock:
                if not unit.seal_log:
                    return False
                want = unit.seal_log[-1][1]
                w = self.workers.get(unit.worker_id) \
                    if unit.worker_id is not None else None
            lag_gauge = lambda v: self.metrics.set_gauge(  # noqa: E731
                "cluster_job_durable_lag_epochs", v, job=unit.name,
            )
            if unit.durable_epoch >= want:
                lag_gauge(0)
                continue
            if w is None or not w.alive:
                return False
            while True:
                try:
                    # read-only poll: always retry-safe
                    res = self.retry.run(
                        lambda: w.client.call("job_epochs",
                                              job=job.name),
                        label="job_epochs",
                    )
                except (RpcError, ConnectionError, OSError):
                    return False
                with self._lock:
                    unit.durable_epoch = int(res.get("durable", 0))
                lag_gauge(max(0, want - unit.durable_epoch))
                self.metrics.set_gauge(
                    "cluster_job_upload_queue_depth",
                    int(res.get("upload_queue", 0)), job=unit.name,
                )
                if unit.durable_epoch >= want:
                    break
                if time.monotonic() > deadline:
                    return False
                time.sleep(0.02)
        return True

    def _commit_cluster_epoch(self, round_: int, units) -> None:
        """All units sealed ``round_``: ONE manifest delta records the
        global consistency point — carrying every MV export SST the
        round's seals uploaded (newest round first, so L0 reader order
        stays newest-first) — then serving pins move forward: a
        snapshot read after this sees every MV at the same round."""
        from risingwave_tpu.storage.hummock.version import SstInfo

        epoch_val = min(u.seal_log[-1][1] for _, u in units)
        with self._lock:
            due = sorted(
                [k for k in self._pending_ssts if k[1] <= round_],
                key=lambda k: -k[1],
            )
            adds = [
                SstInfo(
                    key=s["key"],
                    first_key=bytes.fromhex(s["first_key"]),
                    last_key=bytes.fromhex(s["last_key"]),
                    n_records=int(s["n_records"]),
                    size=int(s["size"]),
                )
                for k in due for s in self._pending_ssts[k]
            ]
            for k in due:
                del self._pending_ssts[k]
            policies = self._pending_policies
            self._pending_policies = {}
        self.hummock.commit_external(epoch_val, adds,
                                     policies=policies or None)
        # durable round record AFTER the manifest commit: a crash in
        # between re-commits the round idempotently at restart (empty
        # delta, same epoch stamp) — never a lost or double round
        self.store.append_cluster_commit(
            round_, epoch_val,
            {u.name: u.seal_log[-1][1] for _, u in units},
        )
        retired: list[tuple[int, str]] = []
        with self._lock:
            self.cluster_epoch = round_
            plans: dict[str, list] = {}
            for job, u in units:
                job.rounds = round_
                u.pinned_epoch = u.seal_log[-1][1]
                if u is not job:
                    # reads pinned at this round route with the vnode
                    # set of this round — consistent through handover
                    u.pinned_vnodes = list(u.vnodes)
                    plans.setdefault(job.name, []).append(
                        (u.worker_id, u.pinned_epoch, list(u.vnodes))
                    )
                # seal_log only needs entries recovery can rewind to;
                # everything at/before the global commit is final
                if len(u.seal_log) > 64:
                    u.seal_log = u.seal_log[-64:]
            for job, _ in units:
                if job.name in plans:
                    # the ATOMIC routing switch: fan-out reads now see
                    # this round's owners/vnodes — never a mixed-round
                    # union; retiring donors are safe to drop
                    job.serve_plan = plans[job.name]
                    for p in [p for p in job.partitions.values()
                              if p.retiring]:
                        job.partitions.pop(p.lineage, None)
                        if p.worker_id is not None:
                            retired.append((p.worker_id, job.name))
                            w = self.workers.get(p.worker_id)
                            if w is not None:
                                w.jobs.discard(job.name)
        for wid, jname in retired:
            with self._lock:
                w = self.workers.get(wid)
            if w is not None and w.alive:
                try:
                    w.client.call("release", job=jname)
                except (RpcError, ConnectionError, OSError):
                    pass  # best-effort; the idle partition is inert
        self.metrics.set_gauge("cluster_epoch_committed", round_)
        self.metrics.set_gauge("cluster_manifest_epoch", epoch_val)

    # -- serving reads ---------------------------------------------------
    def rpc_serve(self, sql: str) -> dict:
        cols, rows = self.serve(sql)
        return {"cols": cols, "rows": rows}

    def serve(self, sql: str):
        """Route a serving read.  SELECTs go ROUND-ROBIN across live
        serving replicas (the stateless read tier over shared SSTs,
        pinned at the last cluster-committed manifest epoch); when no
        replica is registered, a replica refuses the statement shape
        (``ServeUnsupported``), or every replica is unreachable, the
        read falls back to the MV's OWNING worker pinned at the job's
        last cluster-committed epoch.  While the owner is dead/
        unassigned (failover in progress) the read WAITS for the
        reassignment instead of erroring — reads never observe partial
        state and never fail across a worker OR replica kill."""
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse

        stmts = parse(sql)
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Select):
            raise ValueError("cluster serving handles a single SELECT")
        sel = stmts[0]
        if not isinstance(sel.from_, ast.TableRef):
            raise ValueError(
                "cluster serving reads are SELECT ... FROM <mv>"
            )
        mv = sel.from_.name
        deadline = time.monotonic() + self.serve_retry_timeout_s
        try_replicas = True
        while True:
            with self._lock:
                jname = self._mv_to_job.get(mv)
                if jname is None:
                    raise ValueError(
                        f"{mv!r} does not exist (not a placed MV)"
                    )
                job = self.jobs[jname]
                parts = list(job.partitions.values()) \
                    if job.partitions else None
                w = self.workers.get(job.worker_id) \
                    if job.worker_id is not None else None
                pin = job.pinned_epoch
                manifest_pin = self.versions.max_committed_epoch
                replicas = [r for r in self.serving.values() if r.alive]
                self._serve_rr += 1
                start = self._serve_rr
            if parts is not None and _select_needs_engine_merge(sel):
                # per-partition results of an aggregate-shaped SELECT
                # cannot be unioned — a loud refusal, never a wrong row
                raise ValueError(
                    "aggregate serving reads over a partitioned MV: "
                    "create a materialized view for the aggregation"
                )
            if try_replicas and replicas:
                for i in range(len(replicas)):
                    r = replicas[(start + i) % len(replicas)]
                    try:
                        res = r.client.call("read", sql=sql,
                                            min_epoch=manifest_pin)
                        self.metrics.inc("cluster_serving_reads_total")
                        return res["cols"], [tuple(row)
                                             for row in res["rows"]]
                    except RpcError as e:
                        if "ServeUnsupported" in str(e):
                            # statement shape needs the engine — the
                            # owning worker serves it (and every retry
                            # of this read)
                            try_replicas = False
                            break
                        if "ServeUnavailable" in str(e):
                            # replica transiently stuck (lease refresh
                            # lost, behind the pin): route around it —
                            # next replica or the owner, never an error
                            continue
                        raise  # replica answered with a real failure
                    except (ConnectionError, OSError):
                        continue  # replica died mid-read: next one
            if parts is not None:
                # partitioned MV: fan out per the serve PLAN (the
                # atomically-published routing of the last commit — a
                # consistent single-round view through handovers) and
                # union the disjoint slices; any owner mid-failover ⇒
                # wait and retry the whole read (never a partial
                # answer)
                with self._lock:
                    plan = list(job.serve_plan) if job.serve_plan \
                        else [(p.worker_id, p.pinned_epoch,
                               list(p.pinned_vnodes)
                               or list(p.vnodes))
                              for p in job.partitions.values()
                              if not p.retiring]
                    owners = [
                        (self.workers.get(wid)
                         if wid is not None else None, pe, pv)
                        for wid, pe, pv in plan
                    ]
                if all(w2 is not None and w2.alive
                       for w2, _, _ in owners):
                    rows: list[tuple] = []
                    cols: list = []
                    complete = True
                    for w2, pe, pv in owners:
                        try:
                            res = w2.client.call(
                                "serve", sql=sql, query_epoch=pe,
                                vnodes=pv,
                            )
                        except RpcError as e:
                            if "does not exist" in str(e) \
                                    or "is not retained" in str(e):
                                # stale routing (released donor), or a
                                # checkpoint repair truncated the
                                # pinned epoch — both transient: the
                                # next commit republishes plan + pins.
                                # Retry, never a failed read
                                complete = False
                                break
                            raise  # the engine refused: final
                        except (ConnectionError, OSError):
                            complete = False
                            break
                        cols = res["cols"]
                        rows += [tuple(r) for r in res["rows"]]
                    if complete:
                        self.metrics.inc(
                            "cluster_partitioned_reads_total"
                        )
                        return cols, rows
            elif w is not None and w.alive:
                try:
                    res = w.client.call("serve", sql=sql,
                                        query_epoch=pin)
                    return res["cols"], [tuple(r) for r in res["rows"]]
                except RpcError as e:
                    if "is not retained" in str(e):
                        # a checkpoint repair truncated the pinned
                        # epoch: wait for the next commit to re-pin
                        pass
                    else:
                        raise  # the engine refused: final
                except (ConnectionError, OSError):
                    pass  # owner died mid-read: wait for reassignment
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no live owner for {mv!r} within "
                    f"{self.serve_retry_timeout_s}s"
                )
            time.sleep(0.05)

    def rpc_serve_batch(self, sqls: list) -> dict:
        return {"results": [
            {"cols": cols, "rows": [list(r) for r in rows]}
            for cols, rows in self.serve_batch(list(sqls))
        ]}

    def serve_batch(self, sqls: list) -> list:
        """Route N SELECTs through ONE replica RPC frame (the batched
        multi-get protocol).  Items the replica cannot serve
        (``unsupported``) fall back PER ITEM to the single-read router
        (owning worker); a final per-item error (unknown column/MV)
        raises like the single-read path would.  With no live replica
        every item takes the single-read router."""
        with self._lock:
            replicas = [r for r in self.serving.values() if r.alive]
            manifest_pin = self.versions.max_committed_epoch
            self._serve_rr += 1
            start = self._serve_rr
        for i in range(len(replicas)):
            r = replicas[(start + i) % len(replicas)]
            try:
                res = r.client.call("read_batch", sqls=sqls,
                                    min_epoch=manifest_pin)
            except RpcError as e:
                if "ServeUnavailable" in str(e):
                    continue  # replica stuck behind the pin: next one
                raise
            except (ConnectionError, OSError):
                continue  # replica died mid-batch: next one
            out = []
            for item, sql in zip(res["results"], sqls):
                if item.get("error") is not None:
                    raise ValueError(item["error"])
                if "unsupported" in item:
                    out.append(self.serve(sql))
                else:
                    out.append((item["cols"],
                                [tuple(row) for row in item["rows"]]))
            self.metrics.inc("cluster_serving_batch_reads_total",
                             len(sqls))
            return out
        return [self.serve(sql) for sql in sqls]

    def rpc_serve_multi_get(self, mv: str, pks: list,
                            cols: list | None = None) -> dict:
        names, rows = self.serve_multi_get(mv, pks, cols)
        return {"cols": names, "rows": [list(r) for r in rows]}

    def serve_multi_get(self, mv: str, pks: list,
                        cols: list | None = None):
        """First-class multi-get: one MV + N full pks in one frame.
        Routes to a replica (one sorted SstView pass); with none live
        it falls back to per-pk SELECTs against the single-read
        router, union sorted by encoded pk — the same row order the
        replica path answers.  Missing pks are omitted."""
        from risingwave_tpu.serve.reader import MvSchema, schema_key

        with self._lock:
            if mv not in self._mv_to_job:
                raise ValueError(
                    f"{mv!r} does not exist (not a placed MV)"
                )
            replicas = [r for r in self.serving.values() if r.alive]
            manifest_pin = self.versions.max_committed_epoch
            self._serve_rr += 1
            start = self._serve_rr
        for i in range(len(replicas)):
            r = replicas[(start + i) % len(replicas)]
            try:
                res = r.client.call("multi_get", mv=mv, pks=pks,
                                    cols=cols, min_epoch=manifest_pin)
                self.metrics.inc("cluster_serving_batch_reads_total",
                                 len(pks))
                return res["cols"], [tuple(row) for row in res["rows"]]
            except RpcError as e:
                if "ServeUnavailable" in str(e) \
                        or "ServeUnsupported" in str(e):
                    # stuck replica, or the MV's schema doc has not
                    # landed yet: fall through (next replica / owner)
                    continue
                raise
            except (ConnectionError, OSError):
                continue
        # owner fallback: per-pk SELECTs, union in encoded-pk order
        import json as _json

        try:
            schema = MvSchema(_json.loads(
                self.hummock.store.get(schema_key(mv))
            ))
        except Exception:  # noqa: BLE001 — never exported yet
            schema = None
        if schema is None:
            raise ValueError(
                f"multi_get on {mv!r}: no schema published and no "
                "live serving replica"
            )
        pk_names = [schema.columns[i].name for i in schema.pk]
        keyed = []
        out_cols: list = []
        for pk in pks:
            where = " AND ".join(
                f"{n} = {_sql_literal(v)}"
                for n, v in zip(pk_names, pk)
            )
            proj = ", ".join(cols) if cols else "*"
            c, rows = self.serve(
                f"SELECT {proj} FROM {mv} WHERE {where}"
            )
            out_cols = c or out_cols
            enc = b"".join(
                schema.encode_pk_value(ci, v)
                for ci, v in zip(schema.pk, pk)
            )
            keyed += [(enc, tuple(row)) for row in rows]
        keyed.sort(key=lambda kv: kv[0])
        return out_cols, [row for _, row in keyed]

    # -- introspection ----------------------------------------------------
    def rpc_cluster_state(self) -> dict:
        return self.state()

    def rpc_metrics(self) -> dict:
        return {"prometheus": self.metrics.render_prometheus()}

    def rpc_trace_dump(self, trace_id: str | None = None) -> dict:
        return {"role": "meta",
                "spans": GLOBAL_TRACE.dump(trace_id)}

    def rpc_cluster_trace(self, round: "int | None" = None) -> dict:
        return self.cluster_trace(round)

    def cluster_trace(self, round: "int | None" = None) -> dict:
        """Assemble ONE cross-role span tree for a round (``ctl
        cluster trace``): the meta's own flight recorder merged with
        every live worker's and serving replica's ``trace_dump``
        (best-effort — a dead peer's spans are simply absent, leaving
        a truncated-but-parseable tree).  Defaults to the most recent
        round that has spans at or below the committed cluster epoch;
        returns the filtered spans plus a ``tree_check`` verdict and
        the full list of rounds the recorders still hold."""
        dumps = [GLOBAL_TRACE.dump()]
        with self._lock:
            workers = [w for w in self.workers.values() if w.alive]
            serving = [r for r in self.serving.values() if r.alive]
        for peer in workers + serving:
            try:
                d = peer.client.call("trace_dump")
                dumps.append(d.get("spans") or [])
            except (RpcError, ConnectionError, OSError):
                pass
        spans = merge_dumps(dumps)
        rounds = round_ids(spans)
        if round is not None:
            rn = int(round)
        else:
            committed = [r for r in rounds if r <= self.cluster_epoch]
            rn = committed[-1] if committed \
                else (rounds[-1] if rounds else 0)
        picked = spans_for_round(spans, rn)
        return {
            "round": rn,
            "rounds": rounds,
            "cluster_epoch": self.cluster_epoch,
            "spans": picked,
            "check": tree_check(picked),
        }

    def rpc_cluster_metrics(self) -> dict:
        return {"prometheus": self.cluster_metrics()}

    def cluster_metrics(self) -> str:
        """ONE aggregated Prometheus scrape for the whole cluster
        (``ctl cluster metrics``): the meta's own registry plus every
        live worker's and serving replica's ``rpc_metrics`` text,
        merged with ``role``/``worker``/``replica`` identity labels
        injected per sample (best-effort — an unreachable peer's
        section is absent, never an error)."""
        scrapes: list[tuple[dict, str]] = [
            ({"role": "meta"}, self.metrics.render_prometheus()),
        ]
        with self._lock:
            workers = [w for w in self.workers.values() if w.alive]
            serving = [r for r in self.serving.values() if r.alive]
        for w in workers:
            try:
                text = w.client.call("metrics").get("prometheus", "")
                scrapes.append((
                    {"role": f"worker{w.worker_id}",
                     "worker": str(w.worker_id)}, text,
                ))
            except (RpcError, ConnectionError, OSError):
                pass
        for r in serving:
            try:
                text = r.client.call("metrics").get("prometheus", "")
                scrapes.append((
                    {"role": f"serving{r.replica_id}",
                     "replica": str(r.replica_id)}, text,
                ))
            except (RpcError, ConnectionError, OSError):
                pass
        return merge_prometheus(scrapes)

    def rpc_cluster_pushdown(self) -> dict:
        return self.cluster_pushdown()

    def cluster_pushdown(self) -> dict:
        """The pushdown-plane observability surface (``ctl cluster
        pushdown``): the manifest's per-table expiry policy docs plus
        the meta-side compactor elision counters, and each live
        serving replica's negative-cache / warmup numbers from its
        ``state`` RPC (best-effort — an unreachable replica reports
        null rather than failing the whole view)."""
        stats = self.hummock.stats()
        out = {
            "version_id": stats.get("version_id"),
            "pushdown": stats.get("pushdown") or {},
            "serving": {},
        }
        with self._lock:
            serving = [r for r in self.serving.values() if r.alive]
        for r in serving:
            try:
                st = r.client.call("state")
                out["serving"][r.replica_id] = {
                    "negative_cache_hits":
                        st.get("negative_cache_hits"),
                    "negative_cache_entries":
                        st.get("negative_cache_entries"),
                    "warmup_replays": st.get("warmup_replays"),
                }
            except (RpcError, ConnectionError, OSError):
                out["serving"][r.replica_id] = None
        return out

    def rpc_cluster_faults(self) -> dict:
        return self.cluster_faults()

    def cluster_faults(self) -> dict:
        """The chaos observability surface (``ctl cluster faults``):
        this process' injected-fault counters plus the meta's retry
        budget, and the same two numbers from every live worker and
        serving replica (best-effort — an unreachable peer reports
        null rather than failing the whole view)."""
        self._export_fault_gauges()
        fabric = get_fabric()
        out = {
            "meta": {
                "fabric": fabric.stats() if fabric is not None else None,
                "rpc_retries_total": self.retry.retries,
                "rpc_retry_gave_up_total": self.retry.gave_up,
            },
            "workers": {},
            "serving": {},
        }
        with self._lock:
            workers = [w for w in self.workers.values() if w.alive]
            serving = [r for r in self.serving.values() if r.alive]
        for w in workers:
            try:
                out["workers"][w.worker_id] = w.client.call("faults")
            except (RpcError, ConnectionError, OSError):
                out["workers"][w.worker_id] = None
        for r in serving:
            try:
                out["serving"][r.replica_id] = r.client.call("faults")
            except (RpcError, ConnectionError, OSError):
                out["serving"][r.replica_id] = None
        return out

    def _export_fault_gauges(self) -> None:
        fabric = get_fabric()
        self.metrics.set_gauge(
            "faults_injected_total",
            fabric.injected_total() if fabric is not None else 0,
        )
        self.metrics.set_gauge("rpc_retries_spent_total",
                               self.retry.retries)
        self.metrics.set_gauge("rpc_retry_gave_up_spent_total",
                               self.retry.gave_up)

    def state(self) -> dict:
        """The ctl/dashboard surface (risectl cluster-info analog)."""
        now = time.monotonic()
        with self._lock:
            return {
                "cluster_epoch": self.cluster_epoch,
                "manifest_epoch":
                    self.versions.current.max_committed_epoch,
                "failovers": self.failovers,
                "recovered": self.recovered,
                "workers": [
                    {"id": w.worker_id, "addr": w.addr,
                     "alive": w.alive, "pid": w.pid,
                     "heartbeat_age_s": round(now - w.last_seen, 3),
                     "jobs": sorted(w.jobs)}
                    for w in self.workers.values()
                ],
                "serving": [
                    {"id": r.replica_id, "addr": r.addr,
                     "alive": r.alive, "pid": r.pid,
                     "heartbeat_age_s": round(now - r.last_seen, 3),
                     "granted_vid": r.granted_vid,
                     "pinned_vids": sorted(r.pins)}
                    for r in self.serving.values()
                ],
                "jobs": [
                    {"name": j.name, "mvs": list(j.mvs),
                     "worker": j.worker_id, "rounds": j.rounds,
                     "pinned_epoch": j.pinned_epoch,
                     "sealed_epoch":
                         j.seal_log[-1][1] if j.seal_log else 0,
                     "durable_epoch": j.durable_epoch,
                     "committed_epoch":
                         j.seal_log[-1][1] if j.seal_log else 0,
                     "partitions": [
                         {"lineage": p.lineage,
                          "worker": p.worker_id,
                          "vnodes": len(p.vnodes),
                          "rounds": p.rounds,
                          "pinned_epoch": p.pinned_epoch}
                         for p in j.partitions.values()
                     ] if j.partitions else None}
                    for j in self.jobs.values()
                ],
                "integrity": {
                    "scrub_cycles": self.scrubber.cycles,
                    "scrub_objects_verified":
                        self.scrubber.objects_verified,
                    "scrub_corruptions": self.scrubber.corruptions,
                    "repairs": dict(self.repairs),
                },
                "exchange": {
                    "version": (self._choreography or {}).get(
                        "version", 0
                    ) if hasattr(self, "_choreography") else 0,
                    "tables": {
                        t: {"leader": e["leader"],
                            "standby": e.get("standby"),
                            "mode": e["mode"],
                            "key_col": e.get("key_col")}
                        for t, e in ((self._choreography or {})
                                     .get("tables", {})).items()
                    } if hasattr(self, "_choreography") else {},
                    "specs": list((self._choreography or {})
                                  .get("specs", []))
                    if hasattr(self, "_choreography") else [],
                },
                "scale": {
                    "partitioning": self.scale_partitioning,
                    "n_vnodes": self.n_vnodes,
                    "active_workers": list(self.active_workers),
                    "scale_ops": self.scale_ops,
                    "vnode_map": {
                        str(w): sum(1 for x in self.vnode_map
                                    if x == w)
                        for w in sorted(set(self.vnode_map))
                    } if self.vnode_map else None,
                },
            }


class MetaFrontend:
    """The thin pgwire façade over a MetaService: SELECTs route to
    workers through the pinned epoch, everything else is cluster DDL.
    Duck-types Engine.query, so ``pgwire.pg_serve`` hosts it as-is
    (the frontend node stays a router, exactly the reference split)."""

    def __init__(self, meta: MetaService):
        self.meta = meta

    def query(self, sql: str):
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse

        stmts = parse(sql)
        if len(stmts) == 1 and isinstance(stmts[0], ast.Select):
            return self.meta.serve(sql)
        self.meta.execute_ddl(sql)
        return [], []

    def query_batch(self, sqls: list) -> list:
        """Batched serving reads: N SELECTs through one replica RPC
        frame (``MetaService.serve_batch``); per-item owner fallback
        keeps the SQL surface identical to ``query``."""
        return self.meta.serve_batch(list(sqls))

    def multi_get(self, mv: str, pks: list,
                  cols: list | None = None):
        """First-class multi-get: one MV + N pks in one frame, rows
        back in encoded-pk order (missing pks omitted)."""
        return self.meta.serve_multi_get(mv, list(pks), cols)
