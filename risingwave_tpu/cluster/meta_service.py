"""MetaService: the cluster's coordination brain (meta node role).

Reference counterparts, collapsed into one object:

- ``ClusterController`` worker registry + heartbeat expiry
  (src/meta/src/manager/cluster.rs) — workers register, beat, and are
  declared dead after ``heartbeat_timeout_s`` of silence;
- ``DdlController`` + streaming job placement
  (src/meta/src/rpc/ddl_controller.rs) — DDL lands in the durable
  catalog log, streaming jobs are scheduled onto compute workers
  (job-level placement: least-loaded live worker, MV-on-MV co-located
  with its upstream job);
- ``GlobalBarrierWorker`` (src/meta/src/barrier/worker.rs:378) — the
  global checkpoint protocol: one *round* injects a barrier into every
  job on every worker, collects per-job epoch seals, and only when ALL
  jobs sealed the round commits ONE cluster epoch through the
  versioned manifest (storage/hummock/version.py) — so a snapshot
  read pinned at that commit sees every MV at the same round;
- recovery (SURVEY.md §3.5) — on missed heartbeats the worker is
  marked dead, its jobs are reassigned to survivors and recovered
  from their last durable checkpoint; counter-addressed sources make
  the replay exact, so the cluster converges to the byte-identical
  result of an undisturbed run.

Pacing contract: compute workers have NO self-ticker — every chunk
and barrier a job processes is driven by a meta ``tick()`` round.
That makes the meta the global serializer for checkpoint-store
commits (one barrier RPC in flight at a time), which is what keeps
the shared manifest single-writer without a distributed lock.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from dataclasses import dataclass, field

from risingwave_tpu.cluster.rpc import (
    RpcClient,
    RpcError,
    RpcServer,
)
from risingwave_tpu.common.faults import RetryPolicy, get_fabric
from risingwave_tpu.common.metrics import MetricsRegistry
from risingwave_tpu.meta.store import MetaStore


@dataclass
class WorkerInfo:
    """One registered compute worker (ref WorkerNode)."""

    worker_id: int
    host: str
    port: int
    pid: int | None = None
    alive: bool = True
    last_seen: float = field(default_factory=time.monotonic)
    #: job names assigned to this worker
    jobs: set = field(default_factory=set)
    client: RpcClient | None = None
    #: SST keys allocated to this worker for MV exports, not yet
    #: returned in a barrier seal (released as orphans on death)
    sst_keys: set = field(default_factory=set)

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class ServingReplicaInfo:
    """One registered serving replica (the stateless read tier).

    ``pins`` maps manifest vid → meta-side pin id: the replica's HELD
    version and its latest GRANT both stay pinned in the meta's
    ``VersionManager``, so vacuum counts them in its keep-set — a
    serving read can never lose an SST underneath it.  The lease
    advances on heartbeats (the replica reports the vid it holds; the
    meta releases older pins and pins the current version as the next
    grant) and is reaped wholesale when the replica's heartbeat
    expires."""

    replica_id: int
    host: str
    port: int
    pid: int | None = None
    alive: bool = True
    last_seen: float = field(default_factory=time.monotonic)
    client: RpcClient | None = None
    #: manifest vid -> VersionManager pin id
    pins: dict = field(default_factory=dict)
    granted_vid: int = 0

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class JobInfo:
    """One placed streaming job (ref TableFragments / StreamingJob).

    ``mvs`` lists every MV/sink riding the job (MV-on-MV attaches to
    its upstream's job, exactly like the engine merges DagJobs).
    ``seal_log`` records (round, committed_epoch) per successful
    barrier — the map recovery uses to translate a recovered epoch
    back into a round position.
    """

    name: str
    ddl: list = field(default_factory=list)
    mvs: list = field(default_factory=list)
    worker_id: int | None = None
    #: cluster round this job has sealed up to
    rounds: int = 0
    #: (round, epoch_value) per sealed barrier, round-ascending
    seal_log: list = field(default_factory=list)
    #: epoch value serving reads pin for this job (last CLUSTER commit)
    pinned_epoch: int = 0
    #: last durable (upload-acked) epoch the worker reported — the
    #: cluster epoch commits only when this catches the round's seal
    durable_epoch: int = 0


class MetaService:
    """The meta node.  ``start()`` brings up the RPC server and the
    heartbeat monitor; tests may also drive every method in-process."""

    def __init__(self, data_dir: str, heartbeat_timeout_s: float = 3.0,
                 metrics: MetricsRegistry | None = None,
                 serve_retry_timeout_s: float = 60.0,
                 rpc_timeout_s: float = 180.0,
                 durable_wait_s: float = 15.0,
                 retry_max_attempts: int = 4,
                 retry_base_delay_s: float = 0.05,
                 retry_max_delay_s: float = 0.5):
        from risingwave_tpu.storage.hummock import (
            CompactorService,
            HummockStorage,
            LocalFsObjectStore,
        )

        self.data_dir = data_dir
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.serve_retry_timeout_s = serve_retry_timeout_s
        self.rpc_timeout_s = rpc_timeout_s
        #: how long one tick() waits for the round's checkpoint
        #: uploads to ack before returning the round uncommitted
        #: (retried by the next tick — rounds never commit past a
        #: non-durable seal)
        self.durable_wait_s = durable_wait_s
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        #: durable DDL log — the same store a single node replays, so a
        #: restarted meta (or a single-node takeover) can rebuild the
        #: cluster catalog
        self.store = MetaStore(data_dir)
        #: the meta-owned storage service over the shared data_dir:
        #: the version manifest (meta is its SINGLE writer — workers
        #: upload SST objects under meta-allocated keys and hand the
        #: descriptors back through barrier seals), the background
        #: compactor, and pin-aware vacuum.  ``versions`` stays the
        #: cluster-epoch commit point it always was.
        self.hummock = HummockStorage(
            LocalFsObjectStore(os.path.join(data_dir, "hummock")),
            metrics=self.metrics,
        )
        self.versions = self.hummock.versions
        # gentler poll than the embedded default: the meta shares its
        # core with the barrier loop and the RPC server
        self.compactor = CompactorService(self.hummock,
                                          poll_interval_s=0.05)
        self._lock = threading.RLock()
        #: serializes barrier rounds AND failover reassignment: a job
        #: is never adopted while one of its barrier RPCs is in flight
        self._tick_lock = threading.Lock()
        self.workers: dict[int, WorkerInfo] = {}
        #: registered serving replicas (the stateless read tier)
        self.serving: dict[int, ServingReplicaInfo] = {}
        self._next_replica = 1
        #: round-robin cursor for serving-read routing
        self._serve_rr = 0
        #: (job_name, round) -> uploaded-but-uncommitted MV export SST
        #: descriptors; committed into the manifest with the round's
        #: cluster epoch, replaced when a failover re-seals the round
        self._pending_ssts: dict[tuple, list] = {}
        self.jobs: dict[str, JobInfo] = {}
        #: mv/sink name -> owning JobInfo name
        self._mv_to_job: dict[str, str] = {}
        #: non-job DDL in arrival order (sources/tables/SETs/functions)
        #: — shipped to a worker the first time a job needs them
        self.prelude: list[str] = []
        self._next_worker = 1
        #: committed cluster epoch (round number, 0 = nothing committed)
        self.cluster_epoch = 0
        self.failovers = 0
        #: unified backoff for every retry-safe control RPC the meta
        #: issues (barrier/job_epochs/adopt are idempotent or
        #: round-guarded; RpcError — the peer REFUSED — never retries)
        self.retry = RetryPolicy(
            max_attempts=retry_max_attempts,
            base_delay_s=retry_base_delay_s,
            max_delay_s=retry_max_delay_s,
            metrics=self.metrics, op="meta",
        )
        self._server: RpcServer | None = None
        self._monitor: threading.Thread | None = None
        self._stop = threading.Event()
        #: True when this meta rebuilt jobs from a durable catalog (a
        #: restart) — introspection for operators and chaos asserts
        self.recovered = False
        self._recover_from_store()
        self._set_worker_gauges()

    # -- crash recovery ---------------------------------------------------
    def _recover_from_store(self) -> None:
        """Meta restart: rebuild the cluster catalog (jobs, MV→job map,
        prelude) by replaying the durable DDL log, then restore the
        round position from the last committed-round record.  Every
        job comes back UNASSIGNED — workers detect the dead meta
        through heartbeat errors, re-register with backoff, and
        ``_assign_pending`` re-adopts their jobs from the last durable
        checkpoint; ``_rewind_job`` translates each recovered epoch
        back into a round (crediting a round the old meta sealed but
        never committed — the in-flight round re-seals, it never
        re-runs).  No operator action anywhere on this path."""
        ddl = self.store.ddl_log()
        if not ddl:
            return
        self.recovered = True
        for sql in ddl:
            self.execute_ddl(sql, replay=True)
        rec = self.store.last_cluster_commit()
        if rec is None:
            return
        self.cluster_epoch = int(rec["round"])
        for job in self.jobs.values():
            seal = rec["seals"].get(job.name)
            job.rounds = self.cluster_epoch
            if seal is not None:
                job.seal_log = [(self.cluster_epoch, int(seal))]
                job.pinned_epoch = int(seal)
        self.metrics.set_gauge("cluster_epoch_committed",
                               self.cluster_epoch)
        self.metrics.set_gauge("cluster_manifest_epoch",
                               self.versions.max_committed_epoch)

    # -- lifecycle ------------------------------------------------------
    @property
    def rpc_port(self) -> int:
        return self._server.port if self._server is not None else 0

    def start(self, host: str = "127.0.0.1", port: int = 0,
              monitor: bool = True, compactor: bool = True,
              ) -> "MetaService":
        self._stop.clear()
        self._server = RpcServer(self, host, port).start()
        if monitor:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="meta-monitor",
                daemon=True,
            )
            self._monitor.start()
        if compactor:
            # the shared-storage compactor rides the meta process (the
            # manifest's single writer); in-process tests may pass
            # compactor=False and drive hummock.compact_once directly
            self.compactor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.compactor.stop()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        with self._lock:
            for w in self.workers.values():
                if w.client is not None:
                    w.client.close()
            for r in self.serving.values():
                if r.client is not None:
                    r.client.close()

    # -- worker registry / heartbeats -----------------------------------
    def rpc_register_worker(self, host: str, port: int,
                            pid: int | None = None) -> dict:
        with self._lock:
            wid = self._next_worker
            self._next_worker += 1
            w = WorkerInfo(wid, host, int(port), pid)
            w.client = RpcClient(host, int(port),
                                 timeout=self.rpc_timeout_s,
                                 src="meta", dst=f"worker{wid}")
            self.workers[wid] = w
            self._set_worker_gauges()
        # a fresh worker can pick up any stranded jobs immediately
        self._assign_pending()
        return {"worker_id": wid, "cluster_epoch": self.cluster_epoch}

    def rpc_heartbeat(self, worker_id: int) -> dict:
        with self._lock:
            w = self.workers.get(int(worker_id))
            if w is None or not w.alive:
                # a dead-marked worker must re-register: its jobs may
                # already run elsewhere (ref: expired workers rejoin
                # through the registration path)
                raise ValueError(f"unknown or expired worker {worker_id}")
            w.last_seen = time.monotonic()
        return {"ok": True, "cluster_epoch": self.cluster_epoch}

    def live_workers(self) -> list[WorkerInfo]:
        with self._lock:
            return [w for w in self.workers.values() if w.alive]

    def _set_worker_gauges(self) -> None:
        self.metrics.set_gauge(
            "cluster_live_workers",
            sum(1 for w in self.workers.values() if w.alive),
        )
        self.metrics.set_gauge("cluster_jobs", len(self.jobs))
        self.metrics.set_gauge(
            "cluster_serving_replicas",
            sum(1 for r in self.serving.values() if r.alive),
        )
        self.metrics.set_gauge(
            "cluster_serving_pins",
            sum(len(r.pins) for r in self.serving.values()),
        )

    def _monitor_loop(self) -> None:
        interval = min(self.heartbeat_timeout_s / 4, 0.5)
        while not self._stop.wait(interval):
            self.check_heartbeats()

    def check_heartbeats(self) -> None:
        """One monitor pass: refresh age gauges, expire silent workers,
        reassign their jobs (also called directly by tests).  Serving
        replicas expire on the same cadence — a dead replica's epoch
        pin lease is reaped immediately so it can never block vacuum
        forever."""
        now = time.monotonic()
        expired: list[WorkerInfo] = []
        stale_serving: list[ServingReplicaInfo] = []
        with self._lock:
            for w in self.workers.values():
                if not w.alive:
                    continue
                age = now - w.last_seen
                self.metrics.set_gauge(
                    "cluster_worker_heartbeat_age_seconds", age,
                    worker=str(w.worker_id),
                )
                if age > self.heartbeat_timeout_s:
                    expired.append(w)
            for r in self.serving.values():
                if r.alive and now - r.last_seen \
                        > self.heartbeat_timeout_s:
                    stale_serving.append(r)
        for w in expired:
            self._on_worker_dead(w)
        for r in stale_serving:
            self._on_serving_dead(r)
        if expired or any(j.worker_id is None
                          for j in self.jobs.values()):
            self._assign_pending()

    def _on_serving_dead(self, r: ServingReplicaInfo) -> None:
        """Reap one serving replica: drop it from routing and release
        every pin of its lease (stale leases must not hold GC keep-set
        entries for a process that will never read again)."""
        with self._lock:
            if not r.alive:
                return
            r.alive = False
            for pin_id in r.pins.values():
                self.versions.unpin(pin_id)
            r.pins.clear()
            if r.client is not None:
                r.client.close()
            self.serving.pop(r.replica_id, None)
            self._set_worker_gauges()

    def _on_worker_dead(self, w: WorkerInfo) -> None:
        # under the tick lock: never declare dead / reassign while one
        # of the worker's barrier RPCs is still in flight (a stale
        # barrier finishing late must not interleave checkpoint writes
        # with the new owner's)
        with self._tick_lock:
            with self._lock:
                if not w.alive:
                    return
                w.alive = False
                self.failovers += 1
                self.metrics.inc("cluster_failovers_total")
                self.metrics.remove_series(
                    "cluster_worker_heartbeat_age_seconds",
                    worker=str(w.worker_id),
                )
                for name in list(w.jobs):
                    self.jobs[name].worker_id = None
                w.jobs.clear()
                # allocated-but-never-sealed export keys become
                # vacuumable orphans; keys already riding a sealed
                # round stay protected in _pending_ssts
                pending = {s["key"] for ssts in
                           self._pending_ssts.values() for s in ssts}
                for key in w.sst_keys - pending:
                    self.hummock.release_external_sst_key(key)
                w.sst_keys.clear()
                if w.client is not None:
                    w.client.close()
                self._set_worker_gauges()

    # -- serving replicas: registry + epoch pin leases -------------------
    def rpc_register_serving(self, host: str, port: int,
                             pid: int | None = None) -> dict:
        """Register a serving replica and grant its FIRST epoch pin
        lease: the current manifest version is pinned meta-side BEFORE
        the grant leaves, so every SST the replica can reach stays in
        the vacuum keep-set from the very first read."""
        with self._lock:
            rid = self._next_replica
            self._next_replica += 1
            r = ServingReplicaInfo(rid, host, int(port), pid)
            r.client = RpcClient(host, int(port),
                                 timeout=self.rpc_timeout_s,
                                 src="meta", dst=f"serving{rid}")
            pin_id, version = self.versions.pin()
            r.pins[version.vid] = pin_id
            r.granted_vid = version.vid
            self.serving[rid] = r
            self._set_worker_gauges()
        self.hummock._update_gauges()
        return {
            "replica_id": rid,
            "granted_vid": r.granted_vid,
            "cluster_epoch": self.cluster_epoch,
            "manifest_epoch": self.versions.max_committed_epoch,
        }

    def rpc_serving_heartbeat(self, replica_id: int,
                              vid: int = 0) -> dict:
        """One lease round-trip: the replica reports the manifest vid
        it HOLDS (acking older grants), the meta releases pins below
        it, pins the current version as the next grant, and returns
        the grant.  The replica only ever advances to granted vids, so
        its held version is pinned at all times — vacuum can never
        reap an SST under a live serving read."""
        with self._lock:
            r = self.serving.get(int(replica_id))
            if r is None or not r.alive:
                raise ValueError(
                    f"unknown or expired serving replica {replica_id}"
                )
            r.last_seen = time.monotonic()
            held = int(vid)
            pin_id, version = self.versions.pin()
            if version.vid in r.pins:
                self.versions.unpin(pin_id)
            else:
                r.pins[version.vid] = pin_id
            r.granted_vid = version.vid
            # keep exactly the held version and the fresh grant; every
            # pin in between was a grant the replica skipped past
            keep = {held, version.vid}
            for pv in [p for p in r.pins if p not in keep]:
                self.versions.unpin(r.pins.pop(pv))
            self._set_worker_gauges()
        return {
            "ok": True,
            "granted_vid": r.granted_vid,
            "cluster_epoch": self.cluster_epoch,
            "manifest_epoch": self.versions.max_committed_epoch,
        }

    def rpc_unregister_serving(self, replica_id: int) -> dict:
        with self._lock:
            r = self.serving.get(int(replica_id))
        if r is not None:
            self._on_serving_dead(r)
        return {"ok": True}

    # -- external SST allocation (worker MV exports) ---------------------
    def rpc_alloc_sst(self, worker_id: int) -> dict:
        """Allocate one vacuum-protected SST key for a worker's MV
        export upload (the single allocator keeps keys collision-free
        across worker processes)."""
        with self._lock:
            w = self.workers.get(int(worker_id))
            if w is None or not w.alive:
                raise ValueError(f"unknown or expired worker {worker_id}")
        key = self.hummock.alloc_external_sst_key()
        with self._lock:
            w.sst_keys.add(key)
        return {"key": key}

    # -- storage service (vacuum rides the meta) -------------------------
    def storage_vacuum(self) -> dict:
        """GC pass over the shared store: deletes SST objects
        unreferenced by the current version, any serving pin lease, or
        an in-flight allocation."""
        deleted = self.hummock.vacuum()
        return {"deleted_objects": deleted,
                "remaining_objects": self.hummock.stats()["objects"]}

    def rpc_storage_vacuum(self) -> dict:
        return self.storage_vacuum()

    # -- DDL / placement -------------------------------------------------
    def rpc_execute_ddl(self, sql: str) -> dict:
        return self.execute_ddl(sql)

    def execute_ddl(self, sql: str, replay: bool = False) -> dict:
        """Apply one or more statements at the cluster level: job DDL
        places a streaming job, everything else joins the prelude all
        future jobs replay.  ``replay=True`` (meta crash recovery)
        rebuilds the in-memory catalog from the already-durable log:
        nothing is re-appended, no worker is called, no job assigned
        (workers re-register and re-adopt on their own schedule)."""
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse_with_text

        placed: list[str] = []
        for text, stmt in parse_with_text(sql):
            if isinstance(stmt, (ast.CreateMaterializedView,
                                 ast.CreateSink)):
                self._place_job(text, stmt.name, replay=replay)
                placed.append(stmt.name)
            elif isinstance(stmt, ast.Insert):
                # never reaches the DDL log; forwarded rows live in the
                # workers' durable table history + checkpoints
                if not replay:
                    self._forward_dml(text, stmt.table)
            else:
                if not replay:
                    self.store.append_ddl(text)
                self.prelude.append(text)
        return {"ok": True, "placed": placed,
                "cluster_epoch": self.cluster_epoch}

    def _co_located_job(self, text: str) -> "JobInfo | None":
        """MV-on-MV placement: a query referencing an existing MV must
        land on that MV's job (the engine attaches it to the same
        DagJob there)."""
        import re

        for mv, jname in self._mv_to_job.items():
            if re.search(rf"\b{re.escape(mv)}\b", text):
                return self.jobs[jname]
        return None

    def _place_job(self, text: str, name: str,
                   replay: bool = False) -> None:
        if name in self._mv_to_job:
            raise ValueError(f"{name!r} already exists")
        if not replay:
            self.store.append_ddl(text)
        upstream = self._co_located_job(text)
        if upstream is not None:
            # ship only the prelude delta the job hasn't seen yet plus
            # the new statement; the worker attaches it to the live job
            sent = len(upstream.ddl) - len(upstream.mvs)
            delta = self.prelude[sent:] + [text]
            upstream.ddl.extend(delta)
            upstream.mvs.append(name)
            with self._lock:
                self._mv_to_job[name] = upstream.name
            if not replay and upstream.worker_id is not None:
                w = self.workers[upstream.worker_id]
                self.retry.run(
                    lambda: w.client.call("adopt", ddl=delta,
                                          name=upstream.name,
                                          recover=False),
                    label="adopt",
                )
            return
        job = JobInfo(name=name, ddl=list(self.prelude) + [text],
                      mvs=[name])
        # a job created after commits joins at the current round: it
        # seals the NEXT round with everyone else
        job.rounds = self.cluster_epoch
        with self._lock:
            self.jobs[name] = job
            self._mv_to_job[name] = name
            self._set_worker_gauges()
        if not replay:
            self._assign_pending()

    def _forward_dml(self, text: str, table: str) -> None:
        """INSERTs fan out to every worker whose catalog has the table
        (each job's private reader consumes its worker-local history —
        the same per-job readers a single node plans)."""
        delivered = 0
        for w in self.live_workers():
            try:
                w.client.call("execute", sql=text)
                delivered += 1
            except RpcError as e:
                # a worker without the table answers KeyError("relation
                # ... does not exist") — that worker just isn't a host
                if "does not exist" in str(e):
                    continue
                raise
            except (ConnectionError, OSError):
                continue  # heartbeat monitor will expire it
        if delivered == 0:
            raise ValueError(
                f"INSERT into {table!r}: no live worker has the table "
                "(create it and place a job first)"
            )
        # durable only once at least one host accepted it (rejected
        # statements must not resurrect at replay)
        self.store.append_dml_sql(text)

    def _assign_pending(self) -> None:
        """Place every unassigned job on the least-loaded live worker;
        adoption recovers the job from its last durable checkpoint."""
        while True:
            with self._lock:
                pending = [j for j in self.jobs.values()
                           if j.worker_id is None]
                live = [w for w in self.workers.values() if w.alive]
                if not pending or not live:
                    return
                job = pending[0]
                target = min(live,
                             key=lambda w: (len(w.jobs), w.worker_id))
            try:
                # adopt is idempotent (already-present DDL is skipped,
                # recovery rewinds to the same durable epoch) — safe to
                # retry through transient drops
                res = self.retry.run(
                    lambda: target.client.call(
                        "adopt", ddl=job.ddl, name=job.name,
                        recover=True,
                    ),
                    label="adopt",
                )
            except (RpcError, ConnectionError, OSError):
                # adoption failed: leave unassigned; the monitor loop
                # retries (and may expire the worker first)
                return
            recovered = int(res.get("committed_epoch", 0))
            with self._lock:
                if job.worker_id is not None:
                    continue  # raced with another assigner
                job.worker_id = target.worker_id
                target.jobs.add(job.name)
                self._rewind_job(job, recovered)

    def _rewind_job(self, job: JobInfo, epoch: int) -> None:
        """Translate a recovered committed epoch back into the round
        the job actually reached (its checkpoint may include a round
        meta never saw acknowledged)."""
        # the recovered epoch IS durable (adoption loads the manifest)
        job.durable_epoch = max(epoch, 0)
        epochs = [e for _, e in job.seal_log]
        if epoch <= 0:
            # no durable checkpoint: the job replays every round it
            # was credited with (fresh state, sources at zero)
            if job.seal_log:
                job.rounds = job.seal_log[0][0] - 1
            else:
                job.rounds = min(job.rounds, self.cluster_epoch)
            job.seal_log = []
            return
        i = bisect.bisect_right(epochs, epoch)
        if i > 0 and epochs[i - 1] == epoch:
            job.seal_log = job.seal_log[:i]
            job.rounds = job.seal_log[-1][0]
        elif i == len(epochs):
            # sealed + checkpointed, died before acking: credit the
            # in-flight round
            round_ = (job.seal_log[-1][0] + 1) if job.seal_log \
                else job.rounds + 1
            job.seal_log.append((round_, epoch))
            job.rounds = round_
        else:
            # an epoch meta never recorded, older than later seals —
            # cannot happen with meta-serialized rounds; resync hard
            job.seal_log = job.seal_log[:i]
            job.rounds = job.seal_log[-1][0] if job.seal_log else 0

    # -- the global checkpoint protocol ---------------------------------
    def rpc_tick(self, chunks_per_barrier: int = 1) -> dict:
        return self.tick(chunks_per_barrier)

    def tick(self, chunks_per_barrier: int = 1) -> dict:
        """Drive ONE global barrier round: every job SEALS round
        ``cluster_epoch + 1`` (the barrier RPC returns as soon as the
        epoch is sealed — its checkpoint upload runs in the worker's
        background uploader); the cluster epoch commits through the
        versioned manifest only when every job's upload has ACKED the
        sealed epoch.  Incomplete rounds (dead/unassigned workers,
        uploads still in flight) commit nothing — the cluster epoch
        never moves past a hole, and survivors run at most one round
        ahead."""
        t0 = time.perf_counter()
        with self._tick_lock:
            target = self.cluster_epoch + 1
            with self._lock:
                jobs = list(self.jobs.values())
            if not jobs:
                return {"round": target, "committed": False,
                        "jobs": 0, "sealed": 0}
            self.metrics.set_gauge("cluster_epoch_in_flight", target)
            sealed = 0
            for job in jobs:
                if job.rounds >= target:
                    sealed += 1
                    continue
                with self._lock:
                    w = self.workers.get(job.worker_id) \
                        if job.worker_id is not None else None
                if w is None or not w.alive:
                    continue
                try:
                    # round-tagged: the worker caches each job's last
                    # (round, seal) and answers a replay from the
                    # cache, so retrying after a lost RESPONSE cannot
                    # run the round twice (epoch-guarded idempotence)
                    res = self.retry.run(
                        lambda: w.client.call(
                            "barrier", job=job.name,
                            chunks=int(chunks_per_barrier),
                            round=target,
                        ),
                        label="barrier",
                    )
                except (RpcError, ConnectionError, OSError):
                    continue  # monitor expires the worker; round stalls
                epoch = int(res.get("sealed_epoch",
                                    res["committed_epoch"]))
                ssts = res.get("ssts") or []
                with self._lock:
                    job.rounds = target
                    job.seal_log.append((target, epoch))
                    job.durable_epoch = int(
                        res.get("durable_epoch", epoch)
                    )
                    # a failover re-seal replaces the dead attempt's
                    # pending export (same round, recomputed bytes)
                    for s in self._pending_ssts.pop((job.name, target),
                                                    []):
                        self.hummock.release_external_sst_key(s["key"])
                    if ssts:
                        self._pending_ssts[(job.name, target)] = ssts
                        w.sst_keys.difference_update(
                            {s["key"] for s in ssts}
                        )
                sealed += 1
            committed = sealed == len(jobs) \
                and self._await_durable(jobs, target)
            if committed:
                self._commit_cluster_epoch(target, jobs)
                self.metrics.observe(
                    "cluster_barrier_commit_seconds",
                    time.perf_counter() - t0,
                )
            self._export_fault_gauges()
            return {"round": target, "committed": committed,
                    "jobs": len(jobs), "sealed": sealed,
                    "cluster_epoch": self.cluster_epoch}

    def _await_durable(self, jobs: list[JobInfo], target: int) -> bool:
        """The seal-vs-ack split: poll each sealed job's worker until
        its durable (upload-acked) epoch reaches the round's seal, or
        the bounded wait expires (round retried by the next tick)."""
        deadline = time.monotonic() + self.durable_wait_s
        for job in jobs:
            with self._lock:
                if not job.seal_log:
                    return False
                want = job.seal_log[-1][1]
                w = self.workers.get(job.worker_id) \
                    if job.worker_id is not None else None
            lag_gauge = lambda v: self.metrics.set_gauge(  # noqa: E731
                "cluster_job_durable_lag_epochs", v, job=job.name,
            )
            if job.durable_epoch >= want:
                lag_gauge(0)
                continue
            if w is None or not w.alive:
                return False
            while True:
                try:
                    # read-only poll: always retry-safe
                    res = self.retry.run(
                        lambda: w.client.call("job_epochs",
                                              job=job.name),
                        label="job_epochs",
                    )
                except (RpcError, ConnectionError, OSError):
                    return False
                with self._lock:
                    job.durable_epoch = int(res.get("durable", 0))
                lag_gauge(max(0, want - job.durable_epoch))
                self.metrics.set_gauge(
                    "cluster_job_upload_queue_depth",
                    int(res.get("upload_queue", 0)), job=job.name,
                )
                if job.durable_epoch >= want:
                    break
                if time.monotonic() > deadline:
                    return False
                time.sleep(0.02)
        return True

    def _commit_cluster_epoch(self, round_: int,
                              jobs: list[JobInfo]) -> None:
        """All jobs sealed ``round_``: ONE manifest delta records the
        global consistency point — carrying every MV export SST the
        round's seals uploaded (newest round first, so L0 reader order
        stays newest-first) — then serving pins move forward: a
        snapshot read after this sees every MV at the same round."""
        from risingwave_tpu.storage.hummock.version import SstInfo

        epoch_val = min(j.seal_log[-1][1] for j in jobs)
        with self._lock:
            due = sorted(
                [k for k in self._pending_ssts if k[1] <= round_],
                key=lambda k: -k[1],
            )
            adds = [
                SstInfo(
                    key=s["key"],
                    first_key=bytes.fromhex(s["first_key"]),
                    last_key=bytes.fromhex(s["last_key"]),
                    n_records=int(s["n_records"]),
                    size=int(s["size"]),
                )
                for k in due for s in self._pending_ssts[k]
            ]
            for k in due:
                del self._pending_ssts[k]
        self.hummock.commit_external(epoch_val, adds)
        # durable round record AFTER the manifest commit: a crash in
        # between re-commits the round idempotently at restart (empty
        # delta, same epoch stamp) — never a lost or double round
        self.store.append_cluster_commit(
            round_, epoch_val,
            {j.name: j.seal_log[-1][1] for j in jobs},
        )
        with self._lock:
            self.cluster_epoch = round_
            for j in jobs:
                j.pinned_epoch = j.seal_log[-1][1]
                # seal_log only needs entries recovery can rewind to;
                # everything at/before the global commit is final
                if len(j.seal_log) > 64:
                    j.seal_log = j.seal_log[-64:]
        self.metrics.set_gauge("cluster_epoch_committed", round_)
        self.metrics.set_gauge("cluster_manifest_epoch", epoch_val)

    # -- serving reads ---------------------------------------------------
    def rpc_serve(self, sql: str) -> dict:
        cols, rows = self.serve(sql)
        return {"cols": cols, "rows": rows}

    def serve(self, sql: str):
        """Route a serving read.  SELECTs go ROUND-ROBIN across live
        serving replicas (the stateless read tier over shared SSTs,
        pinned at the last cluster-committed manifest epoch); when no
        replica is registered, a replica refuses the statement shape
        (``ServeUnsupported``), or every replica is unreachable, the
        read falls back to the MV's OWNING worker pinned at the job's
        last cluster-committed epoch.  While the owner is dead/
        unassigned (failover in progress) the read WAITS for the
        reassignment instead of erroring — reads never observe partial
        state and never fail across a worker OR replica kill."""
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse

        stmts = parse(sql)
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Select):
            raise ValueError("cluster serving handles a single SELECT")
        sel = stmts[0]
        if not isinstance(sel.from_, ast.TableRef):
            raise ValueError(
                "cluster serving reads are SELECT ... FROM <mv>"
            )
        mv = sel.from_.name
        deadline = time.monotonic() + self.serve_retry_timeout_s
        try_replicas = True
        while True:
            with self._lock:
                jname = self._mv_to_job.get(mv)
                if jname is None:
                    raise ValueError(f"{mv!r} is not a placed MV")
                job = self.jobs[jname]
                w = self.workers.get(job.worker_id) \
                    if job.worker_id is not None else None
                pin = job.pinned_epoch
                manifest_pin = self.versions.max_committed_epoch
                replicas = [r for r in self.serving.values() if r.alive]
                self._serve_rr += 1
                start = self._serve_rr
            if try_replicas and replicas:
                for i in range(len(replicas)):
                    r = replicas[(start + i) % len(replicas)]
                    try:
                        res = r.client.call("read", sql=sql,
                                            min_epoch=manifest_pin)
                        self.metrics.inc("cluster_serving_reads_total")
                        return res["cols"], [tuple(row)
                                             for row in res["rows"]]
                    except RpcError as e:
                        if "ServeUnsupported" in str(e):
                            # statement shape needs the engine — the
                            # owning worker serves it (and every retry
                            # of this read)
                            try_replicas = False
                            break
                        if "ServeUnavailable" in str(e):
                            # replica transiently stuck (lease refresh
                            # lost, behind the pin): route around it —
                            # next replica or the owner, never an error
                            continue
                        raise  # replica answered with a real failure
                    except (ConnectionError, OSError):
                        continue  # replica died mid-read: next one
            if w is not None and w.alive:
                try:
                    res = w.client.call("serve", sql=sql,
                                        query_epoch=pin)
                    return res["cols"], [tuple(r) for r in res["rows"]]
                except RpcError:
                    raise  # the engine refused: final
                except (ConnectionError, OSError):
                    pass  # owner died mid-read: wait for reassignment
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no live owner for {mv!r} within "
                    f"{self.serve_retry_timeout_s}s"
                )
            time.sleep(0.05)

    # -- introspection ----------------------------------------------------
    def rpc_cluster_state(self) -> dict:
        return self.state()

    def rpc_metrics(self) -> dict:
        return {"prometheus": self.metrics.render_prometheus()}

    def rpc_cluster_faults(self) -> dict:
        return self.cluster_faults()

    def cluster_faults(self) -> dict:
        """The chaos observability surface (``ctl cluster faults``):
        this process' injected-fault counters plus the meta's retry
        budget, and the same two numbers from every live worker and
        serving replica (best-effort — an unreachable peer reports
        null rather than failing the whole view)."""
        self._export_fault_gauges()
        fabric = get_fabric()
        out = {
            "meta": {
                "fabric": fabric.stats() if fabric is not None else None,
                "rpc_retries_total": self.retry.retries,
                "rpc_retry_gave_up_total": self.retry.gave_up,
            },
            "workers": {},
            "serving": {},
        }
        with self._lock:
            workers = [w for w in self.workers.values() if w.alive]
            serving = [r for r in self.serving.values() if r.alive]
        for w in workers:
            try:
                out["workers"][w.worker_id] = w.client.call("faults")
            except (RpcError, ConnectionError, OSError):
                out["workers"][w.worker_id] = None
        for r in serving:
            try:
                out["serving"][r.replica_id] = r.client.call("faults")
            except (RpcError, ConnectionError, OSError):
                out["serving"][r.replica_id] = None
        return out

    def _export_fault_gauges(self) -> None:
        fabric = get_fabric()
        self.metrics.set_gauge(
            "faults_injected_total",
            fabric.injected_total() if fabric is not None else 0,
        )
        self.metrics.set_gauge("rpc_retries_spent_total",
                               self.retry.retries)
        self.metrics.set_gauge("rpc_retry_gave_up_spent_total",
                               self.retry.gave_up)

    def state(self) -> dict:
        """The ctl/dashboard surface (risectl cluster-info analog)."""
        now = time.monotonic()
        with self._lock:
            return {
                "cluster_epoch": self.cluster_epoch,
                "manifest_epoch":
                    self.versions.current.max_committed_epoch,
                "failovers": self.failovers,
                "recovered": self.recovered,
                "workers": [
                    {"id": w.worker_id, "addr": w.addr,
                     "alive": w.alive, "pid": w.pid,
                     "heartbeat_age_s": round(now - w.last_seen, 3),
                     "jobs": sorted(w.jobs)}
                    for w in self.workers.values()
                ],
                "serving": [
                    {"id": r.replica_id, "addr": r.addr,
                     "alive": r.alive, "pid": r.pid,
                     "heartbeat_age_s": round(now - r.last_seen, 3),
                     "granted_vid": r.granted_vid,
                     "pinned_vids": sorted(r.pins)}
                    for r in self.serving.values()
                ],
                "jobs": [
                    {"name": j.name, "mvs": list(j.mvs),
                     "worker": j.worker_id, "rounds": j.rounds,
                     "pinned_epoch": j.pinned_epoch,
                     "sealed_epoch":
                         j.seal_log[-1][1] if j.seal_log else 0,
                     "durable_epoch": j.durable_epoch,
                     "committed_epoch":
                         j.seal_log[-1][1] if j.seal_log else 0}
                    for j in self.jobs.values()
                ],
            }


class MetaFrontend:
    """The thin pgwire façade over a MetaService: SELECTs route to
    workers through the pinned epoch, everything else is cluster DDL.
    Duck-types Engine.query, so ``pgwire.pg_serve`` hosts it as-is
    (the frontend node stays a router, exactly the reference split)."""

    def __init__(self, meta: MetaService):
        self.meta = meta

    def query(self, sql: str):
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse

        stmts = parse(sql)
        if len(stmts) == 1 and isinstance(stmts[0], ast.Select):
            return self.meta.serve(sql)
        self.meta.execute_ddl(sql)
        return [], []
