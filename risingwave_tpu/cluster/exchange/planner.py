"""ExchangePlanner: compile the cluster shuffle choreography ONCE.

Reference counterpart: the stream graph's exchange edges — the
fragmenter decides *at plan time* which dispatcher (hash / broadcast /
simple) connects every pair of fragments (src/stream/src/executor/
dispatch.rs); actors then move chunks without ever consulting the
meta.  *Suki*'s choreographed dataflow (PAPERS.md) is the sharper
model this module lifts to the cluster: the whole exchange topology is
compiled into a static choreography at placement/scale time, pushed to
every worker, and the per-chunk data path executes it peer-to-peer
with the meta fully out of the loop.

This is the worker-topology analog of ``sql/engine._plan_mesh_attach``
(round 12): there the planner derived, per DAG edge, which *device
shard* owns each row (all_to_all specs inside one ``shard_map``); here
it derives, per cluster edge, which *worker* owns each row's vnode.
Same hash (``common.hash.hash64_columns`` through
``scale.vnode.vnodes_of_ints``), same minimal-movement map
(``scale.vnode.rebalance``), one planning problem at two radii.

Edge taxonomy (``ExchangeSpec.kind``):

- ``source``   — ingest shuffle: the table's ingest leader hash-
  partitions each DML batch by the distribution-key vnode ONCE and
  sends each worker only its owned slice (``mode="shuffle"``); when
  the key is not traceable to a raw source column, or consumer jobs
  disagree on the key, the edge degrades to ``mode="replicate"`` (the
  PR-7 full fan-out; the VnodeGate then filters);
- ``join``     — a partitioned join job's two source edges, keyed per
  side by that side's first equi-key column (rows with equal join
  keys co-locate because equal tuples share their first column);
- ``attach``   — an MV-on-MV edge over a partitioned upstream.  When
  the downstream keys contain the upstream distribution key the
  exchange is the IDENTITY (``mode="local"`` — each partition's
  changelog already lives on the right owner, the cheapest possible
  choreography); reduced-key shapes are refused at plan time.

The compiled :class:`Choreography` is a plain JSON document (version,
per-table routing, edge specs) so the meta can push it over the
existing ``update_routing`` RPC and a restarted worker can ask for it
again — compile once, execute forever, exactly the Suki discipline.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field


@dataclass
class ExchangeSpec:
    """One compiled exchange edge of the cluster dataflow."""

    #: edge label, e.g. ``src:t>agg`` / ``join:t>j.left`` /
    #: ``attach:agg>agg2`` — also the metrics ``edge=`` label
    edge: str
    #: "source" | "join" | "attach"
    kind: str
    #: DML table the edge ships (source/join edges)
    table: str | None = None
    #: raw source-column index of the routing key (None = untraceable)
    key_col: int | None = None
    #: "shuffle" (sliced delivery) | "replicate" (full fan-out) |
    #: "local" (identity — rows already live on their owner)
    mode: str = "replicate"
    #: consumer job the edge feeds
    job: str = ""


@dataclass
class Choreography:
    """The compiled cluster shuffle plan (one per routing version).

    ``tables`` maps each replicated DML table to its routing entry::

        {"leader": wid, "standby": wid | None, "hosts": [wid...],
         "key_col": int | None, "mode": "shuffle" | "replicate",
         "n_vnodes": N, "slices": {wid: [vnode...]}}

    The ``standby`` host additionally receives the LEADER's own slice
    so a dead leader's unconsumed rows survive one failure (the next
    leader by sorted id IS the standby).
    """

    version: int = 0
    tables: dict = field(default_factory=dict)
    specs: list = field(default_factory=list)

    def to_doc(self) -> dict:
        return {
            "version": self.version,
            "tables": self.tables,
            "specs": [asdict(s) for s in self.specs],
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "Choreography":
        ch = cls(version=int(doc.get("version", 0)))
        for t, ent in (doc.get("tables") or {}).items():
            ch.tables[t] = {
                "leader": int(ent["leader"]),
                "standby": (int(ent["standby"])
                            if ent.get("standby") is not None else None),
                "hosts": [int(h) for h in ent["hosts"]],
                "key_col": (int(ent["key_col"])
                            if ent.get("key_col") is not None else None),
                "mode": ent.get("mode", "replicate"),
                "n_vnodes": int(ent.get("n_vnodes", 0)),
                "slices": {int(w): [int(v) for v in vs]
                           for w, vs in (ent.get("slices") or {}).items()},
            }
        ch.specs = [ExchangeSpec(**s) for s in (doc.get("specs") or [])]
        return ch


class ExchangePlanner:
    """Compiles the choreography from the meta's placement state.

    Input is deliberately plain data (no JobInfo coupling): one dict
    per partitioned job::

        {"name": str,
         "dml_tables": [table, ...],
         "shuffle_cols": {table: raw_col | None},
         "kinds": {table: "source" | "join"},
         "attach_edges": [(upstream_mv, downstream_mv), ...],
         "owners": {worker_id: [vnode, ...]}}

    plus the shared vnode ring size.  Everything here is a pure
    function of its inputs — every process compiles the same
    choreography from the same placement (the same determinism
    contract as ``scale.vnode.rebalance``).
    """

    @staticmethod
    def compile(jobs: list[dict], n_vnodes: int,
                version: int = 0) -> Choreography:
        ch = Choreography(version=version)
        # -- per-table routing: consumers must agree on the key -------
        consumers: dict[str, list[dict]] = {}
        for j in jobs:
            for t in j.get("dml_tables", ()):
                consumers.setdefault(t, []).append(j)
        for table, js in sorted(consumers.items()):
            hosts = sorted({w for j in js for w in j["owners"]})
            if not hosts:
                continue
            keys = {j.get("shuffle_cols", {}).get(table) for j in js}
            key_col = keys.pop() if len(keys) == 1 else None
            mode = "shuffle" if key_col is not None else "replicate"
            # a worker's slice for this table: the union of its owned
            # vnodes across consumer jobs (one global map ⇒ identical
            # per job, but stay robust to asymmetric placements)
            slices: dict[int, set] = {w: set() for w in hosts}
            for j in js:
                for w, vns in j["owners"].items():
                    slices.setdefault(w, set()).update(
                        int(v) for v in vns
                    )
            ch.tables[table] = {
                "leader": hosts[0],
                "standby": hosts[1] if len(hosts) > 1 else None,
                "hosts": hosts,
                "key_col": key_col,
                "mode": mode,
                "n_vnodes": int(n_vnodes),
                "slices": {w: sorted(v) for w, v in slices.items()},
            }
            for j in js:
                kind = j.get("kinds", {}).get(table, "source")
                ch.specs.append(ExchangeSpec(
                    edge=f"{'join' if kind == 'join' else 'src'}:"
                         f"{table}>{j['name']}",
                    kind=kind, table=table, key_col=key_col,
                    mode=mode, job=j["name"],
                ))
        # -- attach edges (MV-on-MV over a partitioned upstream) ------
        for j in jobs:
            for up, down in j.get("attach_edges", ()):
                ch.specs.append(ExchangeSpec(
                    edge=f"attach:{up}>{down}", kind="attach",
                    table=None, key_col=None, mode="local",
                    job=j["name"],
                ))
        return ch
