"""Exchange-lite: the cluster shuffle plane (ISSUE 11).

``planner`` compiles the static exchange choreography (which worker
ships which vnodes' rows to which peer on which edge) at placement and
scale time; ``shuffle`` executes it per chunk over the position-
stamped idempotent peer-batch protocol.  See ARCHITECTURE.md
"Exchange plane: Exchange-lite".
"""

from risingwave_tpu.cluster.exchange.planner import (  # noqa: F401
    Choreography,
    ExchangePlanner,
    ExchangeSpec,
)
from risingwave_tpu.cluster.exchange.shuffle import (  # noqa: F401
    ShuffleService,
    vnodes_of_rows,
)
