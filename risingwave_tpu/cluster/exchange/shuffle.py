"""ShuffleService: execute the compiled choreography per chunk.

The data-plane half of Exchange-lite (``planner.py`` is the control
half).  One instance rides each ComputeWorker and does three things:

- **leader slicing** — ``route_batch`` hash-partitions one ingest
  batch by distribution-key vnode ONCE (numpy, the same
  ``hash64_columns`` mix as the device state tables) and produces one
  position-stamped sparse payload per peer: the peer's owned rows
  plus the batch's full vnode log, so every host always knows which
  global positions belong to whom even for rows it never stored;
- **receiver apply** — ``apply_batch`` merges a sparse payload into
  the local table history (placeholder-padded to GLOBAL positions, so
  source cursors and round fences stay in the one global domain the
  PR-7 handover protocol already aligns);
- **repair slicing** — ``slice_history`` re-cuts any historical range
  for any vnode set (gap repair at the round fence, gained-vnode
  backfill after a repartition, standby promotion).

Byte/row/batch counters accumulate per EDGE label and are exported as
``cluster_exchange_{rows,bytes,batches}_total{edge=...}`` plus a
per-batch latency histogram — the observability the chaos schedules
assert on.
"""

from __future__ import annotations

import base64
import threading
import time

import numpy as np

from risingwave_tpu.cluster.exchange.planner import Choreography


def pack_vnodes(vns) -> str:
    """Base64-packed vnode log (one byte per position; rings ≤ 256).
    A 50k-row batch's log is ONE json string token instead of 50k
    number tokens — json decode goes from tens of ms to noise."""
    return base64.b64encode(bytes(int(v) & 0xFF for v in vns)).decode()


def unpack_vnodes(payload: dict) -> list[int]:
    s = payload.get("vn64")
    if s is not None:
        return list(base64.b64decode(s))
    return [int(v) for v in payload.get("vnodes") or ()]


def vnodes_of_rows(rows: list, key_col: int, n_vnodes: int) -> list[int]:
    """Host vnode of each row's key column — numpy end to end (one
    hash per batch, computed at the ingest leader), bit-identical to
    the device gate's ``vnodes_of_ints`` because both compute the
    SAME splitmix mix over the int64 payload (``hash64_i64_host`` is
    the numpy twin of ``hash64_columns``, equality asserted in
    tests).  ``None`` keys hash as 0, matching ``split_col``'s zeroed
    payload on the device path."""
    from risingwave_tpu.common.hash import hash64_i64_host

    vals = np.asarray(
        [0 if r[key_col] is None else int(r[key_col]) for r in rows],
        np.int64,
    )
    h = hash64_i64_host(vals)
    return [int(v) for v in (h % np.uint64(n_vnodes))]


class ShuffleService:
    """Per-worker executor of the exchange choreography."""

    def __init__(self, worker_id=None, metrics=None):
        self.worker_id = worker_id
        self.metrics = metrics
        self.choreography = Choreography()
        self._lock = threading.Lock()
        #: per-edge counters (host-side mirror of the metric series)
        self.rows_out: dict[str, int] = {}
        self.bytes_out: dict[str, int] = {}
        self.batches_out: dict[str, int] = {}

    # -- choreography ---------------------------------------------------
    def update(self, doc: dict | Choreography) -> None:
        ch = doc if isinstance(doc, Choreography) \
            else Choreography.from_doc(doc)
        with self._lock:
            if ch.version >= self.choreography.version:
                self.choreography = ch

    def table_plan(self, table: str) -> dict | None:
        with self._lock:
            return self.choreography.tables.get(table)

    def shuffled_tables(self) -> dict[str, dict]:
        with self._lock:
            return {t: e for t, e in self.choreography.tables.items()
                    if e["mode"] == "shuffle"}

    def edge_of(self, table: str) -> str:
        with self._lock:
            for s in self.choreography.specs:
                if s.table == table:
                    return s.edge
        return f"src:{table}"

    # -- leader slicing -------------------------------------------------
    def route_batch(self, table: str, seq: int, rows: list
                    ) -> dict[int, dict]:
        """Slice one ingest batch per the choreography: returns
        ``{worker_id: payload}`` for every OTHER host, where payload is

        - shuffle mode: ``{"seq", "end", "items": [[pos, row]...],
          "vnodes": [...]}`` — the peer's owned slice (plus the
        leader's own slice for the standby host) and the full
        position→vnode log of the batch;
        - replicate mode: ``{"seq", "rows": [...]}`` (the PR-7 wire
          format, unchanged)."""
        plan = self.table_plan(table)
        end = seq + len(rows)
        out: dict[int, dict] = {}
        if plan is None:
            return out
        if plan["mode"] != "shuffle" or plan["key_col"] is None:
            for w in plan["hosts"]:
                if w != self.worker_id:
                    out[w] = {"seq": seq, "rows": [list(r) for r in rows]}
            return out
        vns = vnodes_of_rows(rows, plan["key_col"], plan["n_vnodes"])
        own_of: dict[int, set] = {w: set(plan["slices"].get(w, ()))
                                  for w in plan["hosts"]}
        my = own_of.get(self.worker_id, set())
        standby = plan.get("standby")
        for w in plan["hosts"]:
            if w == self.worker_id:
                continue
            want = own_of[w]
            if w == standby:
                # the standby also carries the leader's slice: one
                # surviving copy of every row through a leader death
                want = want | my
            # positions are ELIDED from the wire: the receiver derives
            # them from the (byte-packed) vnode log + the covered-
            # vnode set — each row crosses once, no per-row position,
            # and the log is one string token
            out[w] = {"seq": seq, "end": end,
                      "vn64": pack_vnodes(vns),
                      "own": sorted(want),
                      "rows": [list(rows[i])
                               for i, v in enumerate(vns)
                               if v in want]}
        return out

    @staticmethod
    def unpack_rows(payload: dict) -> list:
        """Expand a positions-elided payload into ``(pos, row)``
        items (the receiver-side inverse of ``route_batch``)."""
        if "items" in payload:  # explicit-position form (repairs)
            return [(int(p), tuple(r)) for p, r in payload["items"]]
        seq = int(payload["seq"])
        want = {int(v) for v in payload.get("own", ())}
        rows = payload["rows"]
        out = []
        it = iter(rows)
        for i, v in enumerate(unpack_vnodes(payload)):
            if v in want:
                out.append((seq + i, tuple(next(it))))
        return out

    def slice_history(self, mgr, from_seq: int, to_seq: int | None,
                      vnodes, table: str) -> dict:
        """Re-cut a historical range for one vnode set (fence gap
        repair / gained-vnode backfill).  Positions the local history
        never stored (holes) are simply absent from ``items`` — the
        caller peer-fills from other hosts if its own completeness
        check still fails."""
        plan = self.table_plan(table)
        end = mgr.history_len() if to_seq is None \
            else min(int(to_seq), mgr.history_len())
        lo = int(from_seq)
        if plan is None or plan["key_col"] is None:
            rows = mgr.history_slice(lo, end)
            return {"seq": lo, "end": end,
                    "items": [[lo + i, r] for i, r in enumerate(rows)
                              if r is not None],
                    "vnodes": mgr.vnode_slice(lo, end)}
        want = {int(v) for v in vnodes}
        vns: list[int] = []
        rows_by_pos: dict[int, tuple] = {}
        unknown: list[tuple[int, tuple]] = []
        for pos in range(lo, end):
            row = mgr.history_row(pos)
            vn = mgr.vnode_at(pos)
            if vn is None and row is not None:
                unknown.append((pos, row))
            vns.append(-1 if vn is None else int(vn))
            if row is not None:
                rows_by_pos[pos] = row
        if unknown:
            # one batched hash for every un-stamped position (rows
            # ingested before the shuffle plan existed)
            hashed = vnodes_of_rows([r for _, r in unknown],
                                    plan["key_col"], plan["n_vnodes"])
            for (pos, _), v in zip(unknown, hashed):
                vns[pos - lo] = int(v)
        items = [[pos, list(rows_by_pos[pos])]
                 for i, pos in enumerate(range(lo, end))
                 if pos in rows_by_pos and vns[i] in want]
        return {"seq": lo, "end": end, "items": items, "vnodes": vns}

    # -- receiver -------------------------------------------------------
    @classmethod
    def apply_batch(cls, mgr, payload: dict) -> int:
        """Merge one sparse payload into a table manager (idempotent;
        fills placeholder holes; refuses gaps like ``insert_at``)."""
        return mgr.insert_sparse(
            int(payload["seq"]), int(payload["end"]),
            cls.unpack_rows(payload),
            unpack_vnodes(payload),
        )

    # -- observability --------------------------------------------------
    @staticmethod
    def _payload_size(payload: dict) -> int:
        """Approximate wire bytes without re-serializing (the RPC
        layer already pays one json.dumps; a second one per send was
        measurable on the ingest hot path).  Counts ~12 bytes per
        scalar + framing — close enough for a byte-rate counter."""
        items = payload.get("items")
        if items is not None:
            per_row = 12 * (1 + (len(items[0][1]) if items else 0))
            return 64 + per_row * len(items) \
                + 4 * len(payload.get("vnodes", ()))
        rows = payload.get("rows", ())
        return 64 + 12 * len(rows) * (len(rows[0]) if rows else 1) \
            + 4 * len(payload.get("vnodes", ())) \
            + len(payload.get("vn64", ""))

    def note_send(self, edge: str, payload: dict,
                  elapsed_s: float) -> None:
        rows = len(payload.get("items", payload.get("rows", ())))
        size = self._payload_size(payload)
        with self._lock:
            self.rows_out[edge] = self.rows_out.get(edge, 0) + rows
            self.bytes_out[edge] = self.bytes_out.get(edge, 0) + size
            self.batches_out[edge] = self.batches_out.get(edge, 0) + 1
        if self.metrics is not None:
            self.metrics.inc("cluster_exchange_rows_total", rows,
                             edge=edge)
            self.metrics.inc("cluster_exchange_bytes_total", size,
                             edge=edge)
            self.metrics.inc("cluster_exchange_batches_total",
                             edge=edge)
            self.metrics.observe("cluster_exchange_batch_seconds",
                                 elapsed_s, edge=edge)

    def stats(self) -> dict:
        with self._lock:
            return {
                "version": self.choreography.version,
                "rows_out": dict(self.rows_out),
                "bytes_out": dict(self.bytes_out),
                "batches_out": dict(self.batches_out),
            }

    def timed(self):
        """Tiny perf_counter context for send timing."""
        class _T:
            def __enter__(s):
                s.t0 = time.perf_counter()
                return s

            def __exit__(s, *exc):
                s.dt = time.perf_counter() - s.t0
        return _T()
