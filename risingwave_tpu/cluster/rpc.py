"""Line-delimited JSON-RPC over localhost TCP (stdlib only).

Reference counterpart: the tonic gRPC mesh between the four node
roles (``src/rpc_client``, proto/*.proto — MetaClient, StreamClient,
ComputeClient).  The reference's service surface is wide because every
subsystem speaks protobuf; this repo's control plane needs exactly one
transport primitive — *call a named method on a peer and get a JSON
answer* — so the whole layer is a newline-framed JSON request/response
protocol any language (or ``nc``) can speak:

    -> {"id": 1, "method": "heartbeat", "params": {"worker_id": 2}}
    <- {"id": 1, "result": {"ok": true, "cluster_epoch": 7}}
    <- {"id": 1, "error": "unknown worker 2"}          (on failure)

Server: a threaded TCP server dispatching ``rpc_<method>`` attributes
on a handler object (one thread per connection, many concurrent
callers).  Client: one persistent connection, serialized calls,
transparent reconnect-once on a broken socket.

Error split (the failover-correctness contract): ``RpcError`` means
the PEER ANSWERED with a failure — the application decision is final
(an unknown MV stays unknown on retry).  ``ConnectionError``/
``OSError`` means the peer is unreachable — the caller may retry
against a reassigned owner.  MetaService.serve leans on exactly this
split to keep serving reads error-free across a worker kill.

Robustness contracts added for the chaos fabric (common/faults.py):

- The client consults the process-global ``FaultFabric`` once per
  logical call under the label ``src>dst/method`` — deterministic
  drops, delays, lost responses and one-way partitions inject at this
  seam, surfacing as ``ConnectionError`` exactly like real ones.
- The server answers malformed frames (junk bytes, truncated JSON,
  non-object requests, oversized payloads) with an error frame — the
  CLIENT gets ``RpcError`` — and keeps serving the connection (line
  framing resyncs at the next newline); garbage can never take down
  the accept loop.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading

from risingwave_tpu.common.faults import get_fabric
from risingwave_tpu.common.trace import GLOBAL_TRACE

#: hard cap per frame; a peer streaming an unbounded line would pin
#: server memory (serve results stay far below this)
MAX_FRAME_BYTES = 64 * 1024 * 1024


class RpcError(RuntimeError):
    """The remote handler raised: the call was delivered and REFUSED
    (retrying the same call cannot succeed)."""


def _json_default(o):
    """Serialize numpy scalars (engine rows carry them) and stray
    bytes; anything else is a programming error worth surfacing."""
    if hasattr(o, "item"):
        return o.item()
    if isinstance(o, bytes):
        return o.decode("utf-8", errors="replace")
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


def _dumps(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":"),
                      default=_json_default).encode() + b"\n"


class _RpcHandler(socketserver.StreamRequestHandler):
    def _respond(self, resp: dict) -> bool:
        try:
            self.wfile.write(_dumps(resp))
            self.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionError, OSError, ValueError):
            return False

    def handle(self):
        target = self.server.target
        while True:
            line = self.rfile.readline(MAX_FRAME_BYTES)
            if not line:
                return
            if len(line) >= MAX_FRAME_BYTES and not line.endswith(b"\n"):
                # oversized frame: discard through the next newline so
                # the connection resyncs, then answer the error
                while True:
                    rest = self.rfile.readline(MAX_FRAME_BYTES)
                    if not rest:
                        return
                    if rest.endswith(b"\n"):
                        break
                if not self._respond({"id": None,
                                      "error": "oversized rpc frame"}):
                    return
                continue
            try:
                req = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError) as e:
                # junk/torn frame: the CLIENT gets the error; line
                # framing resyncs at the newline we just consumed
                if not self._respond({"id": None,
                                      "error": f"malformed frame: {e}"}):
                    return
                continue
            if not isinstance(req, dict):
                if not self._respond({
                        "id": None,
                        "error": "malformed frame: request must be an "
                                 "object"}):
                    return
                continue
            rid = req.get("id")
            method = req.get("method", "")
            params = req.get("params") or {}
            fn = getattr(target, f"rpc_{method}", None) \
                if isinstance(method, str) else None
            if fn is None:
                resp = {"id": rid, "error": f"unknown method {method!r}"}
            elif not isinstance(params, dict):
                resp = {"id": rid,
                        "error": "malformed frame: params must be an "
                                 "object"}
            else:
                try:
                    # a "trace" key on the frame carries the caller's
                    # (trace_id, span_id): adopt it for this handler so
                    # spans recorded inside parent across the process
                    # boundary (no-op when tracing is off or absent)
                    with GLOBAL_TRACE.activate(req.get("trace")):
                        resp = {"id": rid, "result": fn(**params)}
                except Exception as e:  # handler errors travel back
                    resp = {"id": rid,
                            "error": f"{type(e).__name__}: {e}"}
            if not self._respond(resp):
                return


class RpcServer(socketserver.ThreadingTCPServer):
    """Serve ``rpc_*`` methods of ``target`` on (host, port); port 0
    binds an ephemeral port (read it back from ``.port``)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, target, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _RpcHandler)
        self.target = target
        self.host = host
        self.port = self.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "RpcServer":
        self._thread = threading.Thread(
            target=self.serve_forever, name=f"rpc-{self.port}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class _RpcChannel:
    """One pooled connection: its own socket, file, and lock."""

    __slots__ = ("host", "port", "timeout", "lock", "_sock", "_file",
                 "_next_id")

    def __init__(self, host: str, port: int, timeout: float):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._file = None
        self._next_id = 1

    def connect(self):
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self._file = self._sock.makefile("rwb")

    def roundtrip(self, payload: bytes) -> dict:
        if self._sock is None:
            self.connect()
        self._file.write(payload)
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("rpc peer closed the connection")
        return json.loads(line)

    def close(self) -> None:
        try:
            if self._file is not None:
                self._file.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._file = None


class RpcClient:
    """Persistent connection(s) to a peer.  ``pool=1`` (the default)
    keeps the original shape: one socket, calls serialized on its lock
    (the meta→worker control channel is low-rate by design).  A pool
    > 1 lets CONCURRENT callers overlap round-trips on independent
    sockets — the meta's serving-read router uses this so reader
    threads aren't serialized behind one in-flight batch frame.

    ``src``/``dst`` name the two endpoints for the fault fabric: every
    call is matched under the label ``src>dst/method``, which is what
    makes one-way partitions expressible (meta>worker1 dark while
    worker1>meta flows)."""

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 src: str = "", dst: str = "", pool: int = 1):
        self.host = host
        self.timeout = timeout
        self.src = src or "client"
        self.dst = dst or f"{host}:{port}"
        self._chans = [_RpcChannel(host, port, timeout)
                       for _ in range(max(1, int(pool)))]
        self._rr = 0
        self._port = port

    @property
    def port(self) -> int:
        return self._port

    @port.setter
    def port(self, value: int) -> None:
        """Re-point the client (tests move a client to a restarted
        peer's fresh port): every pooled channel reconnects lazily at
        the new address."""
        self._port = int(value)
        for ch in self._chans:
            with ch.lock:
                ch.close()
                ch.port = self._port

    def _acquire(self) -> _RpcChannel:
        """A free channel if any lock is immediately available, else
        block on the round-robin next (fair under saturation)."""
        for ch in self._chans:
            if ch.lock.acquire(blocking=False):
                return ch
        self._rr = (self._rr + 1) % len(self._chans)
        ch = self._chans[self._rr]
        ch.lock.acquire()
        return ch

    def call(self, method: str, **params):
        """Invoke one remote method.  Raises ``RpcError`` for remote
        handler failures, ``ConnectionError``/``OSError`` when the
        peer is unreachable (one silent reconnect is attempted for
        idle-dropped sockets).  The fault fabric injects ONCE per
        logical call (never again on the internal reconnect resend)."""
        ch = self._acquire()
        try:
            fabric = get_fabric()
            sever_after = None
            if fabric is not None:
                sever_after = fabric.rpc_before_send(
                    f"{self.src}>{self.dst}/{method}"
                )  # raises FaultInjected for drops
            rid = ch._next_id
            ch._next_id += 1
            frame = {"id": rid, "method": method, "params": params}
            tctx = GLOBAL_TRACE.current() if GLOBAL_TRACE.enabled \
                else None
            if tctx is not None:
                frame["trace"] = list(tctx)
            payload = _dumps(frame)
            if sever_after is not None:
                # error_after_send: the request IS delivered and
                # executed, but the response is lost with the socket —
                # the probe for non-idempotent handlers
                if ch._sock is None:
                    ch.connect()
                ch._file.write(payload)
                ch._file.flush()
                ch.close()
                raise ConnectionError(
                    f"injected rpc error-after-send: "
                    f"{self.src}>{self.dst}/{method}"
                )
            try:
                resp = ch.roundtrip(payload)
            except (ConnectionError, OSError, json.JSONDecodeError):
                ch.close()
                ch.connect()
                resp = ch.roundtrip(payload)
            if resp.get("error") is not None:
                raise RpcError(resp["error"])
            return resp.get("result")
        finally:
            ch.lock.release()

    def close(self) -> None:
        for ch in self._chans:
            with ch.lock:
                ch.close()


def parse_addr(addr: str) -> tuple[str, int]:
    """'host:port' → (host, port) for CLI flags."""
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)
