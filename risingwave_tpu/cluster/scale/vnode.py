"""The vnode keyspace: consistent hashing + the minimal rebalance.

Reference counterpart: ``VirtualNode`` (src/common/src/hash/consistent_
hash/vnode.rs) and the meta's ``WorkerMapping`` rebalance
(src/meta/src/stream/scale.rs:224) — a job's keyed state is
partitioned over a fixed ring of N virtual nodes; capacity changes
remap *vnodes to workers*, never keys to vnodes, so scaling N→M moves
only ``|delta targets|`` vnodes and the state behind them.

Every hash here routes through ``common.hash.hash64_columns`` — the
SAME mix the device state tables use — so a row's vnode computed at
the chunk gate, a group's vnode computed from a checkpoint slice, and
an MV row's vnode computed at serving-read time can never disagree.
The map itself is a plain ``list[int]`` of length ``n_vnodes`` whose
entries are worker ids; all functions are pure and deterministic
(sorted-worker order, index order), so every process derives the same
map from the same inputs.
"""

from __future__ import annotations

import numpy as np

#: the default ring size (ref VirtualNode::COUNT is 256; 64 keeps the
#: per-vnode slices chunky on small test tables)
N_VNODES_DEFAULT = 64


def vnodes_of_ints(col, n_vnodes: int):
    """``int32 [cap]`` vnode of each value of an integer key column.

    Accepts host numpy or device jnp arrays; the hash is
    ``hash64_columns`` — identical to the state tables' slot hashing —
    so chunk-gate routing, checkpoint slicing, and read filtering all
    agree bit-for-bit.  Distribution keys are restricted to
    NOT NULL integer-family columns (engine eligibility), which keeps
    host row values and raw stored values in the same hash domain.
    """
    import jax.numpy as jnp

    from risingwave_tpu.common.hash import hash64_columns

    h = hash64_columns([jnp.asarray(col).astype(jnp.int64)])
    return (h % np.uint64(n_vnodes)).astype(jnp.int32)


def vnode_member_mask(vnodes, n_vnodes: int):
    """``bool [n_vnodes]`` membership mask of a vnode set (device)."""
    import jax.numpy as jnp

    mask = jnp.zeros((n_vnodes,), jnp.bool_)
    vn = sorted(int(v) for v in vnodes)
    if not vn:
        return mask
    return mask.at[jnp.asarray(vn, jnp.int32)].set(True)


def _targets(workers: list[int], n_vnodes: int) -> dict[int, int]:
    """Per-worker vnode quota: ⌊n/W⌋ (+1 for the first ``n mod W``
    workers in ascending id order) — balanced within ±1 by
    construction, deterministic across processes."""
    ws = sorted(workers)
    base, extra = divmod(n_vnodes, len(ws))
    return {w: base + (1 if i < extra else 0) for i, w in enumerate(ws)}


def initial_map(workers: list[int], n_vnodes: int) -> list[int]:
    """First assignment: round-robin over sorted workers (every worker
    lands within ±1 of its quota)."""
    ws = sorted(workers)
    return [ws[v % len(ws)] for v in range(n_vnodes)]


def rebalance(old: list[int] | None, workers: list[int],
              n_vnodes: int) -> list[int]:
    """Remap the ring onto ``workers`` moving the MINIMAL vnode set.

    Each surviving worker keeps its current vnodes up to its new quota
    (in vnode-index order); only the excess — plus every vnode whose
    owner left — is reassigned, in index order, to the first
    under-quota worker in ascending id order.  Minimal by construction:
    a worker over quota must shed exactly ``count - quota`` vnodes and
    an under-quota worker must gain exactly ``quota - count``; nothing
    else moves.  Pure function of (old, workers): every process
    computes the same map."""
    if not workers:
        raise ValueError("rebalance needs at least one worker")
    if old is None:
        return initial_map(workers, n_vnodes)
    if len(old) != n_vnodes:
        raise ValueError(
            f"map has {len(old)} vnodes, expected {n_vnodes}"
        )
    quota = _targets(workers, n_vnodes)
    kept: dict[int, int] = {w: 0 for w in quota}
    new = list(old)
    pending: list[int] = []
    for v, w in enumerate(old):
        if w in quota and kept[w] < quota[w]:
            kept[w] += 1
        else:
            pending.append(v)
    order = sorted(quota)
    for v in pending:
        for w in order:
            if kept[w] < quota[w]:
                new[v] = w
                kept[w] += 1
                break
    return new


def moved_vnodes(old: list[int],
                 new: list[int]) -> dict[tuple[int, int], list[int]]:
    """``{(src_worker, dst_worker): [vnode, ...]}`` of every vnode that
    changed owner (the handover work list)."""
    out: dict[tuple[int, int], list[int]] = {}
    for v, (a, b) in enumerate(zip(old, new)):
        if a != b:
            out.setdefault((a, b), []).append(v)
    return out


def owned_vnodes(vmap: list[int], worker_id: int) -> list[int]:
    return [v for v, w in enumerate(vmap) if w == worker_id]
