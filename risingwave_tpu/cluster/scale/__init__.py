"""Scale-lite: the elastic vnode scale plane.

Reference counterpart: the meta's scale/recovery plane (PAPER.md §1)
— a consistent-hash virtual-node keyspace owned by the meta, with
``risectl`` rescheduling moving vnodes (and the state behind them)
between compute nodes through a checkpoint epoch
(src/meta/src/stream/scale.rs).  *Suki* (PAPERS.md) is the exemplar
for the choreographed data path: once the meta has placed the
partitions, per-chunk data flows worker↔worker over peer channels and
the meta keeps only control traffic.

Modules:

- ``vnode``    — the vnode keyspace: deterministic hashing, the
  vnode→worker map, and the minimal-movement rebalance;
- ``gate``     — the traceable per-partition row filter (each
  partition of a job masks source rows to its owned vnodes);
- ``handover`` — per-vnode checkpoint slices + live-state transplant
  (the state that follows moved vnodes across workers).
"""

from risingwave_tpu.cluster.scale.vnode import (  # noqa: F401
    N_VNODES_DEFAULT,
    initial_map,
    moved_vnodes,
    owned_vnodes,
    rebalance,
    vnode_member_mask,
    vnodes_of_ints,
)
