"""VnodeGateExecutor: the per-partition row filter.

Reference counterpart: the vnode bitmap every stateful actor holds
(``ActorMapping``/vnode bitmaps, src/common/src/hash/consistent_hash/
mapping.rs) — an actor only processes the keys whose vnodes it owns.
Here a *partition* of a streaming job is a full replica of the job's
fragment on one worker, fed by the same deterministic source stream;
this gate sits directly before the keyed (agg) executor and narrows
the validity mask to rows whose distribution-key vnode the partition
owns.

TPU-first shape: the owned-vnode set is the executor's STATE (a
``bool [n_vnodes]`` membership mask), not a captured constant — a
scale operation updates the mask array in place and the compiled
fragment programs never retrace.  The gate itself is one hash + one
gather per chunk and fuses into the fragment step program, so the
traceable fused multi-chunk dispatch path survives partitioning.
"""

from __future__ import annotations

import jax.numpy as jnp

from risingwave_tpu.common.chunk import (
    Chunk,
    OP_DELETE,
    OP_INSERT,
    OP_UPDATE_DELETE,
    OP_UPDATE_INSERT,
    split_col,
)
from risingwave_tpu.common.types import Schema
from risingwave_tpu.cluster.scale.vnode import (
    vnode_member_mask,
    vnodes_of_ints,
)
from risingwave_tpu.expr.node import Expr
from risingwave_tpu.stream.executor import Executor


class VnodeGateExecutor(Executor):
    """Mask rows to the partition's owned vnodes (state = the mask).

    Exchange-lite makes this gate the correctness ASSERT of the
    shuffled ingest path, not its workhorse: sliced delivery + the
    reader-side vnode filter mean every row reaching the gate is
    already owned, and the gate's second state leaf — a device
    ``dropped`` counter — proves it (``scale_stress --assert`` and the
    shuffle chaos schedules require it to stay ZERO on shuffled
    edges).  On replicate-mode edges the gate still filters, exactly
    the PR-7 behavior.
    """

    emits_on_apply = True
    emits_on_flush = False

    def __init__(self, in_schema: Schema, key_expr,
                 n_vnodes: int):
        super().__init__(in_schema)
        # one routing key (the agg distribution key) or several (a
        # join side routes by its first equi key; the list form keeps
        # the door open for composite routing) — vnode = hash of the
        # FIRST expr, matching the host-side shuffle slicing
        exprs = key_expr if isinstance(key_expr, (list, tuple)) \
            else [key_expr]
        self.key_exprs: tuple[Expr, ...] = tuple(exprs)
        self.key_expr = self.key_exprs[0]
        self.n_vnodes = n_vnodes

    def init_state(self):
        # owns everything until the control plane narrows it — a
        # single-partition job behaves exactly like an unpartitioned
        # one.  State = (membership mask, dropped-row audit counter).
        return (jnp.ones((self.n_vnodes,), jnp.bool_),
                jnp.zeros((), jnp.int64))

    def make_mask(self, vnodes):
        """Device membership mask for ``set_job_vnodes`` state swaps."""
        return vnode_member_mask(vnodes, self.n_vnodes)

    def apply(self, state, chunk: Chunk):
        # dual-form state: a bare mask (legacy callers/tests) or the
        # (mask, dropped) pair the partitioned runtime threads
        if isinstance(state, tuple):
            mask, dropped = state
        else:
            mask, dropped = state, None
        key, null = split_col(self.key_expr.eval(chunk))
        vn = vnodes_of_ints(key, self.n_vnodes)
        keep = mask[vn] & chunk.valid
        if null is not None:
            # eligibility requires a NOT NULL dist key; a runtime NULL
            # (never expected) routes to vnode-of-zero-payload, which
            # the zeroed split_col payload already produces
            pass
        # Update-pair degradation, exactly like FilterExecutor: a U-/U+
        # pair whose sides land in different vnodes degrades to the
        # surviving side's plain Insert/Delete
        is_ud = chunk.ops == OP_UPDATE_DELETE
        is_ui = chunk.ops == OP_UPDATE_INSERT
        partner_keep_for_ud = jnp.roll(keep, -1)
        partner_keep_for_ui = jnp.roll(keep, 1)
        ops = chunk.ops
        ops = jnp.where(is_ud & keep & ~partner_keep_for_ud,
                        OP_DELETE, ops)
        ops = jnp.where(is_ui & keep & ~partner_keep_for_ui,
                        OP_INSERT, ops)
        out = Chunk(chunk.columns, ops, keep, chunk.schema)
        if dropped is None:
            return mask, out
        dropped = dropped + jnp.sum(
            (chunk.valid & ~keep).astype(jnp.int64)
        )
        return (mask, dropped), out

    def __repr__(self) -> str:
        return f"VnodeGateExecutor(n={self.n_vnodes})"
