"""Online state handover: per-vnode checkpoint slices + transplant.

Reference counterpart: the reschedule plan of ``scale.rs`` — when a
vnode moves, the state *behind* it (agg groups, MV rows keyed in that
vnode) moves with it, anchored at a checkpoint epoch so the transfer
is exact.

Mechanics here (cluster/meta_service drives the protocol):

1. the meta seals a round whose checkpoints are DURABLE on every
   partition (the handover epoch);
2. the recipient loads each donor partition's checkpoint *at that
   epoch* from the SHARED checkpoint store — state never crosses an
   RPC, only the moved keys' slices leave disk;
3. ``slice_partition_states`` extracts exactly the moved vnodes'
   entries (group keys + every per-slot state array) — the "only
   moved vnodes transfer" contract is structural, not best-effort;
4. ``clear_vnodes`` tombstones any stale entries the recipient still
   holds for the gained vnodes (a worker regaining vnodes it donated
   earlier refreshes, never resurrects);
5. ``transplant`` bulk find-or-claims the moved keys in the live
   tables (``HashTable.lookup_or_insert`` over the whole slice) and
   scatters the donor's per-slot arrays at the claimed slots.

Eligible state shapes: ``HashAggExecutor`` (prims / row_count / prev
snapshot / emitted / dirty / minput buckets — everything slot-aligned)
and ``MaterializeExecutor`` (pk table + dense value columns).  The
engine's eligibility gate guarantees no DISTINCT dedup tables and an
empty spill ring; both are asserted loudly here anyway.

Exchange-lite (round 14) extends the same contract to partitioned
JOIN jobs and MV-on-MV DAGs: ``partition_sites`` walks a ``DagJob``'s
node tree and yields every sliceable state (aggs, materializes, and
dense hash-join *sides* — key table + [size, B] row buckets + per-key
degree counters, moved as whole key entries so the bucket layout, and
therefore the emission order, is preserved bit-for-bit).  Every keyed
state's LEADING key lives in the same ``hash64`` vnode domain as the
routing key — the engine's partition eligibility enforces that at
adoption, which is what lets one vnode set slice the whole tree.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.cluster.scale.vnode import (
    vnode_member_mask,
    vnodes_of_ints,
)
from risingwave_tpu.common.chunk import NCol, StrCol
from risingwave_tpu.state.hash_table import gather_key
from risingwave_tpu.stream.hash_agg import HashAggExecutor
from risingwave_tpu.stream.materialize import (
    MaterializeExecutor,
    MvState,
    _scatter_col,
)


def _to_dev(col):
    """Host slice column → device (NCol/StrCol aware)."""
    if isinstance(col, NCol):
        return NCol(_to_dev(col.data), jnp.asarray(col.null))
    if isinstance(col, StrCol):
        return StrCol(jnp.asarray(col.data), jnp.asarray(col.lens))
    return jnp.asarray(col)


def _dist_payload(col):
    """Raw integer payload of the distribution key column (the
    eligibility gate guarantees NOT NULL integer family)."""
    if isinstance(col, NCol):
        return col.data
    return col


def _entry_mask(table, vnodes, n_vnodes) -> np.ndarray:
    """Host ``bool [size]``: occupied slots whose key falls in the
    vnode set."""
    occ = np.asarray(table.occupied)
    vn = np.asarray(vnodes_of_ints(
        _dist_payload(table.key_cols[0]), n_vnodes
    ))
    member = np.zeros((n_vnodes,), bool)
    member[[int(v) for v in vnodes]] = True
    return occ & member[vn]


def _assert_plain_agg(ex: HashAggExecutor, state) -> None:
    if state.distinct_tables:
        raise RuntimeError(
            "vnode handover over a DISTINCT aggregation (dedup tables "
            "are not sliceable): not scale-eligible"
        )
    spill = getattr(state, "spill_count", ())
    if not isinstance(spill, tuple) and int(np.asarray(spill)) != 0:
        raise RuntimeError(
            "vnode handover with rows in the spill ring — drain first"
        )


# -- slice (donor checkpoint → moved entries) ---------------------------
def slice_partition_states(executors, states, vnodes,
                           n_vnodes: int) -> dict[int, dict]:
    """Extract the moved vnodes' entries from a (host) checkpoint
    state tree: ``{executor_idx: slice}`` for every keyed executor.

    Works on the numpy trees ``CheckpointStore.load`` returns (and on
    device trees — gathers go through numpy either way)."""
    out: dict[int, dict] = {}
    for i, ex in enumerate(executors):
        st = states[i]
        if isinstance(ex, HashAggExecutor):
            _assert_plain_agg(ex, st)
            take = _entry_mask(st.table, vnodes, n_vnodes)
            idx = np.nonzero(take)[0]
            out[i] = {
                "kind": "agg",
                "n": int(idx.shape[0]),
                "keys": [gather_key(np.asarray(c) if not isinstance(
                    c, (NCol, StrCol)) else c, idx)
                    for c in st.table.key_cols],
                "prims": [np.asarray(p)[idx] for p in st.prims],
                "prev_prims": [np.asarray(p)[idx]
                               for p in st.prev_prims],
                "row_count": np.asarray(st.row_count)[idx],
                "prev_row_count": np.asarray(st.prev_row_count)[idx],
                "dirty": np.asarray(st.dirty)[idx],
                "emitted": np.asarray(st.emitted)[idx],
                "minput_vals": [np.asarray(v)[idx]
                                for v in st.minput_vals],
                "minput_occ": [np.asarray(o)[idx]
                               for o in st.minput_occ],
            }
        elif isinstance(ex, MaterializeExecutor):
            take = _entry_mask(st.table, vnodes, n_vnodes)
            idx = np.nonzero(take)[0]
            out[i] = {
                "kind": "mv",
                "n": int(idx.shape[0]),
                "keys": [gather_key(np.asarray(c) if not isinstance(
                    c, (NCol, StrCol)) else c, idx)
                    for c in st.table.key_cols],
                "values": [gather_key(v if isinstance(v, (NCol, StrCol))
                                      else np.asarray(v), idx)
                           for v in st.values],
            }
    return out


# -- clear (recipient live state: evict stale entries in gained set) ----
def clear_vnodes(executors, states, vnodes, n_vnodes: int):
    """Tombstone every live entry in the given vnode set (stale state
    from an earlier ownership must never shadow the donor's current
    slice).  Returns (states', cleared_entries)."""
    new_states = list(states)
    cleared = 0
    member = vnode_member_mask(vnodes, n_vnodes)
    for i, ex in enumerate(executors):
        st = states[i]
        if isinstance(ex, HashAggExecutor):
            vn = vnodes_of_ints(
                _dist_payload(st.table.key_cols[0]), n_vnodes
            )
            stale = st.table.occupied & member[vn]
            cleared += int(jnp.sum(stale))
            new_states[i] = st._replace(
                table=st.table.clear_where(stale),
                row_count=jnp.where(stale, 0, st.row_count),
                prev_row_count=jnp.where(stale, 0, st.prev_row_count),
                dirty=st.dirty & ~stale,
                emitted=st.emitted & ~stale,
                minput_occ=tuple(o & ~stale[:, None]
                                 for o in st.minput_occ),
            )
        elif isinstance(ex, MaterializeExecutor):
            vn = vnodes_of_ints(
                _dist_payload(st.table.key_cols[0]), n_vnodes
            )
            stale = st.table.occupied & member[vn]
            cleared += int(jnp.sum(stale))
            new_states[i] = MvState(
                st.table.clear_where(stale), st.values, st.overflow
            )
    return tuple(new_states), cleared


# -- DagJob partitions: joins + MV-on-MV trees --------------------------
def partition_sites(job) -> list[tuple]:
    """Every sliceable keyed state of a partitioned job as
    ``(path, kind, executor)``: path indexes the (possibly nested)
    state tree — ``(i,)`` for a linear StreamingJob executor,
    ``(node, exec)`` for a DagJob fragment executor, ``(node,)`` for a
    JoinNode."""
    from risingwave_tpu.stream.dag import DagJob, JoinNode

    sites: list[tuple] = []
    if not isinstance(job, DagJob):
        for i, ex in enumerate(job.fragment.executors):
            if isinstance(ex, (HashAggExecutor, MaterializeExecutor)):
                sites.append(((i,), "agg" if isinstance(
                    ex, HashAggExecutor) else "mv", ex))
        return sites
    for ni, node in enumerate(job.nodes):
        if node is None:
            continue
        if isinstance(node, JoinNode):
            sites.append(((ni,), "join", node.join))
            continue
        for ei, ex in enumerate(node.fragment.executors):
            if isinstance(ex, HashAggExecutor):
                sites.append(((ni, ei), "agg", ex))
            elif isinstance(ex, MaterializeExecutor):
                sites.append(((ni, ei), "mv", ex))
    return sites


def _tree_get(states, path):
    st = states
    for i in path:
        st = st[i]
    return st


def _tree_set(states, path, value):
    if not path:
        return value
    lst = list(states)
    lst[path[0]] = _tree_set(states[path[0]], path[1:], value)
    return tuple(lst)


def _scatter_bucket(store, slots, vals):
    """Write whole [n, B] bucket rows at entry ``slots`` (NCol/StrCol
    aware — the inverse of ``hash_join._gather_bucket``)."""
    if isinstance(vals, NCol):
        return NCol(_scatter_bucket(store.data, slots, vals.data),
                    store.null.at[slots].set(vals.null, mode="drop"))
    if isinstance(vals, StrCol):
        return StrCol(store.data.at[slots].set(vals.data, mode="drop"),
                      store.lens.at[slots].set(vals.lens, mode="drop"))
    return store.at[slots].set(jnp.asarray(vals), mode="drop")


def _gather_host_bucket(store, idx):
    """[size, B, ...] host-gathered at idx -> [n, B, ...]."""
    if isinstance(store, NCol):
        return NCol(_gather_host_bucket(store.data, idx),
                    np.asarray(store.null)[idx])
    if isinstance(store, StrCol):
        return StrCol(np.asarray(store.data)[idx],
                      np.asarray(store.lens)[idx])
    return np.asarray(store)[idx]


def _assert_dense_join(join, st) -> None:
    from risingwave_tpu.stream.hash_join import SideState

    for side_name in ("left", "right"):
        side = getattr(st, side_name)
        if not isinstance(side, SideState):
            raise RuntimeError(
                "vnode handover over a pool-storage join side "
                "(append-only pools are not sliceable): not "
                "scale-eligible"
            )


def _slice_join_side(side, vnodes, n_vnodes: int) -> dict:
    """Extract whole key entries (key + bucket rows + degree) whose
    FIRST join-key column's vnode moved."""
    take = _entry_mask(side.key_table, vnodes, n_vnodes)
    idx = np.nonzero(take)[0]
    return {
        "n": int(idx.shape[0]),
        "keys": [gather_key(c if isinstance(c, (NCol, StrCol))
                            else np.asarray(c), idx)
                 for c in side.key_table.key_cols],
        "rows": [_gather_host_bucket(r, idx) for r in side.rows],
        "occupied": np.asarray(side.occupied)[idx],
        "count": np.asarray(side.count)[idx],
    }


def _clear_join_side(side, member, n_vnodes: int):
    vn = vnodes_of_ints(_dist_payload(side.key_table.key_cols[0]),
                        n_vnodes)
    stale = side.key_table.occupied & member[vn]
    cleared = int(jnp.sum(stale))
    return side._replace(
        key_table=side.key_table.clear_where(stale),
        occupied=side.occupied & ~stale[:, None],
        count=jnp.where(stale, 0, side.count),
    ), cleared


def _transplant_join_side(side, sl: dict):
    n = sl["n"]
    if n == 0:
        return side, 0
    keys = [_to_dev(c) for c in sl["keys"]]
    valid = jnp.ones((n,), jnp.bool_)
    table, slots, _, overflow = side.key_table.lookup_or_insert(
        keys, valid
    )
    if bool(jnp.any(overflow & valid)):
        raise RuntimeError(
            f"vnode transplant overflowed a join key table ({n} "
            "entries) — increase table capacity"
        )
    return side._replace(
        key_table=table,
        rows=tuple(
            _scatter_bucket(store, slots, _to_dev(col))
            for store, col in zip(side.rows, sl["rows"])
        ),
        occupied=side.occupied.at[slots].set(
            _to_dev(sl["occupied"]), mode="drop"),
        count=side.count.at[slots].set(
            _to_dev(sl["count"]), mode="drop"),
    ), n


def slice_job_states(job, states, vnodes, n_vnodes: int) -> dict:
    """``slice_partition_states`` generalized over a partitioned job's
    (possibly nested) state tree; keys are state PATHS."""
    out: dict[tuple, dict] = {}
    for path, kind, ex in partition_sites(job):
        st = _tree_get(states, path)
        if kind == "join":
            _assert_dense_join(ex, st)
            left = _slice_join_side(st.left, vnodes, n_vnodes)
            right = _slice_join_side(st.right, vnodes, n_vnodes)
            out[path] = {"kind": "join", "left": left, "right": right,
                         "n": left["n"] + right["n"]}
        else:
            sl = slice_partition_states([ex], (st,), vnodes, n_vnodes)
            out[path] = sl[0]
    return out


def clear_job_vnodes(job, states, vnodes, n_vnodes: int):
    """``clear_vnodes`` over a partitioned job's state tree."""
    member = vnode_member_mask(vnodes, n_vnodes)
    cleared = 0
    for path, kind, ex in partition_sites(job):
        st = _tree_get(states, path)
        if kind == "join":
            _assert_dense_join(ex, st)
            left, c1 = _clear_join_side(st.left, member, n_vnodes)
            right, c2 = _clear_join_side(st.right, member, n_vnodes)
            states = _tree_set(states, path,
                               st._replace(left=left, right=right))
            cleared += c1 + c2
        else:
            new, c = clear_vnodes([ex], (st,), vnodes, n_vnodes)
            states = _tree_set(states, path, new[0])
            cleared += c
    return states, cleared


def transplant_job(job, states, slices: dict):
    """``transplant`` over a partitioned job's state tree (slices
    keyed by state path, as produced by ``slice_job_states``)."""
    sites = {path: (kind, ex) for path, kind, ex in
             partition_sites(job)}
    moved = 0
    for path, sl in slices.items():
        path = tuple(path)
        kind, ex = sites[path]
        st = _tree_get(states, path)
        if sl.get("kind") == "join":
            left, n1 = _transplant_join_side(st.left, sl["left"])
            right, n2 = _transplant_join_side(st.right, sl["right"])
            states = _tree_set(states, path,
                               st._replace(left=left, right=right))
            moved += n1 + n2
        else:
            new, n = transplant([ex], (st,), {0: sl})
            states = _tree_set(states, path, new[0])
            moved += n
    return states, moved


# -- transplant (moved entries → recipient live state) ------------------
def transplant(executors, states, slices: dict[int, dict]):
    """Merge donor slices into the live state tree; returns
    ``(states', entries_moved)``.  Raises loudly when the recipient
    table cannot claim a slot (undersized table — the overflow analog
    of the streaming path's loud counters)."""
    new_states = list(states)
    moved = 0
    for i, sl in slices.items():
        st = states[i]
        n = sl["n"]
        if n == 0:
            continue
        keys = [_to_dev(c) for c in sl["keys"]]
        valid = jnp.ones((n,), jnp.bool_)
        table, slots, _, overflow = st.table.lookup_or_insert(
            keys, valid
        )
        if bool(jnp.any(overflow & valid)):
            raise RuntimeError(
                f"vnode transplant overflowed executor {i}'s table "
                f"({n} entries) — increase table capacity"
            )
        if sl["kind"] == "agg":
            new_states[i] = st._replace(
                table=table,
                prims=tuple(
                    p.at[slots].set(_to_dev(v), mode="drop")
                    for p, v in zip(st.prims, sl["prims"])
                ),
                prev_prims=tuple(
                    p.at[slots].set(_to_dev(v), mode="drop")
                    for p, v in zip(st.prev_prims, sl["prev_prims"])
                ),
                row_count=st.row_count.at[slots].set(
                    _to_dev(sl["row_count"]), mode="drop"),
                prev_row_count=st.prev_row_count.at[slots].set(
                    _to_dev(sl["prev_row_count"]), mode="drop"),
                dirty=st.dirty.at[slots].set(
                    _to_dev(sl["dirty"]), mode="drop"),
                emitted=st.emitted.at[slots].set(
                    _to_dev(sl["emitted"]), mode="drop"),
                minput_vals=tuple(
                    mv.at[slots].set(_to_dev(v), mode="drop")
                    for mv, v in zip(st.minput_vals, sl["minput_vals"])
                ),
                minput_occ=tuple(
                    mo.at[slots].set(_to_dev(o), mode="drop")
                    for mo, o in zip(st.minput_occ, sl["minput_occ"])
                ),
            )
        else:
            values = tuple(
                _scatter_col(store, slots, _to_dev(col))
                for store, col in zip(st.values, sl["values"])
            )
            new_states[i] = MvState(table, values, st.overflow)
        moved += n
    return tuple(new_states), moved
