"""Online state handover: per-vnode checkpoint slices + transplant.

Reference counterpart: the reschedule plan of ``scale.rs`` — when a
vnode moves, the state *behind* it (agg groups, MV rows keyed in that
vnode) moves with it, anchored at a checkpoint epoch so the transfer
is exact.

Mechanics here (cluster/meta_service drives the protocol):

1. the meta seals a round whose checkpoints are DURABLE on every
   partition (the handover epoch);
2. the recipient loads each donor partition's checkpoint *at that
   epoch* from the SHARED checkpoint store — state never crosses an
   RPC, only the moved keys' slices leave disk;
3. ``slice_partition_states`` extracts exactly the moved vnodes'
   entries (group keys + every per-slot state array) — the "only
   moved vnodes transfer" contract is structural, not best-effort;
4. ``clear_vnodes`` tombstones any stale entries the recipient still
   holds for the gained vnodes (a worker regaining vnodes it donated
   earlier refreshes, never resurrects);
5. ``transplant`` bulk find-or-claims the moved keys in the live
   tables (``HashTable.lookup_or_insert`` over the whole slice) and
   scatters the donor's per-slot arrays at the claimed slots.

Eligible state shapes: ``HashAggExecutor`` (prims / row_count / prev
snapshot / emitted / dirty / minput buckets — everything slot-aligned)
and ``MaterializeExecutor`` (pk table + dense value columns).  The
engine's eligibility gate guarantees no DISTINCT dedup tables and an
empty spill ring; both are asserted loudly here anyway.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.cluster.scale.vnode import (
    vnode_member_mask,
    vnodes_of_ints,
)
from risingwave_tpu.common.chunk import NCol, StrCol
from risingwave_tpu.state.hash_table import gather_key
from risingwave_tpu.stream.hash_agg import HashAggExecutor
from risingwave_tpu.stream.materialize import (
    MaterializeExecutor,
    MvState,
    _scatter_col,
)


def _to_dev(col):
    """Host slice column → device (NCol/StrCol aware)."""
    if isinstance(col, NCol):
        return NCol(_to_dev(col.data), jnp.asarray(col.null))
    if isinstance(col, StrCol):
        return StrCol(jnp.asarray(col.data), jnp.asarray(col.lens))
    return jnp.asarray(col)


def _dist_payload(col):
    """Raw integer payload of the distribution key column (the
    eligibility gate guarantees NOT NULL integer family)."""
    if isinstance(col, NCol):
        return col.data
    return col


def _entry_mask(table, vnodes, n_vnodes) -> np.ndarray:
    """Host ``bool [size]``: occupied slots whose key falls in the
    vnode set."""
    occ = np.asarray(table.occupied)
    vn = np.asarray(vnodes_of_ints(
        _dist_payload(table.key_cols[0]), n_vnodes
    ))
    member = np.zeros((n_vnodes,), bool)
    member[[int(v) for v in vnodes]] = True
    return occ & member[vn]


def _assert_plain_agg(ex: HashAggExecutor, state) -> None:
    if state.distinct_tables:
        raise RuntimeError(
            "vnode handover over a DISTINCT aggregation (dedup tables "
            "are not sliceable): not scale-eligible"
        )
    spill = getattr(state, "spill_count", ())
    if not isinstance(spill, tuple) and int(np.asarray(spill)) != 0:
        raise RuntimeError(
            "vnode handover with rows in the spill ring — drain first"
        )


# -- slice (donor checkpoint → moved entries) ---------------------------
def slice_partition_states(executors, states, vnodes,
                           n_vnodes: int) -> dict[int, dict]:
    """Extract the moved vnodes' entries from a (host) checkpoint
    state tree: ``{executor_idx: slice}`` for every keyed executor.

    Works on the numpy trees ``CheckpointStore.load`` returns (and on
    device trees — gathers go through numpy either way)."""
    out: dict[int, dict] = {}
    for i, ex in enumerate(executors):
        st = states[i]
        if isinstance(ex, HashAggExecutor):
            _assert_plain_agg(ex, st)
            take = _entry_mask(st.table, vnodes, n_vnodes)
            idx = np.nonzero(take)[0]
            out[i] = {
                "kind": "agg",
                "n": int(idx.shape[0]),
                "keys": [gather_key(np.asarray(c) if not isinstance(
                    c, (NCol, StrCol)) else c, idx)
                    for c in st.table.key_cols],
                "prims": [np.asarray(p)[idx] for p in st.prims],
                "prev_prims": [np.asarray(p)[idx]
                               for p in st.prev_prims],
                "row_count": np.asarray(st.row_count)[idx],
                "prev_row_count": np.asarray(st.prev_row_count)[idx],
                "dirty": np.asarray(st.dirty)[idx],
                "emitted": np.asarray(st.emitted)[idx],
                "minput_vals": [np.asarray(v)[idx]
                                for v in st.minput_vals],
                "minput_occ": [np.asarray(o)[idx]
                               for o in st.minput_occ],
            }
        elif isinstance(ex, MaterializeExecutor):
            take = _entry_mask(st.table, vnodes, n_vnodes)
            idx = np.nonzero(take)[0]
            out[i] = {
                "kind": "mv",
                "n": int(idx.shape[0]),
                "keys": [gather_key(np.asarray(c) if not isinstance(
                    c, (NCol, StrCol)) else c, idx)
                    for c in st.table.key_cols],
                "values": [gather_key(v if isinstance(v, (NCol, StrCol))
                                      else np.asarray(v), idx)
                           for v in st.values],
            }
    return out


# -- clear (recipient live state: evict stale entries in gained set) ----
def clear_vnodes(executors, states, vnodes, n_vnodes: int):
    """Tombstone every live entry in the given vnode set (stale state
    from an earlier ownership must never shadow the donor's current
    slice).  Returns (states', cleared_entries)."""
    new_states = list(states)
    cleared = 0
    member = vnode_member_mask(vnodes, n_vnodes)
    for i, ex in enumerate(executors):
        st = states[i]
        if isinstance(ex, HashAggExecutor):
            vn = vnodes_of_ints(
                _dist_payload(st.table.key_cols[0]), n_vnodes
            )
            stale = st.table.occupied & member[vn]
            cleared += int(jnp.sum(stale))
            new_states[i] = st._replace(
                table=st.table.clear_where(stale),
                row_count=jnp.where(stale, 0, st.row_count),
                prev_row_count=jnp.where(stale, 0, st.prev_row_count),
                dirty=st.dirty & ~stale,
                emitted=st.emitted & ~stale,
                minput_occ=tuple(o & ~stale[:, None]
                                 for o in st.minput_occ),
            )
        elif isinstance(ex, MaterializeExecutor):
            vn = vnodes_of_ints(
                _dist_payload(st.table.key_cols[0]), n_vnodes
            )
            stale = st.table.occupied & member[vn]
            cleared += int(jnp.sum(stale))
            new_states[i] = MvState(
                st.table.clear_where(stale), st.values, st.overflow
            )
    return tuple(new_states), cleared


# -- transplant (moved entries → recipient live state) ------------------
def transplant(executors, states, slices: dict[int, dict]):
    """Merge donor slices into the live state tree; returns
    ``(states', entries_moved)``.  Raises loudly when the recipient
    table cannot claim a slot (undersized table — the overflow analog
    of the streaming path's loud counters)."""
    new_states = list(states)
    moved = 0
    for i, sl in slices.items():
        st = states[i]
        n = sl["n"]
        if n == 0:
            continue
        keys = [_to_dev(c) for c in sl["keys"]]
        valid = jnp.ones((n,), jnp.bool_)
        table, slots, _, overflow = st.table.lookup_or_insert(
            keys, valid
        )
        if bool(jnp.any(overflow & valid)):
            raise RuntimeError(
                f"vnode transplant overflowed executor {i}'s table "
                f"({n} entries) — increase table capacity"
            )
        if sl["kind"] == "agg":
            new_states[i] = st._replace(
                table=table,
                prims=tuple(
                    p.at[slots].set(_to_dev(v), mode="drop")
                    for p, v in zip(st.prims, sl["prims"])
                ),
                prev_prims=tuple(
                    p.at[slots].set(_to_dev(v), mode="drop")
                    for p, v in zip(st.prev_prims, sl["prev_prims"])
                ),
                row_count=st.row_count.at[slots].set(
                    _to_dev(sl["row_count"]), mode="drop"),
                prev_row_count=st.prev_row_count.at[slots].set(
                    _to_dev(sl["prev_row_count"]), mode="drop"),
                dirty=st.dirty.at[slots].set(
                    _to_dev(sl["dirty"]), mode="drop"),
                emitted=st.emitted.at[slots].set(
                    _to_dev(sl["emitted"]), mode="drop"),
                minput_vals=tuple(
                    mv.at[slots].set(_to_dev(v), mode="drop")
                    for mv, v in zip(st.minput_vals, sl["minput_vals"])
                ),
                minput_occ=tuple(
                    mo.at[slots].set(_to_dev(o), mode="drop")
                    for mo, o in zip(st.minput_occ, sl["minput_occ"])
                ),
            )
        else:
            values = tuple(
                _scatter_col(store, slots, _to_dev(col))
                for store, col in zip(st.values, sl["values"])
            )
            new_states[i] = MvState(table, values, st.overflow)
        moved += n
    return tuple(new_states), moved
