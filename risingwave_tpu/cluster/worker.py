"""ComputeWorker: one compute-node process in the cluster.

Reference counterpart: the compute node (``src/compute``) — hosts
streaming actors, answers the meta's barrier injections, serves batch
reads over its local state, and reports liveness through heartbeats
(src/compute/src/server.rs; heartbeats in meta's ClusterController).

Shape here: an ``Engine`` in ``role="compute"`` (shared durable
checkpoint store under the cluster ``data_dir``, no meta store, no
hummock manifest — meta owns both), driven ENTIRELY by meta RPCs:

- ``adopt``  — execute the job's DDL (skipping objects already in the
  local catalog) and recover it from its last durable checkpoint; the
  placement AND the failover path are the same call;
- ``barrier`` — process N chunks + inject one barrier for ONE job
  (the meta drives rounds job-by-job, so the shared checkpoint
  manifest has a single writer at any instant).  Barriers are
  ROUND-TAGGED: the worker caches each job's last (round, seal)
  answer and replays it verbatim when the meta retries a round whose
  response was lost — a retried barrier can never run chunks twice;
- ``serve``  — a batch read, optionally pinned at ``query_epoch``
  (the meta passes its last cluster-committed epoch);
- ``execute`` — generic statement forwarding (INSERT fan-out).

A worker has no self-ticker: if the meta dies, the cluster freezes
consistently instead of diverging.  The heartbeat thread, however,
never dies with the meta: transient unreachability backs off and
keeps beating, and a meta that answers "unknown worker" (it restarted
and lost the registry, or expired us across a partition) triggers
RE-REGISTRATION — the meta then re-adopts our jobs from the durable
checkpoint chain, with no operator in the loop.
"""

from __future__ import annotations

import os
import threading
import time

from risingwave_tpu.cluster.rpc import (
    RpcClient,
    RpcError,
    RpcServer,
    parse_addr,
)
from risingwave_tpu.common.faults import RetryPolicy, get_fabric
from risingwave_tpu.common.trace import GLOBAL_TRACE


class ComputeWorker:
    def __init__(self, meta_addr: str, data_dir: str, config=None,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_interval_s: float = 0.5):
        from risingwave_tpu.sql.engine import Engine

        self.meta_host, self.meta_port = parse_addr(meta_addr)
        self.engine = Engine(config, data_dir=data_dir, role="compute")
        self.host = host
        self._port_req = port
        self.heartbeat_interval_s = heartbeat_interval_s
        self.worker_id: int | None = None
        self._lock = threading.Lock()
        self._server: RpcServer | None = None
        self._meta_client: RpcClient | None = None
        self._hb_thread: threading.Thread | None = None
        self._stop = threading.Event()
        cl = getattr(config, "cluster", None)
        self.retry = RetryPolicy(
            max_attempts=cl.rpc_retry_max_attempts if cl else 4,
            base_delay_s=cl.rpc_retry_base_delay_s if cl else 0.05,
            max_delay_s=cl.rpc_retry_max_delay_s if cl else 0.5,
            op="worker",
        )
        #: per-job idempotence cache of the last ROUND-TAGGED barrier:
        #: {"round", "sealed", "result"} — ``result`` is the full
        #: answer a meta retry replays; ``sealed`` alone survives a
        #: mid-handler failure (e.g. the export upload died AFTER the
        #: chunks ran), so the retry redoes only the export, never the
        #: chunks.  Cleared on adopt (an ownership change must never
        #: answer from a stale seal).
        self._round_cache: dict[str, dict] = {}
        #: heartbeats delivered (introspection/tests)
        self.heartbeats_sent = 0
        #: heartbeats that failed transiently (meta down / partition)
        self.heartbeat_failures = 0
        #: times this worker (re-)registered with a meta
        self.registrations = 0
        # -- worker↔worker exchange (the scale plane's data path) -------
        #: meta-pushed routing: peer addresses + replicated-table hosts
        #: (the choreography — per-chunk data then flows peer-to-peer,
        #: the meta keeps only control traffic)
        self._routing: dict = {"version": -1, "peers": {}, "tables": {}}
        self._routing_lock = threading.Lock()
        #: lazily-opened peer channels, labeled worker{i}>worker{j} so
        #: the fault fabric can storm the exchange seam
        self._peers: dict[int, RpcClient] = {}
        #: exchange counters (stress/chaos observability)
        self.exchange_rows_out = 0
        self.exchange_rows_in = 0
        self.exchange_batches_out = 0
        self.exchange_batches_in = 0
        self.exchange_fetches = 0
        self.exchange_send_failures = 0
        # -- Exchange-lite: the compiled shuffle choreography ----------
        #: executes the meta-compiled choreography: slices each ingest
        #: batch by vnode ONCE and ships each peer only its owned
        #: slice (plus the leader's slice to the standby); per-edge
        #: rows/bytes/batches counters + latency histogram land in the
        #: engine's metrics registry
        from risingwave_tpu.cluster.exchange import ShuffleService

        self.shuffle = ShuffleService(metrics=self.engine.metrics)

    @property
    def port(self) -> int:
        return self._server.port if self._server is not None else 0

    # -- lifecycle ------------------------------------------------------
    def start(self, heartbeat: bool = True) -> "ComputeWorker":
        self._stop.clear()
        self._server = RpcServer(self, self.host, self._port_req).start()
        self._meta_client = RpcClient(self.meta_host, self.meta_port,
                                      timeout=30.0, src="worker",
                                      dst="meta")
        # the FIRST registration is patient beyond the retry budget: a
        # worker booting alongside its meta (deployment races, chaos
        # restarts) waits for the meta to listen instead of dying
        deadline = time.monotonic() + 60.0
        while True:
            try:
                self._register()
                break
            except (ConnectionError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.25)
        # MV export SST keys come from the meta (single allocator:
        # collision-free across workers, vacuum-protected until the
        # round's cluster epoch commits them into the manifest).
        # worker_id is read at CALL time, so re-registration after a
        # meta restart transparently re-points the allocator.
        self.engine.sst_key_allocator = lambda: self.retry.call(
            self._meta_client, "alloc_sst", worker_id=self.worker_id,
        )["key"]
        if heartbeat:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                name=f"worker-{self.worker_id}-hb", daemon=True,
            )
            self._hb_thread.start()
        return self

    def _register(self) -> None:
        """(Re-)register with the meta.  A fresh meta hands out a new
        worker id; the old id's entry (if any) stays dead on its side.
        Retried with backoff — registration is idempotent from the
        worker's view (only the NEWEST id is ever used again)."""
        res = self.retry.call(
            self._meta_client, "register_worker",
            host=self.host, port=self.port, pid=os.getpid(),
        )
        self.worker_id = int(res["worker_id"])
        self._meta_client.src = f"worker{self.worker_id}"
        self.shuffle.worker_id = self.worker_id
        self.registrations += 1
        if GLOBAL_TRACE.role == "compute":
            # a dedicated compute process (server.py boot): trace spans
            # carry the meta-assigned identity so merged cluster dumps
            # keep each worker on its own chrome pid lane.  In-process
            # test clusters share one recorder and keep its role.
            GLOBAL_TRACE.configure(role=f"worker{self.worker_id}")

    def _heartbeat_loop(self) -> None:
        # independent of the engine lock: a worker busy compiling or
        # crossing a barrier still beats (liveness != idleness)
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                self._meta_client.call("heartbeat",
                                       worker_id=self.worker_id)
                self.heartbeats_sent += 1
            except (ConnectionError, OSError):
                # meta unreachable (restarting / partitioned): the
                # thread SURVIVES and keeps beating — the loop cadence
                # is the backoff
                self.heartbeat_failures += 1
            except RpcError:
                # the meta answered but doesn't know us: it restarted
                # (lost registry) or expired us — re-register so it
                # can re-adopt our jobs; on failure the next beat
                # retries
                self.heartbeat_failures += 1
                try:
                    self._register()
                except (RpcError, ConnectionError, OSError):
                    pass
            except Exception:  # noqa: BLE001 — never kill the thread
                self.heartbeat_failures += 1

    def stop(self) -> None:
        try:
            with self._lock:
                # orderly exit: sealed epochs finish becoming durable
                self.engine.drain_uploads()
        except Exception:  # noqa: BLE001 — a failed upload rewinds
            pass
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        with self._routing_lock:
            for c in self._peers.values():
                c.close()
            self._peers.clear()
        if self._meta_client is not None:
            self._meta_client.close()
            self._meta_client = None

    # -- worker↔worker exchange (scale plane data path) -----------------
    def rpc_update_routing(self, version: int, peers: dict,
                           tables: dict,
                           exchange: dict | None = None) -> dict:
        """Meta-pushed placement choreography: peer worker addresses,
        per replicated DML table its hosts + ingest leader, and (when
        the exchange plane is compiled) the full Exchange-lite
        choreography — per-table shuffle key, vnode slices, standby.
        The per-chunk fan-out below never touches the meta again."""
        with self._routing_lock:
            if int(version) >= self._routing["version"]:
                self._routing = {
                    "version": int(version),
                    "peers": {int(k): tuple(v)
                              for k, v in peers.items()},
                    "tables": {t: {"leader": int(i["leader"]),
                                   "hosts": [int(h)
                                             for h in i["hosts"]]}
                               for t, i in tables.items()},
                }
                # drop channels to peers that left the ring
                for wid in [w for w in self._peers
                            if w not in self._routing["peers"]]:
                    self._peers.pop(wid).close()
        if exchange is not None:
            self.shuffle.update(exchange)
            with self._lock:
                self.engine.apply_shuffle_plan(
                    self.shuffle.choreography.tables
                )
        return {"ok": True}

    def _peer(self, wid: int) -> RpcClient:
        with self._routing_lock:
            c = self._peers.get(wid)
            if c is None:
                host, port = self._routing["peers"][wid]
                c = RpcClient(host, int(port), timeout=30.0,
                              src=f"worker{self.worker_id}",
                              dst=f"worker{wid}")
                self._peers[wid] = c
            return c

    def _table_route(self, table: str) -> dict | None:
        with self._routing_lock:
            return self._routing["tables"].get(table)

    def _dml_manager(self, table: str):
        entry = self.engine.catalog.get(table)
        if entry.dml is None:
            raise ValueError(f"{table!r} is not a DML table")
        return entry.dml

    def rpc_execute(self, sql: str) -> dict:
        """Generic statement execution.  INSERTs into a replicated
        table take the choreographed path: a non-leader forwards to
        the table's ingest leader (worker↔worker); the leader applies
        locally and fans the position-stamped batch out to every other
        host over peer channels — the meta never sees a data chunk."""
        from risingwave_tpu.sql import ast
        from risingwave_tpu.sql.parser import parse

        stmts = parse(sql)
        route = None
        if len(stmts) == 1 and isinstance(stmts[0],
                                          (ast.Insert, ast.Delete)):
            # DELETE routes identically: the leader executes the SQL,
            # the history slice it ships already carries the
            # marker-tail op encoding (connector/dml.py)
            route = self._table_route(stmts[0].table)
        if route is None:
            with self._lock:
                self.engine.execute(sql)
            return {"ok": True}
        table = stmts[0].table
        if route["leader"] != self.worker_id:
            # worker↔worker forward; the leader's answer is ours
            return self.retry.run(
                lambda: self._peer(route["leader"]).call(
                    "execute", sql=sql),
                label="execute_forward",
            )
        with self._lock:
            mgr = self._dml_manager(table)
            seq = mgr.history_len()
            self.engine.execute(sql)
            rows = mgr.history_slice(seq)
        # Exchange-lite: slice the batch by vnode ONCE, ship each peer
        # only its owned slice (standby additionally carries the
        # leader's slice); replicate-mode tables keep the PR-7 full
        # fan-out.  All OUTSIDE the engine lock (peers may be
        # forwarding to us concurrently); a dropped delivery
        # self-heals at the next barrier's fence repair.
        payloads = self.shuffle.route_batch(table, seq, rows)
        if not payloads:
            # choreography not yet pushed (registration race): the
            # legacy full fan-out keeps every host convergent
            payloads = {w: {"seq": seq, "rows": rows}
                        for w in route["hosts"] if w != self.worker_id}
        sliced = any("end" in p for p in payloads.values())
        if sliced:
            # stamp the leader's own vnode log (receivers get theirs
            # from the payload): every host can audit ownership
            from risingwave_tpu.cluster.exchange.shuffle import (
                unpack_vnodes,
            )

            first = next(iter(payloads.values()))
            with self._lock:
                mgr.set_vnode_range(seq, unpack_vnodes(first))
        edge = self.shuffle.edge_of(table)
        for wid, payload in payloads.items():
            method = "exchange_sparse" if "end" in payload \
                else "exchange"
            n_rows = len(payload.get("rows", ()))
            try:
                with self.shuffle.timed() as t:
                    self.retry.run(
                        lambda w=wid, p=payload, m=method:
                        self._peer(w).call(m, table=table, **p),
                        label="exchange",
                    )
                self.shuffle.note_send(edge, payload, t.dt)
                self.exchange_rows_out += n_rows
                self.exchange_batches_out += 1
            except (RpcError, ConnectionError, OSError, KeyError):
                self.exchange_send_failures += 1
        return {"ok": True, "seq": seq, "rows": len(rows)}

    def rpc_exchange(self, table: str, seq: int, rows: list) -> dict:
        """Receive one position-stamped batch from a peer.  Duplicate
        positions are skipped; a batch beyond the local tail is
        refused (the barrier-time catch-up fetch fills the gap from
        the leader — ordered, idempotent delivery without a broker)."""
        with self._lock:
            mgr = self._dml_manager(table)
            try:
                applied = mgr.insert_at(
                    int(seq), [tuple(r) for r in rows]
                )
            except ValueError:
                return {"ok": False, "have": mgr.history_len()}
        self.exchange_rows_in += applied
        self.exchange_batches_in += 1
        return {"ok": True, "applied": applied}

    def rpc_exchange_sparse(self, table: str, seq: int, end: int,
                            vnodes: list | None = None,
                            vn64: str | None = None,
                            rows: list | None = None,
                            own: list | None = None,
                            items: list | None = None) -> dict:
        """Receive one SLICED position-stamped batch (Exchange-lite):
        this host's owned rows (positions derived from the batch's
        vnode log + the covered-vnode set — rows cross the wire once,
        without per-row positions), placeholders elsewhere.
        Idempotent; placeholder holes fill on redelivery; a batch
        beyond the local tail is refused (fence repair fills the gap
        from the leader)."""
        from risingwave_tpu.cluster.exchange import ShuffleService

        payload = {"seq": int(seq), "end": int(end),
                   "vnodes": vnodes or (), "rows": rows or (),
                   "own": own or ()}
        if vn64 is not None:
            payload["vn64"] = vn64
        if items is not None:
            payload["items"] = items
        with self._lock:
            mgr = self._dml_manager(table)
            try:
                applied = ShuffleService.apply_batch(mgr, payload)
            except ValueError:
                return {"ok": False, "have": mgr.history_len()}
        self.exchange_rows_in += applied
        self.exchange_batches_in += 1
        return {"ok": True, "applied": applied}

    def rpc_fetch_table(self, table: str, from_seq: int = 0) -> dict:
        """Peer catch-up: the table's history from a position (the
        handover/new-host backfill and the gap repair path)."""
        with self._lock:
            mgr = self._dml_manager(table)
            return {"seq": int(from_seq),
                    "rows": mgr.history_slice(int(from_seq))}

    def rpc_fetch_slice(self, table: str, from_seq: int = 0,
                        to_seq: int | None = None,
                        vnodes: list | None = None) -> dict:
        """Sliced peer catch-up: one vnode set's rows over a history
        range, plus the vnode log (gap repair on the shuffled path and
        gained-vnode backfill after a repartition).  Positions this
        host never stored are absent — the caller peer-fills."""
        with self._lock:
            mgr = self._dml_manager(table)
            return self.shuffle.slice_history(
                mgr, int(from_seq), to_seq, vnodes or (), table
            )

    def rpc_fetch_positions(self, table: str, positions: list) -> dict:
        """Point catch-up: specific global positions this host holds
        (the peer-fill path when the leader itself has holes — e.g. a
        standby promoted past a dead leader)."""
        with self._lock:
            mgr = self._dml_manager(table)
            items = []
            for p in positions:
                row = mgr.history_row(int(p))
                if row is not None:
                    items.append([int(p), list(row)])
            return {"items": items}

    def rpc_table_len(self, table: str) -> dict:
        with self._lock:
            return {"len": self._dml_manager(table).history_len()}

    def _owned_vnodes_for(self, table: str) -> "set[int] | None":
        """Union of this worker's owned vnodes across partitioned jobs
        reading a SHUFFLED table (None = table not shuffled here)."""
        plan = self.shuffle.table_plan(table)
        if plan is None or plan["mode"] != "shuffle":
            return None
        own: set[int] = set()
        with self._lock:
            for job in self.engine.jobs:
                if getattr(job, "n_vnodes", None) is None:
                    continue
                if table in getattr(job, "shuffle_cols", {}):
                    own |= {int(v) for v in job.vnodes}
        # the standby audits the leader's slice too (it must hold a
        # full copy so a promoted standby can serve every fetch)
        if plan.get("standby") == self.worker_id \
                and plan["leader"] in plan["slices"]:
            own |= {int(v) for v in plan["slices"][plan["leader"]]}
        return own

    def _peer_fill(self, table: str, positions: list[int]) -> int:
        """Fill specific missing positions from any live peer (double-
        failure repair: the leader died and its successor has holes)."""
        filled = 0
        with self._routing_lock:
            peer_ids = [w for w in self._routing["peers"]
                        if w != self.worker_id]
        for wid in peer_ids:
            if not positions:
                break
            try:
                res = self._peer(wid).call(
                    "fetch_positions", table=table,
                    positions=positions,
                )
            except (RpcError, ConnectionError, OSError, KeyError):
                continue
            got = {int(p): tuple(r) for p, r in res["items"]}
            if not got:
                continue
            with self._lock:
                mgr = self._dml_manager(table)
                for p, r in got.items():
                    filled += mgr.insert_sparse(
                        p, p + 1, [(p, r)], []
                    )
            positions = [p for p in positions if p not in got]
        self.exchange_rows_in += filled
        return filled

    def _ensure_table_len(self, table: str, want: int) -> None:
        """Catch the local replica up to the round's consumption fence
        before the barrier runs — exchange drops (chaos) repair here.
        On a shuffled table "caught up" means TWO things: history long
        enough AND every OWNED position below the fence actually holds
        a row (a sliced delivery lost to chaos leaves a hole the
        length check alone would miss)."""
        with self._lock:
            mgr = self._dml_manager(table)
            have = mgr.history_len()
        own = self._owned_vnodes_for(table)
        route = self._table_route(table)
        is_leader = route is not None \
            and route["leader"] == self.worker_id
        if have < want:
            if route is None or is_leader:
                raise RuntimeError(
                    f"{table!r} behind its fence ({have} < {want}) "
                    "with no leader to fetch from"
                )
            if own is None:
                res = self.retry.run(
                    lambda: self._peer(route["leader"]).call(
                        "fetch_table", table=table, from_seq=have),
                    label="fetch_table",
                )
                rows = [tuple(r) for r in res["rows"]
                        if r is not None]
                with self._lock:
                    applied = self._dml_manager(table).insert_at(
                        int(res["seq"]), rows
                    )
            else:
                res = self.retry.run(
                    lambda: self._peer(route["leader"]).call(
                        "fetch_slice", table=table, from_seq=have,
                        to_seq=want, vnodes=sorted(own)),
                    label="fetch_slice",
                )
                with self._lock:
                    applied = self._dml_manager(table).insert_sparse(
                        int(res["seq"]), int(res["end"]),
                        [(int(p), tuple(r)) for p, r in res["items"]],
                        [int(v) for v in res.get("vnodes") or ()],
                    )
            self.exchange_fetches += 1
            self.exchange_rows_in += applied
            if applied:
                self.exchange_batches_in += 1
        if own is None:
            return
        # completeness audit below the fence (sliced path): scan only
        # the still-unconsumed window — holes below every reader's
        # cursor can never be read again
        with self._lock:
            lo = self.engine.table_consumption_floor(table)
            missing = self._dml_manager(table).missing_positions(
                own, lo, want
            )
        if not missing:
            return
        if route is not None and not is_leader:
            try:
                res = self.retry.run(
                    lambda: self._peer(route["leader"]).call(
                        "fetch_positions", table=table,
                        positions=missing),
                    label="fetch_positions",
                )
                got = [(int(p), tuple(r)) for p, r in res["items"]]
                with self._lock:
                    mgr = self._dml_manager(table)
                    for p, r in got:
                        mgr.insert_sparse(p, p + 1, [(p, r)], [])
                self.exchange_fetches += 1
                self.exchange_rows_in += len(got)
                missing = [p for p in missing
                           if p not in {g[0] for g in got}]
            except (RpcError, ConnectionError, OSError, KeyError):
                pass
        if missing:
            self._peer_fill(table, missing)

    # -- RPC surface ----------------------------------------------------
    def rpc_ping(self) -> dict:
        return {"ok": True, "worker_id": self.worker_id,
                "jobs": [j.name for j in self.engine.jobs]}

    def rpc_scale_stats(self) -> dict:
        """Exchange/partition observability (scale_stress asserts the
        per-chunk path flows worker↔worker AND, on shuffled edges,
        that the gate audit counters stayed at zero)."""
        with self._lock:
            parts = self.engine.partition_stats()
        return {
            "exchange_rows_out": self.exchange_rows_out,
            "exchange_rows_in": self.exchange_rows_in,
            "exchange_batches_out": self.exchange_batches_out,
            "exchange_batches_in": self.exchange_batches_in,
            "exchange_fetches": self.exchange_fetches,
            "exchange_send_failures": self.exchange_send_failures,
            "routing_version": self._routing["version"],
            "shuffle": self.shuffle.stats(),
            "gate_dropped": sum(p["gate_dropped"]
                                for p in parts.values()),
            "reader_filtered": sum(p["reader_filtered"]
                                   for p in parts.values()),
            "partition_stats": parts,
            "partitions": {
                j.name: sorted(j.vnodes)
                for j in self.engine.jobs
                if hasattr(j, "vnodes")
            },
        }

    def rpc_metrics(self) -> dict:
        """This worker process' metric surface (exchange counters,
        engine gauges) — per-edge series live HERE; the meta keeps
        per-worker aggregates it retires on death."""
        return {"prometheus": self.engine.metrics.render_prometheus()}

    def rpc_trace_dump(self, trace_id: str | None = None) -> dict:
        """This process' span flight recorder (optionally filtered to
        one trace) — the meta merges per-role dumps into the round
        timeline ``ctl cluster trace`` renders."""
        return {"role": GLOBAL_TRACE.role,
                "spans": GLOBAL_TRACE.dump(trace_id)}

    def rpc_adopt(self, ddl: list, name: str, recover: bool = True,
                  vnodes: list | None = None, n_vnodes: int = 0,
                  ckpt_key: str | None = None) -> dict:
        """Adopt (or extend) a streaming job: replay its DDL, then
        recover from the last durable checkpoint (exact replay: the
        checkpoint holds state + source cursors of the same commit).

        With ``vnodes`` the meta asks for a PARTITIONED adoption: the
        job is rebuilt as one vnode partition (gate before the agg,
        checkpoint lineage ``ckpt_key``) owning the given set.  An
        ineligible plan answers ``partitioned: false`` and stays a
        whole job — the meta falls back to job-level placement."""
        from risingwave_tpu.sql.planner import PlanError

        with self._lock:
            # a (re-)adoption invalidates any cached seal: the next
            # round must run against the recovered state
            self._round_cache.pop(name, None)
            if vnodes is None:
                epoch = self.engine.adopt_job(list(ddl), name,
                                              recover=recover)
                return {"ok": True, "committed_epoch": epoch,
                        "partitioned": False}
            self.engine.adopt_job(list(ddl), name, recover=False)
            try:
                spec = self.engine.partition_job(
                    name, int(n_vnodes), ckpt_key or name
                )
            except PlanError as e:
                # not scale-eligible: finish as a plain adoption
                entry = self.engine.catalog.get(name)
                if recover:
                    entry.job.recover()
                return {"ok": True, "partitioned": False,
                        "reason": str(e),
                        "committed_epoch": entry.job.committed_epoch}
            entry = self.engine.catalog.get(name)
            if recover:
                # the partition's OWN lineage (failover / meta restart)
                entry.job.recover()
            self.engine.set_job_vnodes(name, vnodes)
            return {"ok": True, "partitioned": True,
                    "committed_epoch": entry.job.committed_epoch,
                    **spec}

    def rpc_repartition(self, job: str, vnodes: list, transfers: list,
                        rewind_epoch: int | None = None) -> dict:
        """One handover step on this worker's partition (see
        Engine.repartition_job).  Clears the round cache — ownership
        changed, a cached seal must never answer for the new set."""
        with self._lock:
            self._round_cache.pop(job, None)
            res = self.engine.repartition_job(
                job, vnodes, list(transfers or ()),
                rewind_epoch=rewind_epoch,
            )
        return {"ok": True, **res}

    def rpc_release(self, job: str) -> dict:
        """Drop a partition that lost its last vnode (scale-in): the
        MV leaves this engine; sources (and their histories) stay for
        a future re-adoption."""
        with self._lock:
            self._round_cache.pop(job, None)
            if job in self.engine.catalog:
                self.engine.execute(
                    f"DROP MATERIALIZED VIEW {job}"
                )
        return {"ok": True}

    def rpc_barrier(self, job: str, chunks: int = 1,
                    round: int = 0, limits: dict | None = None) -> dict:
        """Process ``chunks`` chunks + one barrier for one job — the
        meta's global round, applied locally.  Returns the SEALED
        epoch immediately (the checkpoint upload runs in the job's
        background uploader) plus the round's MV export SSTs; meta
        polls ``job_epochs`` for the durable ack before committing the
        cluster epoch.  ``round`` tags the call for idempotence: a
        replay of the round we last sealed answers from the cache
        without touching the engine (the meta retries barriers whose
        response was lost in flight).  ``limits`` is the round's
        consumption fence per replicated DML table (scale plane): the
        local replica first catches up to the fence over the peer
        exchange, then consumes exactly up to it — every partition of
        a job sees the identical prefix per round."""
        rnd = int(round or 0)
        if limits:
            for table, want in limits.items():
                try:
                    self._ensure_table_len(table, int(want))
                except (ValueError, KeyError):
                    pass  # not a hosted DML table on this worker
        with self._lock:
            cached = self._round_cache.get(job) if rnd else None
            if cached is not None and cached["round"] == rnd \
                    and cached["result"] is not None:
                return cached["result"]
            if cached is not None and cached["round"] == rnd:
                # chunks already ran for this round; only the export/
                # response was lost — redo the cheap tail
                sealed = cached["sealed"]
            else:
                sealed = self.engine.tick_job(job, int(chunks),
                                              source_limits=limits)
                if rnd:
                    self._round_cache[job] = {"round": rnd,
                                              "sealed": sealed,
                                              "result": None}
            from risingwave_tpu.storage.integrity import IntegrityError

            corrupt: list[str] = []
            t0 = time.perf_counter()
            try:
                with GLOBAL_TRACE.span("mv_export", job=job) as _sp:
                    ssts = self.engine.export_mv_deltas(job, sealed)
                    _sp.set(ssts=len(ssts))
            except IntegrityError as e:
                # a corrupt shared SST under the export's diff-base
                # seeding: seal the round anyway (exports retry next
                # round) and surface the key so the meta repairs it
                ssts = []
                if e.key:
                    corrupt.append(e.key)
            self.engine.metrics.observe(
                "barrier_phase_seconds", time.perf_counter() - t0,
                job=job, phase="mv_export",
            )
            positions = self.engine.job_epochs(job)
            res = {"ok": True, "committed_epoch": sealed,
                   "sealed_epoch": sealed,
                   "durable_epoch": positions["durable"],
                   "ssts": ssts, "corrupt": corrupt,
                   # pushdown plane: expiry-policy docs staged by this
                   # round's exports (None = DROP); the meta folds
                   # them into the same manifest delta as the SSTs
                   "policies": self.engine.take_pending_policies(),
                   # cheap exchange summary (host counters only): the
                   # meta mirrors these as per-worker gauges retired
                   # with the worker
                   "exchange": {
                       "rows_out": self.exchange_rows_out,
                       "rows_in": self.exchange_rows_in,
                       "batches_out": self.exchange_batches_out,
                       "batches_in": self.exchange_batches_in,
                       "send_failures": self.exchange_send_failures,
                   }}
            if rnd:
                self._round_cache[job]["result"] = res
        return res

    def rpc_reexport(self, job: str, exclude: list | None = None) -> dict:
        """Integrity repair: re-export the job's MVs IN FULL against a
        diff base re-seeded from the shared manifest MINUS the
        quarantined keys in ``exclude`` — upserts for every row the
        corrupt SST carried, tombstones for rows it shadowed.  The meta
        commits the returned SSTs atomically with the corrupt object's
        removal."""
        with self._lock:
            ssts = self.engine.reexport_job_mvs(
                job, exclude=exclude or ())
        return {"ok": True, "ssts": ssts}

    def rpc_repair_checkpoint(self, lineage: str) -> dict:
        """Integrity repair: verify + truncate one checkpoint lineage
        this worker owns (quarantine corrupt epoch objects, rewind the
        chain to the last verified epoch).  The next save re-bases with
        a full snapshot, so the lineage converges forward; a recovery
        in the window rewinds to the verified epoch and the meta's
        round-credit rewind replays the gap."""
        with self._lock:
            if self.engine.checkpoint_store is None:
                return {"ok": False, "reason": "no durable store"}
            rep = self.engine.checkpoint_store.repair_lineage(lineage)
        return {"ok": True, **rep}

    def rpc_job_epochs(self, job: str) -> dict:
        """Seal-vs-durable positions of one job (also services its
        pending upload acks — see Engine.job_epochs)."""
        with self._lock:
            return self.engine.job_epochs(job)

    def rpc_serve(self, sql: str, query_epoch: int = 0,
                  vnodes: list | None = None) -> dict:
        """Batch read; ``query_epoch`` pins the retained checkpoint of
        the meta's last cluster commit (reads never see state a global
        commit hasn't covered).  ``vnodes`` narrows a partitioned MV
        read to the vnode set this partition owned AT THE PINNED ROUND
        (the meta fans a partitioned read across owners and unions the
        disjoint slices)."""
        qe = int(query_epoch or 0)
        with self._lock:
            if qe:
                self.engine.session_config.set("query_epoch", qe)
            if vnodes is not None:
                self.engine._serve_vnodes = frozenset(
                    int(v) for v in vnodes
                )
            try:
                cols, rows = self.engine.query(sql)
            finally:
                if qe:
                    self.engine.session_config.set("query_epoch", 0)
                self.engine._serve_vnodes = None
        return {"cols": cols, "rows": [list(r) for r in rows]}

    def rpc_faults(self) -> dict:
        """This process' chaos counters (aggregated by the meta's
        ``cluster_faults`` for the ctl surface)."""
        fabric = get_fabric()
        upload_retries = 0
        for j in self.engine.jobs:
            up = getattr(j, "_uploader", None)
            if up is not None:
                upload_retries += getattr(up, "retries_total", 0)
        return {
            "fabric": fabric.stats() if fabric is not None else None,
            "rpc_retries_total": self.retry.retries,
            "rpc_retry_gave_up_total": self.retry.gave_up,
            "heartbeat_failures": self.heartbeat_failures,
            "registrations": self.registrations,
            "checkpoint_upload_retries_total": upload_retries,
            # the worker↔worker exchange seam (scale_storm and
            # shuffle_storm assert the fabric's faults here were
            # absorbed/repaired)
            "exchange_rows_out": self.exchange_rows_out,
            "exchange_rows_in": self.exchange_rows_in,
            "exchange_fetches": self.exchange_fetches,
            "exchange_send_failures": self.exchange_send_failures,
        }
