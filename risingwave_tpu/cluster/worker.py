"""ComputeWorker: one compute-node process in the cluster.

Reference counterpart: the compute node (``src/compute``) — hosts
streaming actors, answers the meta's barrier injections, serves batch
reads over its local state, and reports liveness through heartbeats
(src/compute/src/server.rs; heartbeats in meta's ClusterController).

Shape here: an ``Engine`` in ``role="compute"`` (shared durable
checkpoint store under the cluster ``data_dir``, no meta store, no
hummock manifest — meta owns both), driven ENTIRELY by meta RPCs:

- ``adopt``  — execute the job's DDL (skipping objects already in the
  local catalog) and recover it from its last durable checkpoint; the
  placement AND the failover path are the same call;
- ``barrier`` — process N chunks + inject one barrier for ONE job
  (the meta drives rounds job-by-job, so the shared checkpoint
  manifest has a single writer at any instant).  Barriers are
  ROUND-TAGGED: the worker caches each job's last (round, seal)
  answer and replays it verbatim when the meta retries a round whose
  response was lost — a retried barrier can never run chunks twice;
- ``serve``  — a batch read, optionally pinned at ``query_epoch``
  (the meta passes its last cluster-committed epoch);
- ``execute`` — generic statement forwarding (INSERT fan-out).

A worker has no self-ticker: if the meta dies, the cluster freezes
consistently instead of diverging.  The heartbeat thread, however,
never dies with the meta: transient unreachability backs off and
keeps beating, and a meta that answers "unknown worker" (it restarted
and lost the registry, or expired us across a partition) triggers
RE-REGISTRATION — the meta then re-adopts our jobs from the durable
checkpoint chain, with no operator in the loop.
"""

from __future__ import annotations

import os
import threading
import time

from risingwave_tpu.cluster.rpc import (
    RpcClient,
    RpcError,
    RpcServer,
    parse_addr,
)
from risingwave_tpu.common.faults import RetryPolicy, get_fabric


class ComputeWorker:
    def __init__(self, meta_addr: str, data_dir: str, config=None,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_interval_s: float = 0.5):
        from risingwave_tpu.sql.engine import Engine

        self.meta_host, self.meta_port = parse_addr(meta_addr)
        self.engine = Engine(config, data_dir=data_dir, role="compute")
        self.host = host
        self._port_req = port
        self.heartbeat_interval_s = heartbeat_interval_s
        self.worker_id: int | None = None
        self._lock = threading.Lock()
        self._server: RpcServer | None = None
        self._meta_client: RpcClient | None = None
        self._hb_thread: threading.Thread | None = None
        self._stop = threading.Event()
        cl = getattr(config, "cluster", None)
        self.retry = RetryPolicy(
            max_attempts=cl.rpc_retry_max_attempts if cl else 4,
            base_delay_s=cl.rpc_retry_base_delay_s if cl else 0.05,
            max_delay_s=cl.rpc_retry_max_delay_s if cl else 0.5,
            op="worker",
        )
        #: per-job idempotence cache of the last ROUND-TAGGED barrier:
        #: {"round", "sealed", "result"} — ``result`` is the full
        #: answer a meta retry replays; ``sealed`` alone survives a
        #: mid-handler failure (e.g. the export upload died AFTER the
        #: chunks ran), so the retry redoes only the export, never the
        #: chunks.  Cleared on adopt (an ownership change must never
        #: answer from a stale seal).
        self._round_cache: dict[str, dict] = {}
        #: heartbeats delivered (introspection/tests)
        self.heartbeats_sent = 0
        #: heartbeats that failed transiently (meta down / partition)
        self.heartbeat_failures = 0
        #: times this worker (re-)registered with a meta
        self.registrations = 0

    @property
    def port(self) -> int:
        return self._server.port if self._server is not None else 0

    # -- lifecycle ------------------------------------------------------
    def start(self, heartbeat: bool = True) -> "ComputeWorker":
        self._stop.clear()
        self._server = RpcServer(self, self.host, self._port_req).start()
        self._meta_client = RpcClient(self.meta_host, self.meta_port,
                                      timeout=30.0, src="worker",
                                      dst="meta")
        # the FIRST registration is patient beyond the retry budget: a
        # worker booting alongside its meta (deployment races, chaos
        # restarts) waits for the meta to listen instead of dying
        deadline = time.monotonic() + 60.0
        while True:
            try:
                self._register()
                break
            except (ConnectionError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.25)
        # MV export SST keys come from the meta (single allocator:
        # collision-free across workers, vacuum-protected until the
        # round's cluster epoch commits them into the manifest).
        # worker_id is read at CALL time, so re-registration after a
        # meta restart transparently re-points the allocator.
        self.engine.sst_key_allocator = lambda: self.retry.call(
            self._meta_client, "alloc_sst", worker_id=self.worker_id,
        )["key"]
        if heartbeat:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                name=f"worker-{self.worker_id}-hb", daemon=True,
            )
            self._hb_thread.start()
        return self

    def _register(self) -> None:
        """(Re-)register with the meta.  A fresh meta hands out a new
        worker id; the old id's entry (if any) stays dead on its side.
        Retried with backoff — registration is idempotent from the
        worker's view (only the NEWEST id is ever used again)."""
        res = self.retry.call(
            self._meta_client, "register_worker",
            host=self.host, port=self.port, pid=os.getpid(),
        )
        self.worker_id = int(res["worker_id"])
        self._meta_client.src = f"worker{self.worker_id}"
        self.registrations += 1

    def _heartbeat_loop(self) -> None:
        # independent of the engine lock: a worker busy compiling or
        # crossing a barrier still beats (liveness != idleness)
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                self._meta_client.call("heartbeat",
                                       worker_id=self.worker_id)
                self.heartbeats_sent += 1
            except (ConnectionError, OSError):
                # meta unreachable (restarting / partitioned): the
                # thread SURVIVES and keeps beating — the loop cadence
                # is the backoff
                self.heartbeat_failures += 1
            except RpcError:
                # the meta answered but doesn't know us: it restarted
                # (lost registry) or expired us — re-register so it
                # can re-adopt our jobs; on failure the next beat
                # retries
                self.heartbeat_failures += 1
                try:
                    self._register()
                except (RpcError, ConnectionError, OSError):
                    pass
            except Exception:  # noqa: BLE001 — never kill the thread
                self.heartbeat_failures += 1

    def stop(self) -> None:
        try:
            with self._lock:
                # orderly exit: sealed epochs finish becoming durable
                self.engine.drain_uploads()
        except Exception:  # noqa: BLE001 — a failed upload rewinds
            pass
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._meta_client is not None:
            self._meta_client.close()
            self._meta_client = None

    # -- RPC surface ----------------------------------------------------
    def rpc_ping(self) -> dict:
        return {"ok": True, "worker_id": self.worker_id,
                "jobs": [j.name for j in self.engine.jobs]}

    def rpc_adopt(self, ddl: list, name: str,
                  recover: bool = True) -> dict:
        """Adopt (or extend) a streaming job: replay its DDL, then
        recover from the last durable checkpoint (exact replay: the
        checkpoint holds state + source cursors of the same commit)."""
        with self._lock:
            # a (re-)adoption invalidates any cached seal: the next
            # round must run against the recovered state
            self._round_cache.pop(name, None)
            epoch = self.engine.adopt_job(list(ddl), name,
                                          recover=recover)
        return {"ok": True, "committed_epoch": epoch}

    def rpc_barrier(self, job: str, chunks: int = 1,
                    round: int = 0) -> dict:
        """Process ``chunks`` chunks + one barrier for one job — the
        meta's global round, applied locally.  Returns the SEALED
        epoch immediately (the checkpoint upload runs in the job's
        background uploader) plus the round's MV export SSTs; meta
        polls ``job_epochs`` for the durable ack before committing the
        cluster epoch.  ``round`` tags the call for idempotence: a
        replay of the round we last sealed answers from the cache
        without touching the engine (the meta retries barriers whose
        response was lost in flight)."""
        rnd = int(round or 0)
        with self._lock:
            cached = self._round_cache.get(job) if rnd else None
            if cached is not None and cached["round"] == rnd \
                    and cached["result"] is not None:
                return cached["result"]
            if cached is not None and cached["round"] == rnd:
                # chunks already ran for this round; only the export/
                # response was lost — redo the cheap tail
                sealed = cached["sealed"]
            else:
                sealed = self.engine.tick_job(job, int(chunks))
                if rnd:
                    self._round_cache[job] = {"round": rnd,
                                              "sealed": sealed,
                                              "result": None}
            ssts = self.engine.export_mv_deltas(job, sealed)
            positions = self.engine.job_epochs(job)
            res = {"ok": True, "committed_epoch": sealed,
                   "sealed_epoch": sealed,
                   "durable_epoch": positions["durable"],
                   "ssts": ssts}
            if rnd:
                self._round_cache[job]["result"] = res
        return res

    def rpc_job_epochs(self, job: str) -> dict:
        """Seal-vs-durable positions of one job (also services its
        pending upload acks — see Engine.job_epochs)."""
        with self._lock:
            return self.engine.job_epochs(job)

    def rpc_serve(self, sql: str, query_epoch: int = 0) -> dict:
        """Batch read; ``query_epoch`` pins the retained checkpoint of
        the meta's last cluster commit (reads never see state a global
        commit hasn't covered)."""
        qe = int(query_epoch or 0)
        with self._lock:
            if qe:
                self.engine.session_config.set("query_epoch", qe)
            try:
                cols, rows = self.engine.query(sql)
            finally:
                if qe:
                    self.engine.session_config.set("query_epoch", 0)
        return {"cols": cols, "rows": [list(r) for r in rows]}

    def rpc_execute(self, sql: str) -> dict:
        with self._lock:
            self.engine.execute(sql)
        return {"ok": True}

    def rpc_faults(self) -> dict:
        """This process' chaos counters (aggregated by the meta's
        ``cluster_faults`` for the ctl surface)."""
        fabric = get_fabric()
        upload_retries = 0
        for j in self.engine.jobs:
            up = getattr(j, "_uploader", None)
            if up is not None:
                upload_retries += getattr(up, "retries_total", 0)
        return {
            "fabric": fabric.stats() if fabric is not None else None,
            "rpc_retries_total": self.retry.retries,
            "rpc_retry_gave_up_total": self.retry.gave_up,
            "heartbeat_failures": self.heartbeat_failures,
            "registrations": self.registrations,
            "checkpoint_upload_retries_total": upload_retries,
        }
