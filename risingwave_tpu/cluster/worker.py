"""ComputeWorker: one compute-node process in the cluster.

Reference counterpart: the compute node (``src/compute``) — hosts
streaming actors, answers the meta's barrier injections, serves batch
reads over its local state, and reports liveness through heartbeats
(src/compute/src/server.rs; heartbeats in meta's ClusterController).

Shape here: an ``Engine`` in ``role="compute"`` (shared durable
checkpoint store under the cluster ``data_dir``, no meta store, no
hummock manifest — meta owns both), driven ENTIRELY by meta RPCs:

- ``adopt``  — execute the job's DDL (skipping objects already in the
  local catalog) and recover it from its last durable checkpoint; the
  placement AND the failover path are the same call;
- ``barrier`` — process N chunks + inject one barrier for ONE job
  (the meta drives rounds job-by-job, so the shared checkpoint
  manifest has a single writer at any instant);
- ``serve``  — a batch read, optionally pinned at ``query_epoch``
  (the meta passes its last cluster-committed epoch);
- ``execute`` — generic statement forwarding (INSERT fan-out).

A worker has no self-ticker: if the meta dies, the cluster freezes
consistently instead of diverging.
"""

from __future__ import annotations

import os
import threading
import time

from risingwave_tpu.cluster.rpc import RpcClient, RpcServer, parse_addr


class ComputeWorker:
    def __init__(self, meta_addr: str, data_dir: str, config=None,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_interval_s: float = 0.5):
        from risingwave_tpu.sql.engine import Engine

        self.meta_host, self.meta_port = parse_addr(meta_addr)
        self.engine = Engine(config, data_dir=data_dir, role="compute")
        self.host = host
        self._port_req = port
        self.heartbeat_interval_s = heartbeat_interval_s
        self.worker_id: int | None = None
        self._lock = threading.Lock()
        self._server: RpcServer | None = None
        self._meta_client: RpcClient | None = None
        self._hb_thread: threading.Thread | None = None
        self._stop = threading.Event()
        #: heartbeats delivered (introspection/tests)
        self.heartbeats_sent = 0

    @property
    def port(self) -> int:
        return self._server.port if self._server is not None else 0

    # -- lifecycle ------------------------------------------------------
    def start(self, heartbeat: bool = True) -> "ComputeWorker":
        self._stop.clear()
        self._server = RpcServer(self, self.host, self._port_req).start()
        self._meta_client = RpcClient(self.meta_host, self.meta_port,
                                      timeout=30.0)
        res = self._meta_client.call(
            "register_worker", host=self.host, port=self.port,
            pid=os.getpid(),
        )
        self.worker_id = int(res["worker_id"])
        # MV export SST keys come from the meta (single allocator:
        # collision-free across workers, vacuum-protected until the
        # round's cluster epoch commits them into the manifest)
        self.engine.sst_key_allocator = lambda: self._meta_client.call(
            "alloc_sst", worker_id=self.worker_id
        )["key"]
        if heartbeat:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                name=f"worker-{self.worker_id}-hb", daemon=True,
            )
            self._hb_thread.start()
        return self

    def _heartbeat_loop(self) -> None:
        # independent of the engine lock: a worker busy compiling or
        # crossing a barrier still beats (liveness != idleness)
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                self._meta_client.call("heartbeat",
                                       worker_id=self.worker_id)
                self.heartbeats_sent += 1
            except Exception:
                # meta unreachable or expired us; keep trying — a
                # revived meta needs re-registration, which operators
                # do by restarting the worker
                time.sleep(self.heartbeat_interval_s)

    def stop(self) -> None:
        try:
            with self._lock:
                # orderly exit: sealed epochs finish becoming durable
                self.engine.drain_uploads()
        except Exception:  # noqa: BLE001 — a failed upload rewinds
            pass
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._meta_client is not None:
            self._meta_client.close()
            self._meta_client = None

    # -- RPC surface ----------------------------------------------------
    def rpc_ping(self) -> dict:
        return {"ok": True, "worker_id": self.worker_id,
                "jobs": [j.name for j in self.engine.jobs]}

    def rpc_adopt(self, ddl: list, name: str,
                  recover: bool = True) -> dict:
        """Adopt (or extend) a streaming job: replay its DDL, then
        recover from the last durable checkpoint (exact replay: the
        checkpoint holds state + source cursors of the same commit)."""
        with self._lock:
            epoch = self.engine.adopt_job(list(ddl), name,
                                          recover=recover)
        return {"ok": True, "committed_epoch": epoch}

    def rpc_barrier(self, job: str, chunks: int = 1) -> dict:
        """Process ``chunks`` chunks + one barrier for one job — the
        meta's global round, applied locally.  Returns the SEALED
        epoch immediately (the checkpoint upload runs in the job's
        background uploader) plus the round's MV export SSTs (row
        diffs uploaded to the shared store under meta-allocated keys;
        the META commits them into the manifest with the cluster
        epoch, so the serving tier reads every MV at the same round);
        meta polls ``job_epochs`` for the durable ack before
        committing the cluster epoch."""
        with self._lock:
            sealed = self.engine.tick_job(job, int(chunks))
            ssts = self.engine.export_mv_deltas(job, sealed)
            positions = self.engine.job_epochs(job)
        return {"ok": True, "committed_epoch": sealed,
                "sealed_epoch": sealed,
                "durable_epoch": positions["durable"],
                "ssts": ssts}

    def rpc_job_epochs(self, job: str) -> dict:
        """Seal-vs-durable positions of one job (also services its
        pending upload acks — see Engine.job_epochs)."""
        with self._lock:
            return self.engine.job_epochs(job)

    def rpc_serve(self, sql: str, query_epoch: int = 0) -> dict:
        """Batch read; ``query_epoch`` pins the retained checkpoint of
        the meta's last cluster commit (reads never see state a global
        commit hasn't covered)."""
        qe = int(query_epoch or 0)
        with self._lock:
            if qe:
                self.engine.session_config.set("query_epoch", qe)
            try:
                cols, rows = self.engine.query(sql)
            finally:
                if qe:
                    self.engine.session_config.set("query_epoch", 0)
        return {"cols": cols, "rows": [list(r) for r in rows]}

    def rpc_execute(self, sql: str) -> dict:
        with self._lock:
            self.engine.execute(sql)
        return {"ok": True}
