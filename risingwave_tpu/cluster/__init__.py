"""Cluster-lite control plane: meta service + compute workers over
localhost JSON-RPC (the multi-process split of the four node roles)."""

from risingwave_tpu.cluster.meta_service import (  # noqa: F401
    MetaFrontend,
    MetaService,
)
from risingwave_tpu.cluster.rpc import (  # noqa: F401
    RpcClient,
    RpcError,
    RpcServer,
    parse_addr,
)
from risingwave_tpu.cluster.worker import ComputeWorker  # noqa: F401
