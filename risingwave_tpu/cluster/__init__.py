"""Cluster-lite control plane: meta service + compute workers over
localhost JSON-RPC (the multi-process split of the four node roles).

Exports resolve lazily (PEP 562): ``meta_service``/``worker`` pull in
engine-side modules, but the engine-free serving tier only needs
``cluster.rpc`` — importing the package must stay jax-free.
"""

_LAZY = {
    "MetaFrontend": ("risingwave_tpu.cluster.meta_service",
                     "MetaFrontend"),
    "MetaService": ("risingwave_tpu.cluster.meta_service",
                    "MetaService"),
    "ComputeWorker": ("risingwave_tpu.cluster.worker", "ComputeWorker"),
    "Choreography": ("risingwave_tpu.cluster.exchange.planner",
                     "Choreography"),
    "ExchangePlanner": ("risingwave_tpu.cluster.exchange.planner",
                        "ExchangePlanner"),
    "ExchangeSpec": ("risingwave_tpu.cluster.exchange.planner",
                     "ExchangeSpec"),
    "ShuffleService": ("risingwave_tpu.cluster.exchange.shuffle",
                       "ShuffleService"),
    "ServingWorker": ("risingwave_tpu.serve.worker", "ServingWorker"),
    "RpcClient": ("risingwave_tpu.cluster.rpc", "RpcClient"),
    "RpcError": ("risingwave_tpu.cluster.rpc", "RpcError"),
    "RpcServer": ("risingwave_tpu.cluster.rpc", "RpcServer"),
    "parse_addr": ("risingwave_tpu.cluster.rpc", "parse_addr"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    value = getattr(importlib.import_module(mod_name), attr)
    globals()[name] = value
    return value
