"""Multi-chip parallelism: vnode sharding over a device mesh.

Reference counterpart: the dispatch/exchange layer (SURVEY.md §2.3
"Parallelism & distribution model") — hash dispatchers computing vnodes
(dispatch.rs:949), permit-based gRPC exchange, and merge alignment.

TPU restructuring (SURVEY.md §5.8): the vnode axis maps onto a mesh
axis; the hash shuffle is an ``all_to_all`` collective over ICI *inside*
the jitted step; barrier alignment degenerates to the host loop ticking
every shard in lockstep (SPMD).
"""

from risingwave_tpu.parallel.exchange import (
    shard_of_vnode,
    shuffle_chunk,
)

__all__ = ["shard_of_vnode", "shuffle_chunk"]
