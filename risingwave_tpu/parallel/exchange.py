"""Hash exchange: vnode partitioning + all_to_all shuffle.

Reference counterparts:
- ``HashDataDispatcher::dispatch_data`` — src/stream/src/executor/
  dispatch.rs:949 (vectorized vnode computation + per-output visibility
  bitmaps)
- ``StreamExchangeService.GetStream`` — proto/task_service.proto:156
  (credit-based chunk exchange)
- ``MergeExecutor`` alignment — src/stream/src/executor/merge.rs:161

TPU-first design
----------------
Inside a ``shard_map``-ed fragment step, each shard partitions its
output chunk into ``n_shards`` fixed-capacity buckets (scatter by
destination, visibility-masked) and one ``lax.all_to_all`` swaps bucket
``i→j`` over ICI.  The received buckets concatenate into a single
``n_shards*cap`` chunk — merge alignment is implicit because SPMD runs
every shard in lockstep per step (credits/permits are unnecessary:
backpressure is the synchronous dataflow itself).

Like the reference, routing is vnode-based (vnode = crc32(keys) %
VNODE_COUNT, then vnode→shard by contiguous ranges), so elastic rescale
= remapping vnode ranges at a barrier, and state follows vnodes.
"""

from __future__ import annotations

import inspect
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk, NCol, StrCol
from risingwave_tpu.common.hash import VNODE_COUNT, compute_vnodes

try:  # jax >= 0.8 (top-level export)
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_impl

#: the replication/varying-manual-axes check kwarg was renamed across
#: jax releases (check_rep -> check_vma); resolve the spelling once so
#: every shard_map site works on whichever jax the container bakes in
_CHECK_KW = next(
    (kw for kw in ("check_vma", "check_rep")
     if kw in inspect.signature(_shard_map_impl).parameters),
    None,
)


def shard_map_nocheck(body, *, mesh, in_specs, out_specs):
    """``shard_map`` with the replication check disabled, under whatever
    keyword this jax spells it (check_vma / check_rep) — the per-shard
    streaming bodies intentionally mix replicated and varying values."""
    kw = {_CHECK_KW: False} if _CHECK_KW else {}
    return _shard_map_impl(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


#: trace-time exchange audit (profile_q8 --assert --sharded): each
#: ``shuffle_chunk`` TRACE bumps ``calls`` and adds the per-shard
#: all_to_all payload bytes.  Programs compile once, so after a warm
#: run this reflects exactly what the compiled graphs contain — a
#: per-row or per-window exchange regression shows up as extra traced
#: calls/bytes, with zero steady-state cost (nothing runs on device).
EXCHANGE_TRACE = {"calls": 0, "bytes": 0}


def reset_exchange_trace() -> None:
    EXCHANGE_TRACE["calls"] = 0
    EXCHANGE_TRACE["bytes"] = 0


def _trace_bytes(x) -> int:
    return int(np.prod(x.shape)) * x.dtype.itemsize


def single_shard_keys(chunk) -> list:
    """Constant routing key: every row hashes to ONE owning shard.

    The device analog of the reference's singleton fragments (global
    aggs / global TopN need a total view): an all_to_all keyed on a
    constant routes the whole stream to whichever shard owns
    vnode(hash(0)), and the other shards run the same programs over
    empty chunks — byte-identical to the linear run at that shard."""
    return [jnp.zeros((chunk.capacity,), jnp.int64)]


def shard_of_vnode(vnodes: jnp.ndarray, n_shards: int,
                   vnode_count: int = VNODE_COUNT) -> jnp.ndarray:
    """Contiguous-range vnode→shard mapping (ref WorkerSlotMapping)."""
    if n_shards > vnode_count:
        raise ValueError(
            f"n_shards={n_shards} exceeds vnode_count={vnode_count}; raise "
            "the job's vnode count (ref: max 2^15 vnodes, vnode.rs:30)"
        )
    per = vnode_count // n_shards
    return jnp.minimum(vnodes // per, n_shards - 1).astype(jnp.int32)


def _bucketize(col, dest_slot: jnp.ndarray, n_shards: int, cap: int):
    """Scatter a [cap] column into [n_shards*cap] bucket-major layout."""
    if isinstance(col, NCol):
        return NCol(
            _bucketize(col.data, dest_slot, n_shards, cap),
            # unfilled bucket slots read as NULL (their validity is
            # False anyway, but NULL is the safe default payload)
            jnp.ones((n_shards * cap,), jnp.bool_).at[dest_slot].set(
                col.null, mode="drop"
            ),
        )
    if isinstance(col, StrCol):
        return StrCol(
            _bucketize(col.data, dest_slot, n_shards, cap),
            _bucketize(col.lens, dest_slot, n_shards, cap),
        )
    out = jnp.zeros((n_shards * cap,) + col.shape[1:], col.dtype)
    return out.at[dest_slot].set(col, mode="drop")


def shuffle_chunk(
    chunk: Chunk,
    key_cols: Sequence,
    axis_name: str,
    n_shards: int,
    vnode_count: int = VNODE_COUNT,
) -> Chunk:
    """Exchange a chunk's rows to their key-owning shards.

    Must be called inside ``shard_map``.  Returns the received chunk of
    capacity ``n_shards * cap`` (worst-case skew-safe: each sender may
    route its whole chunk to one shard).
    """
    cap = chunk.capacity
    vnodes = compute_vnodes(key_cols, vnode_count)
    dest = shard_of_vnode(vnodes, n_shards, vnode_count)
    dest = jnp.where(chunk.valid, dest, n_shards)  # invalid rows dropped

    # position within the destination bucket: stable rank among rows
    # with the same destination (argsort-of-argsort trick, shape-static)
    order = jnp.argsort(dest, stable=True)         # rows grouped by dest
    rank_in_sorted = jnp.zeros((cap,), jnp.int32)
    sorted_dest = dest[order]
    is_new_group = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_dest[1:] != sorted_dest[:-1]]
    )
    group_start = jax.lax.associative_scan(
        jnp.maximum,
        jnp.where(is_new_group, jnp.arange(cap, dtype=jnp.int32), 0),
    )
    rank_sorted = jnp.arange(cap, dtype=jnp.int32) - group_start
    rank_in_sorted = rank_in_sorted.at[order].set(rank_sorted)

    dest_slot = jnp.where(
        dest < n_shards, dest * cap + rank_in_sorted,
        jnp.int32(n_shards * cap),
    )

    cols = tuple(
        _bucketize(c, dest_slot, n_shards, cap) for c in chunk.columns
    )
    ops = _bucketize(chunk.ops, dest_slot, n_shards, cap)
    valid = jnp.zeros((n_shards * cap,), jnp.bool_).at[dest_slot].set(
        chunk.valid, mode="drop"
    )

    # swap bucket i of shard j to shard i (bucket-major leading axis)
    def a2a(x):
        r = x.reshape((n_shards, cap) + x.shape[1:])
        r = jax.lax.all_to_all(
            r, axis_name, split_axis=0, concat_axis=0, tiled=False
        )
        return r.reshape((n_shards * cap,) + x.shape[1:])

    def a2a_col(c):
        if isinstance(c, NCol):
            return NCol(a2a_col(c.data), a2a(c.null))
        if isinstance(c, StrCol):
            return StrCol(a2a(c.data), a2a(c.lens))
        return a2a(c)

    cols = tuple(a2a_col(c) for c in cols)
    ops = a2a(ops)
    valid = a2a(valid)
    EXCHANGE_TRACE["calls"] += 1
    EXCHANGE_TRACE["bytes"] += sum(
        _trace_bytes(x)
        for x in jax.tree.leaves((cols, ops, valid))
    )
    return Chunk(cols, ops, valid, chunk.schema)
