"""JSON-lines parser + rate-limited chunk builder.

Reference counterparts: ``src/connector/src/parser/`` (JsonParser and
the shared ``chunk_builder.rs`` with rate limiting) — the parser turns
raw connector payloads into typed ``StreamChunk``s, tolerating
malformed rows (counted, not fatal: the reference's parser error
policy).

TPU-first shape: parsing is HOST work at the ingest boundary (strings,
ragged bytes); the output is a fixed-capacity device ``Chunk`` whose
columns are dense numpy arrays — one host→device transfer per chunk,
nothing row-at-a-time on device.
"""

from __future__ import annotations

import json
from datetime import datetime, timezone

import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema


def _parse_ts_us(v) -> int:
    """Timestamp to int64 microseconds (ISO string, epoch s/ms/us)."""
    if isinstance(v, (int, float)):
        # heuristic magnitudes: s < 1e11, ms < 1e14, else us
        x = float(v)
        if abs(x) < 1e11:
            return int(x * 1_000_000)
        if abs(x) < 1e14:
            return int(x * 1_000)
        return int(x)
    s = str(v).replace("T", " ").replace("Z", "")
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is not None:
        dt = dt.astimezone(timezone.utc).replace(tzinfo=None)
    epoch = datetime(1970, 1, 1)
    return int((dt - epoch).total_seconds() * 1_000_000)


class JsonChunkBuilder:
    """Accumulate parsed JSON objects into fixed-capacity chunks.

    ``max_rows_per_chunk`` is the rate limit (ref chunk_builder.rs —
    the reference throttles source chunks to ``chunk_size``); rows
    beyond it stay queued for the next chunk.
    """

    def __init__(self, schema: Schema, max_rows_per_chunk: int = 4096):
        self.schema = schema
        self.max_rows = max_rows_per_chunk
        self._rows: list[tuple] = []
        #: malformed payloads skipped (ref parser error tolerance)
        self.parse_errors = 0

    def push_line(self, line: "str | bytes") -> bool:
        """Parse one JSON line into the pending row queue."""
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="replace")
        line = line.strip()
        if not line:
            return False
        try:
            obj = json.loads(line)
            row = []
            for f in self.schema:
                v = obj.get(f.name)
                if v is None:
                    if not f.nullable:
                        raise ValueError(f"missing NOT NULL {f.name}")
                    row.append(None)
                    continue
                t = f.data_type
                if t.is_string:
                    row.append(str(v))
                elif t in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
                    row.append(_parse_ts_us(v))
                elif t in (DataType.FLOAT32, DataType.FLOAT64,
                           DataType.DECIMAL):
                    row.append(float(v))
                elif t == DataType.BOOLEAN:
                    row.append(bool(v))
                else:
                    row.append(int(v))
            self._rows.append(tuple(row))
            return True
        except (ValueError, TypeError, json.JSONDecodeError):
            self.parse_errors += 1
            return False

    def pending(self) -> int:
        return len(self._rows)

    def next_chunk(self, capacity: int) -> Chunk:
        """Emit up to min(capacity, rate limit) rows as a device Chunk
        (possibly zero valid rows — shape-static by construction)."""
        n = min(len(self._rows), capacity, self.max_rows)
        batch, self._rows = self._rows[:n], self._rows[n:]
        if n == 0:
            arrays = [np.zeros((0,), np.int64) for _ in self.schema]
            return Chunk.from_numpy(self.schema, arrays,
                                    capacity=capacity)
        arrays = [
            np.asarray([r[i] for r in batch], dtype=object)
            if any(r[i] is None for r in batch)
            else np.asarray([r[i] for r in batch])
            for i in range(len(self.schema))
        ]
        return Chunk.from_numpy(self.schema, arrays, capacity=capacity)
