"""File-tailing external source: JSON lines streamed from disk.

Reference counterparts: the source abstraction
(``SplitEnumerator``/``SplitReader``, src/connector/src/source/
base.rs:222,596) and the filesystem sources (``source/filesystem/``) —
an external system feeding the dataflow, with resumable per-split
offsets that ride checkpoints (exactly-once ingest: on recovery the
reader seeks back to the last committed offset and replays).

One file = one split this round; a glob enumerates multiple files as
disjoint splits (``FileTailEnumerator``).  The reader tails the file:
rows appended after a chunk was consumed appear in later chunks — the
streaming contract, not a one-shot batch scan.

Offset semantics: ``state()`` reports, per file, the byte offset just
past the last row EMITTED into the dataflow (parsed-but-unemitted rows
roll back and replay on recovery) — so the checkpointed cursor is
exactly the reference's "offsets ride the checkpoint" contract.
"""

from __future__ import annotations

import glob as _glob
import os

from risingwave_tpu.common.types import Schema
from risingwave_tpu.connector.json_parser import JsonChunkBuilder


class FileTailEnumerator:
    """Split discovery: one split per glob match (ref SplitEnumerator)."""

    def __init__(self, pattern: str):
        self.pattern = pattern

    def splits(self) -> list[str]:
        return sorted(_glob.glob(self.pattern))


class FileTailSplitReader:
    """Tail one or more JSONL files from resumable byte offsets."""

    def __init__(self, path: str, schema: Schema, chunk_capacity: int,
                 split_id: int = 0, num_splits: int = 1,
                 max_rows_per_chunk: int | None = None):
        self.schema = schema
        self.cap = chunk_capacity
        self.pattern = path
        enum = FileTailEnumerator(path)
        files = enum.splits()
        #: this reader's assigned splits (disjoint by round-robin, the
        #: reference's split assignment from meta)
        self.files = files[split_id::num_splits] if files else []
        if not self.files and num_splits == 1 and "*" not in path:
            # a not-yet-created file is legal for a tailing source
            self.files = [path]
        #: read position per file (includes parsed-but-unemitted rows)
        self.offsets: dict[str, int] = {f: 0 for f in self.files}
        #: committed position per file: end of the last EMITTED row
        self.emitted_offsets: dict[str, int] = {f: 0 for f in self.files}
        self._carry: dict[str, bytes] = {f: b"" for f in self.files}
        #: FIFO of (path, end_offset) per pending parsed row — parallel
        #: to the builder's row queue (malformed rows advance offsets
        #: immediately: they are skipped identically on replay)
        self._row_ends: list[tuple[str, int]] = []
        self.builder = JsonChunkBuilder(
            schema, max_rows_per_chunk or chunk_capacity
        )

    # -- streaming ------------------------------------------------------
    def _poll(self) -> None:
        """Read newly appended bytes up to the next newline boundary."""
        budget = self.cap * 4  # rows; bounded host work per poll
        for path in self.files:
            if self.builder.pending() >= budget:
                break
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                f.seek(self.offsets[path])
                fresh = f.read(1 << 20)
            if not fresh:
                continue
            data = self._carry[path] + fresh
            base = self.offsets[path] - len(self._carry[path])
            lines = data.split(b"\n")
            tail = lines.pop()  # partial last line stays carried
            pos = base
            for ln in lines:
                pos += len(ln) + 1
                if self.builder.push_line(ln):
                    self._row_ends.append((path, pos))
                else:
                    # skipped (blank/malformed): committed cursor may
                    # advance past it once prior rows emit
                    if not self._row_ends:
                        self.emitted_offsets[path] = pos
            self._carry[path] = tail
            self.offsets[path] = base + len(data)

    def next_chunk(self):
        self._poll()
        before = self.builder.pending()
        chunk = self.builder.next_chunk(self.cap)
        emitted = before - self.builder.pending()
        for _ in range(emitted):
            path, end = self._row_ends.pop(0)
            self.emitted_offsets[path] = end
        return chunk

    def pending(self) -> int:
        return self.builder.pending()

    # -- checkpointed cursor --------------------------------------------
    def state(self) -> dict:
        return {"offsets": dict(self.emitted_offsets)}

    def restore(self, st: dict) -> None:
        for p, off in st.get("offsets", {}).items():
            if p in self.offsets:
                self.offsets[p] = off
                self.emitted_offsets[p] = off
                self._carry[p] = b""
        self._row_ends = []
        self.builder = JsonChunkBuilder(self.schema,
                                        self.builder.max_rows)
