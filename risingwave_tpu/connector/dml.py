"""DML: user tables fed by INSERT statements.

Reference counterpart: ``src/dml`` (``DmlManager``,
src/dml/src/dml_manager.rs) — frontend DML batches flow through
channels into every dataflow reading the table — and the table source
executor (``dml.rs``).

Here a ``TableDmlManager`` per table fans each INSERT batch out to one
queue per downstream job reader; readers emit fixed-capacity chunks
(possibly with zero valid rows when idle — shape-static by
construction).
"""

from __future__ import annotations

from collections import deque
from typing import Sequence

import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import Schema


class TableDmlManager:
    """Fan-out of INSERT batches to all readers of one table.

    The full history is retained so readers created later (new MVs)
    replay earlier inserts — the poor-man's backfill (the reference
    backfills new MVs from the table's state; a bounded log + real
    backfill executor land with the storage round)."""

    def __init__(self, schema: Schema):
        self.schema = schema
        self._readers: list["TableSourceReader"] = []
        self._history: list[tuple] = []
        self.rows_inserted = 0

    def new_reader(self, chunk_capacity: int) -> "TableSourceReader":
        r = TableSourceReader(self.schema, chunk_capacity)
        r.enqueue(self._history)  # replay everything inserted so far
        self._readers.append(r)
        return r

    def insert(self, rows: Sequence[tuple]) -> int:
        rows = list(rows)
        self._history.extend(rows)
        for r in self._readers:
            r.enqueue(rows)
        self.rows_inserted += len(rows)
        return len(rows)


class TableSourceReader:
    """Queue-fed source reader; empty chunks when idle."""

    def __init__(self, schema: Schema, chunk_capacity: int):
        self.schema = schema
        self.cap = chunk_capacity
        self._pending: deque[tuple] = deque()
        #: consumed-row offset (checkpointable like any source cursor;
        #: replay of unread DML after recovery is the caller's concern
        #: until the log-store lands)
        self.offset = 0

    def enqueue(self, rows: Sequence[tuple]) -> None:
        self._pending.extend(rows)

    def pending(self) -> int:
        return len(self._pending)

    def next_chunk(self) -> Chunk:
        n = min(len(self._pending), self.cap)
        batch = [self._pending.popleft() for _ in range(n)]
        self.offset += n
        if n == 0:
            # shape-static empty chunk
            arrays = [np.zeros((0,), np.int64) for _ in self.schema]
            return Chunk.from_numpy(self.schema, arrays, capacity=self.cap)
        arrays = [
            np.asarray([row[i] for row in batch])
            for i in range(len(self.schema))
        ]
        return Chunk.from_numpy(self.schema, arrays, capacity=self.cap)

    def state(self) -> dict:
        return {"offset": self.offset}
