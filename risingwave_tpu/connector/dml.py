"""DML: user tables fed by INSERT statements.

Reference counterpart: ``src/dml`` (``DmlManager``,
src/dml/src/dml_manager.rs) — frontend DML batches flow through
channels into every dataflow reading the table — and the table source
executor (``dml.rs``).

Here a ``TableDmlManager`` per table fans each INSERT batch out to one
queue per downstream job reader; readers emit fixed-capacity chunks
(possibly with zero valid rows when idle — shape-static by
construction).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from risingwave_tpu.common.chunk import OP_DELETE, OP_INSERT, Chunk
from risingwave_tpu.common.types import Schema

#: marker-tail retraction encoding: a DELETE row is the full old row
#: with this sentinel appended PAST the schema width.  Every existing
#: path — vnode hashing (row[key_col]), width checks (schema string
#: column indices), exchange slicing, fence repair, JSON durability —
#: indexes rows by schema position, so marked rows ride all of them
#: untouched; only the source reader looks at the tail to derive the
#: chunk op.
DELETE_MARK = "__rwt_delete__"


def mark_deletes(rows, width: int) -> list[tuple]:
    """Append the delete marker to full-width rows (idempotent)."""
    return [tuple(r) if len(r) > width else tuple(r) + (DELETE_MARK,)
            for r in rows]


def row_is_delete(row, width: int) -> bool:
    return len(row) > width and row[width] == DELETE_MARK


class TableDmlManager:
    """Fan-out of INSERT batches to all readers of one table.

    The full history is retained so readers created later (new MVs)
    replay earlier inserts — the poor-man's backfill (the reference
    backfills new MVs from the table's state; a bounded log + real
    backfill executor land with the storage round)."""

    def __init__(self, schema: Schema, auto_width_cols=()):
        self.schema = schema
        self._readers: list["TableSourceReader"] = []
        #: the position-stamped history.  On the ingest leader every
        #: position holds a row; on a shuffled follower positions the
        #: worker does not own hold ``None`` PLACEHOLDERS — positions
        #: stay GLOBAL, so source cursors, round fences, and handover
        #: cursor checks all live in one shared domain (Exchange-lite)
        self._history: list = []
        #: per-position distribution-key vnode (parallel to
        #: ``_history``; -1 = unknown / not a shuffled table).  Every
        #: sliced delivery carries the full batch's vnode log, so ANY
        #: host can audit which global positions its owned set covers
        #: even for rows it never stored
        self._vnodes: list[int] = []
        self.rows_inserted = 0
        #: columns whose VARCHAR device width was NOT declared: their
        #: width follows the observed max (refresh_schema), never
        #: truncating — the reference's VARCHAR is unbounded
        #: (utf8_array.rs); a fixed-width device column must instead be
        #: sized from the data before programs compile against it
        self.auto_width_cols = set(auto_width_cols)
        self._max_lens = {i: 0 for i in self.auto_width_cols}

    def new_reader(self, chunk_capacity: int) -> "TableSourceReader":
        # the reader shares the history list: it starts at offset 0, so
        # everything inserted so far replays (poor-man's backfill).
        # The vnode log rides along so a filtered reader classifies
        # stamped positions without re-hashing them.
        r = TableSourceReader(self.schema, chunk_capacity,
                              self._history, vnode_log=self._vnodes)
        self._readers.append(r)
        return r

    # -- cluster replication (worker↔worker exchange) -------------------
    def history_len(self) -> int:
        """Current history position (the exchange sequence number)."""
        return len(self._history)

    def history_slice(self, lo: int, hi: int | None = None) -> list:
        """Rows [lo, hi) of the history — the peer catch-up payload.
        Placeholder positions come back as ``None``."""
        return [list(r) if r is not None else None for r in
                (self._history[lo:] if hi is None
                 else self._history[lo:hi])]

    def history_row(self, pos: int):
        """One position's row (None = placeholder / out of range)."""
        return self._history[pos] if 0 <= pos < len(self._history) \
            else None

    def vnode_at(self, pos: int) -> int | None:
        """The position's recorded dist-key vnode (None = unknown)."""
        if 0 <= pos < len(self._vnodes) and self._vnodes[pos] >= 0:
            return self._vnodes[pos]
        return None

    def vnode_slice(self, lo: int, hi: int) -> list[int]:
        out = self._vnodes[lo:hi]
        out += [-1] * ((hi - lo) - len(out))
        return out

    def set_vnode_log(self, positions_vnodes) -> None:
        """Record dist-key vnodes for known positions (the ingest
        leader stamps its own batches after hashing them once)."""
        for pos, vn in positions_vnodes:
            if pos >= len(self._vnodes):
                self._vnodes += [-1] * (pos + 1 - len(self._vnodes))
            self._vnodes[pos] = int(vn)

    def set_vnode_range(self, seq: int, vnodes) -> None:
        """Bulk vnode-log stamp for one contiguous batch [seq, seq+n)
        (the per-batch fast path — one slice assignment, no per-row
        loop)."""
        end = seq + len(vnodes)
        if end > len(self._vnodes):
            self._vnodes += [-1] * (end - len(self._vnodes))
        vals = [int(v) for v in vnodes]
        if all(v >= 0 for v in vals):
            self._vnodes[seq:end] = vals
        else:  # never DOWNGRADE a known vnode to unknown (-1)
            for i, v in enumerate(vals):
                if v >= 0:
                    self._vnodes[seq + i] = v

    def missing_positions(self, vnodes, lo: int, hi: int) -> list[int]:
        """Global positions in [lo, hi) whose recorded vnode falls in
        ``vnodes`` but whose row is a local placeholder — the
        completeness audit behind fence gap repair (a follower must
        hold every OWNED row below the round fence, not merely have a
        long enough history)."""
        want = {int(v) for v in vnodes}
        hi = min(hi, len(self._history))
        return [
            p for p in range(lo, hi)
            if self._history[p] is None
            and (p < len(self._vnodes) and self._vnodes[p] in want)
        ]

    def insert_at(self, seq: int, rows: Sequence[tuple]) -> int:
        """Position-stamped idempotent append (exchange delivery): the
        batch claims positions [seq, seq+len).  Rows already present
        are skipped (duplicate delivery); a batch starting beyond the
        current length is REFUSED (the caller fills the gap from the
        leader first).  Returns rows actually appended."""
        here = len(self._history)
        if seq > here:
            raise ValueError(
                f"exchange gap: batch at seq {seq}, history at {here}"
            )
        fresh = [tuple(r) for r in rows[here - seq:]]
        if fresh:
            self.insert(fresh)
        return len(fresh)

    def insert_sparse(self, seq: int, end: int, items,
                      vnodes=()) -> int:
        """Sliced exchange delivery: claim GLOBAL positions
        [seq, end), placing only the owned rows in ``items``
        (``[(pos, row), ...]``) and ``None`` placeholders elsewhere.
        Re-delivery is idempotent; positions already holding a row are
        never overwritten, but placeholder HOLES are filled (that is
        what makes gained-vnode backfill after a repartition a plain
        re-send).  A batch starting beyond the local tail is refused
        exactly like ``insert_at``.  Returns rows actually placed."""
        here = len(self._history)
        if seq > here:
            raise ValueError(
                f"exchange gap: batch at seq {seq}, history at {here}"
            )
        if end > here:
            self._history += [None] * (end - here)
        fresh = [(int(p), tuple(r)) for p, r in items
                 if seq <= int(p) < end
                 and self._history[int(p)] is None]
        if fresh:
            self._check_widths([r for _, r in fresh])
            for p, r in fresh:
                self._history[p] = r
            self.rows_inserted += len(fresh)
        if vnodes:
            self.set_vnode_range(seq, vnodes)
        return len(fresh)

    def _check_widths(self, rows: Sequence[tuple]) -> None:
        # one pass: per-string-column max encoded length of this batch
        str_cols = [i for i, f in enumerate(self.schema)
                    if f.data_type.is_string]
        batch_max = {i: 0 for i in str_cols}
        for row in rows:
            for i in str_cols:
                v = row[i]
                if isinstance(v, str):
                    n = len(v.encode("utf-8"))
                    if n > batch_max[i]:
                        batch_max[i] = n
        # a string longer than a live reader's compiled width would be
        # silently truncated in that dataflow — refuse loudly instead
        # (batch max vs the narrowest reader: O(readers x columns)).
        # Validated BEFORE _max_lens folds the batch in: a rejected
        # batch must not inflate future auto widths.
        for i in str_cols:
            for r in self._readers:
                f = r.schema[i]
                if batch_max[i] > f.str_width:
                    raise ValueError(
                        f"value for {f.name!r} exceeds the width "
                        f"({f.str_width}B) a running job compiled "
                        "against; declare VARCHAR(n) wide enough "
                        "before creating views on this table"
                    )
        for i in self._max_lens:
            self._max_lens[i] = max(self._max_lens[i], batch_max[i])

    def insert(self, rows: Sequence[tuple],
               delete: bool = False) -> int:
        rows = list(rows)
        if delete:
            rows = mark_deletes(rows, len(self.schema))
        self._check_widths(rows)
        self._history.extend(rows)  # readers see this shared list
        self.rows_inserted += len(rows)
        return len(rows)

    def refresh_schema(self) -> Schema:
        """Re-derive auto varchar widths from observed data.

        Called by the engine before planning a new job on this table;
        widths only grow (multiple-of-8, floor = the field's current
        width) so already-compiled readers stay valid."""
        from dataclasses import replace

        fields = list(self.schema)
        for i in self.auto_width_cols:
            need = self._max_lens[i]
            if need > fields[i].str_width:
                fields[i] = replace(
                    fields[i], str_width=-(-need // 8) * 8
                )
        self.schema = Schema(tuple(fields))
        return self.schema


class TableSourceReader:
    """Cursor over the table's shared history log; empty chunks when
    idle.

    NON-destructive: rows are never popped, only the ``offset`` cursor
    advances — so recovery can REWIND the cursor and replay rows that
    were consumed but not yet committed (a destructive queue silently
    lost them; the reference's DML replays from the upstream table's
    durable state, here the history list is that log)."""

    def __init__(self, schema: Schema, chunk_capacity: int,
                 history: list, vnode_log: list | None = None):
        self.schema = schema
        self.cap = chunk_capacity
        #: shared with TableDmlManager._history (no copy)
        self._rows = history
        #: shared with TableDmlManager._vnodes (no copy): positions
        #: the exchange already stamped skip the filter's hash
        self._vnode_log = vnode_log if vnode_log is not None else []
        #: consumed-row cursor into the table history (checkpointable)
        self.offset = 0
        #: consumption fence (cluster lockstep rounds): rows at or
        #: beyond this history position are invisible until the meta
        #: raises it — every partition of a job consumes the IDENTICAL
        #: prefix per round, so cursors stay aligned across workers
        self.limit: int | None = None
        #: Exchange-lite shuffled consumption: ``(key_col, owned_set,
        #: n_vnodes)`` or None.  With a filter set the reader packs
        #: each chunk with up to ``cap`` OWNED rows (skipping
        #: placeholders and non-owned rows) — the VnodeGate downstream
        #: becomes a correctness assert instead of the workhorse, and
        #: a partition's per-round work shrinks to its share of the
        #: stream (what makes ingest throughput track worker count)
        self.vnode_filter: tuple | None = None
        #: rows the filter skipped because their vnode was not owned
        #: (zero on a correctly shuffled follower: non-owned positions
        #: are placeholders there, not rows)
        self.filtered_rows = 0

    def pending(self) -> int:
        # a restored offset may exceed the in-process history (fresh
        # process, history not yet replayed): never negative — the
        # cursor simply has nothing to read until history catches up
        end = len(self._rows)
        if self.limit is not None:
            end = min(end, self.limit)
        return max(0, end - self.offset)

    def _owns(self, row) -> bool:
        key_col, owned, n_vn = self.vnode_filter
        from risingwave_tpu.cluster.exchange.shuffle import (
            vnodes_of_rows,
        )

        return vnodes_of_rows([row], key_col, n_vn)[0] in owned

    def next_chunk(self) -> Chunk:
        end = len(self._rows)
        if self.limit is not None:
            end = min(end, self.limit)
        batch: list = []
        if self.vnode_filter is None:
            while self.offset < end and len(batch) < self.cap:
                row = self._rows[self.offset]
                self.offset += 1
                if row is not None:
                    batch.append(row)
        else:
            # batched host hashing: classify a whole window at once
            # (one numpy hash per window, not per row)
            from risingwave_tpu.cluster.exchange.shuffle import (
                vnodes_of_rows,
            )

            key_col, owned, n_vn = self.vnode_filter
            log = self._vnode_log
            n_log = len(log)
            while self.offset < end and len(batch) < self.cap:
                stop = min(end, self.offset + self.cap)
                window_pos = [p for p in range(self.offset, stop)
                              if self._rows[p] is not None]
                if not window_pos:
                    self.offset = stop
                    continue
                # stamped positions classify straight off the shared
                # vnode log; only un-stamped rows (pre-choreography
                # history) pay one batched hash
                vns = [log[p] if p < n_log else -1
                       for p in window_pos]
                unknown = [i for i, v in enumerate(vns) if v < 0]
                if unknown:
                    hashed = vnodes_of_rows(
                        [self._rows[window_pos[i]] for i in unknown],
                        key_col, n_vn,
                    )
                    for i, v in zip(unknown, hashed):
                        vns[i] = v
                consumed_to = stop
                for p, v in zip(window_pos, vns):
                    if len(batch) >= self.cap:
                        # cursor parks at the first unconsumed row
                        consumed_to = p
                        break
                    if v in owned:
                        batch.append(self._rows[p])
                    else:
                        self.filtered_rows += 1
                self.offset = consumed_to
        if not batch:
            # shape-static empty chunk
            arrays = [np.zeros((0,), np.int64) for _ in self.schema]
            return Chunk.from_numpy(self.schema, arrays, capacity=self.cap)
        arrays = [
            np.asarray([row[i] for row in batch])
            for i in range(len(self.schema))
        ]
        # marker-tail rows become OP_DELETE changelog entries here —
        # the single point where the retraction encoding is decoded
        width = len(self.schema)
        ops = np.asarray(
            [OP_DELETE if row_is_delete(row, width) else OP_INSERT
             for row in batch], np.int8)
        return Chunk.from_numpy(self.schema, arrays, ops=ops,
                                capacity=self.cap)

    def state(self) -> dict:
        return {"offset": self.offset}

    def restore(self, state: dict) -> None:
        self.offset = int(state.get("offset", 0))
