"""DML: user tables fed by INSERT statements.

Reference counterpart: ``src/dml`` (``DmlManager``,
src/dml/src/dml_manager.rs) — frontend DML batches flow through
channels into every dataflow reading the table — and the table source
executor (``dml.rs``).

Here a ``TableDmlManager`` per table fans each INSERT batch out to one
queue per downstream job reader; readers emit fixed-capacity chunks
(possibly with zero valid rows when idle — shape-static by
construction).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import Schema


class TableDmlManager:
    """Fan-out of INSERT batches to all readers of one table.

    The full history is retained so readers created later (new MVs)
    replay earlier inserts — the poor-man's backfill (the reference
    backfills new MVs from the table's state; a bounded log + real
    backfill executor land with the storage round)."""

    def __init__(self, schema: Schema, auto_width_cols=()):
        self.schema = schema
        self._readers: list["TableSourceReader"] = []
        self._history: list[tuple] = []
        self.rows_inserted = 0
        #: columns whose VARCHAR device width was NOT declared: their
        #: width follows the observed max (refresh_schema), never
        #: truncating — the reference's VARCHAR is unbounded
        #: (utf8_array.rs); a fixed-width device column must instead be
        #: sized from the data before programs compile against it
        self.auto_width_cols = set(auto_width_cols)
        self._max_lens = {i: 0 for i in self.auto_width_cols}

    def new_reader(self, chunk_capacity: int) -> "TableSourceReader":
        # the reader shares the history list: it starts at offset 0, so
        # everything inserted so far replays (poor-man's backfill)
        r = TableSourceReader(self.schema, chunk_capacity, self._history)
        self._readers.append(r)
        return r

    # -- cluster replication (worker↔worker exchange) -------------------
    def history_len(self) -> int:
        """Current history position (the exchange sequence number)."""
        return len(self._history)

    def history_slice(self, lo: int, hi: int | None = None) -> list:
        """Rows [lo, hi) of the history — the peer catch-up payload."""
        return [list(r) for r in
                (self._history[lo:] if hi is None
                 else self._history[lo:hi])]

    def insert_at(self, seq: int, rows: Sequence[tuple]) -> int:
        """Position-stamped idempotent append (exchange delivery): the
        batch claims positions [seq, seq+len).  Rows already present
        are skipped (duplicate delivery); a batch starting beyond the
        current length is REFUSED (the caller fills the gap from the
        leader first).  Returns rows actually appended."""
        here = len(self._history)
        if seq > here:
            raise ValueError(
                f"exchange gap: batch at seq {seq}, history at {here}"
            )
        fresh = [tuple(r) for r in rows[here - seq:]]
        if fresh:
            self.insert(fresh)
        return len(fresh)

    def insert(self, rows: Sequence[tuple]) -> int:
        rows = list(rows)
        # one pass: per-string-column max encoded length of this batch
        str_cols = [i for i, f in enumerate(self.schema)
                    if f.data_type.is_string]
        batch_max = {i: 0 for i in str_cols}
        for row in rows:
            for i in str_cols:
                v = row[i]
                if isinstance(v, str):
                    n = len(v.encode("utf-8"))
                    if n > batch_max[i]:
                        batch_max[i] = n
        # a string longer than a live reader's compiled width would be
        # silently truncated in that dataflow — refuse loudly instead
        # (batch max vs the narrowest reader: O(readers x columns)).
        # Validated BEFORE _max_lens folds the batch in: a rejected
        # batch must not inflate future auto widths.
        for i in str_cols:
            for r in self._readers:
                f = r.schema[i]
                if batch_max[i] > f.str_width:
                    raise ValueError(
                        f"value for {f.name!r} exceeds the width "
                        f"({f.str_width}B) a running job compiled "
                        "against; declare VARCHAR(n) wide enough "
                        "before creating views on this table"
                    )
        for i in self._max_lens:
            self._max_lens[i] = max(self._max_lens[i], batch_max[i])
        self._history.extend(rows)  # readers see this shared list
        self.rows_inserted += len(rows)
        return len(rows)

    def refresh_schema(self) -> Schema:
        """Re-derive auto varchar widths from observed data.

        Called by the engine before planning a new job on this table;
        widths only grow (multiple-of-8, floor = the field's current
        width) so already-compiled readers stay valid."""
        from dataclasses import replace

        fields = list(self.schema)
        for i in self.auto_width_cols:
            need = self._max_lens[i]
            if need > fields[i].str_width:
                fields[i] = replace(
                    fields[i], str_width=-(-need // 8) * 8
                )
        self.schema = Schema(tuple(fields))
        return self.schema


class TableSourceReader:
    """Cursor over the table's shared history log; empty chunks when
    idle.

    NON-destructive: rows are never popped, only the ``offset`` cursor
    advances — so recovery can REWIND the cursor and replay rows that
    were consumed but not yet committed (a destructive queue silently
    lost them; the reference's DML replays from the upstream table's
    durable state, here the history list is that log)."""

    def __init__(self, schema: Schema, chunk_capacity: int,
                 history: list):
        self.schema = schema
        self.cap = chunk_capacity
        #: shared with TableDmlManager._history (no copy)
        self._rows = history
        #: consumed-row cursor into the table history (checkpointable)
        self.offset = 0
        #: consumption fence (cluster lockstep rounds): rows at or
        #: beyond this history position are invisible until the meta
        #: raises it — every partition of a job consumes the IDENTICAL
        #: prefix per round, so cursors stay aligned across workers
        self.limit: int | None = None

    def pending(self) -> int:
        # a restored offset may exceed the in-process history (fresh
        # process, history not yet replayed): never negative — the
        # cursor simply has nothing to read until history catches up
        end = len(self._rows)
        if self.limit is not None:
            end = min(end, self.limit)
        return max(0, end - self.offset)

    def next_chunk(self) -> Chunk:
        n = min(self.pending(), self.cap)
        batch = self._rows[self.offset:self.offset + n]
        self.offset += n
        if n == 0:
            # shape-static empty chunk
            arrays = [np.zeros((0,), np.int64) for _ in self.schema]
            return Chunk.from_numpy(self.schema, arrays, capacity=self.cap)
        arrays = [
            np.asarray([row[i] for row in batch])
            for i in range(len(self.schema))
        ]
        return Chunk.from_numpy(self.schema, arrays, capacity=self.cap)

    def state(self) -> dict:
        return {"offset": self.offset}

    def restore(self, state: dict) -> None:
        self.offset = int(state.get("offset", 0))
