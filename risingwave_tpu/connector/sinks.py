"""Sink connectors: deliver MV changelogs to external systems.

Reference counterpart: ``src/connector/src/sink/`` — the ``Sink``/
``SinkWriter`` traits (sink/mod.rs:773, writer.rs:33) with per-epoch
commit barriers.  Round 1 ships the in-repo sinks (blackhole for
benchmarking, jsonl/csv files with epoch commit records); kafka/iceberg
land behind the same interface when external IO is available.
"""

from __future__ import annotations

import json
import os
from typing import Sequence

_OPS = {0: "insert", 1: "delete", 2: "update_delete", 3: "update_insert"}


class Sink:
    """Write changelog batches; commit at checkpoint epochs."""

    def write_batch(self, column_names: Sequence[str], ops, rows) -> None:
        raise NotImplementedError

    def commit(self, epoch: int) -> None:
        """Barrier commit (ref SinkWriter::barrier(checkpoint=true))."""

    def close(self) -> None:
        pass


class BlackholeSink(Sink):
    """Counts rows, delivers nowhere (ref blackhole; benchmarking)."""

    def __init__(self, **_options):
        self.rows_written = 0
        self.commits = 0

    def write_batch(self, column_names, ops, rows) -> None:
        self.rows_written += len(rows)

    def commit(self, epoch: int) -> None:
        self.commits += 1


class FileSink(Sink):
    """Append-mode jsonl/csv file sink with epoch commit markers.

    Each row becomes one line; checkpoint commits fsync and append a
    commit record so a reader can take only closed epochs (the
    poor-man's exactly-once of the reference's file sinks).
    """

    def __init__(self, path: str, format: str = "jsonl", **_options):
        self.path = path
        self.format = format
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def write_batch(self, column_names, ops, rows) -> None:
        for op, row in zip(ops, rows):
            if self.format == "csv":
                vals = ",".join(str(v) for v in row)
                self._f.write(f"{_OPS[int(op)]},{vals}\n")
            else:
                rec = {"op": _OPS[int(op)]}
                rec.update(zip(column_names, (
                    v.item() if hasattr(v, "item") else v for v in row
                )))
                self._f.write(json.dumps(rec) + "\n")

    def commit(self, epoch: int) -> None:
        if self.format == "csv":
            self._f.write(f"__commit__,{epoch}\n")
        else:
            self._f.write(json.dumps({"op": "commit", "epoch": epoch}) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()


SINK_REGISTRY = {
    "blackhole": BlackholeSink,
    "file": FileSink,
}


def create_sink(options: dict) -> Sink:
    connector = options.get("connector")
    if connector not in SINK_REGISTRY:
        raise ValueError(
            f"unsupported sink connector {connector!r} "
            f"(available: {sorted(SINK_REGISTRY)})"
        )
    kwargs = {k: v for k, v in options.items() if k != "connector"}
    return SINK_REGISTRY[connector](**kwargs)
