"""Connectors: sources and sinks.

Reference counterpart: ``src/connector`` (SURVEY.md §2.6).  Round 1
ships the benchmark-critical native generators (nexmark, datagen); the
external-system surface (kafka etc.) lands behind the same
``SplitEnumerator``/``SplitReader`` abstractions in later rounds.
"""
