"""Native Nexmark event generator, vectorized and device-resident.

Reference counterpart: ``src/connector/src/source/nexmark/`` (the
reference wraps the `nexmark` crate's sequential generator; proportions
and id chaining follow the canonical Beam/Flink NEXMark generator).

TPU-first design
----------------
The canonical generator is a sequential RNG walk.  Here every random
field is derived from a *counter-based* hash of the global event number
(splitmix64 mix), so generation is a pure vectorized function of an
index vector — a whole chunk of events materializes as one fused XLA
program directly on device, and any split/offset is addressable O(1)
(seek = arithmetic, which also makes checkpoint/resume trivial: the
source offset IS the event counter).

Event layout per 50-event epoch (canonical proportions 1:3:46):
  offset 0       -> Person
  offset 1..3    -> Auction
  offset 4..49   -> Bid
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk, StrCol, encode_strings
from risingwave_tpu.common.types import DataType, Field, Schema

PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
BID_PROPORTION = 46
TOTAL_PROPORTION = 50

FIRST_PERSON_ID = 1000
FIRST_AUCTION_ID = 1000
FIRST_CATEGORY_ID = 10

NUM_CATEGORIES = 5
HOT_AUCTION_RATIO = 100
HOT_BIDDER_RATIO = 100
HOT_SELLER_RATIO = 100
ACTIVE_PEOPLE = 1000
IN_FLIGHT_AUCTIONS = 100

#: default synthetic start time (unix micros) — 2015-07-15, as in Beam's
#: BASE_TIME, so q5/q7 window math exercises realistic timestamps.
BASE_TIME_US = 1_436_918_400_000_000


# ---------------------------------------------------------------------------
# counter-based randomness

_K1 = np.uint64(0x9E3779B97F4A7C15)
_K2 = np.uint64(0xBF58476D1CE4E5B9)
_K3 = np.uint64(0x94D049BB133111EB)


def _mix(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> np.uint64(30))) * _K2
    x = (x ^ (x >> np.uint64(27))) * _K3
    return x ^ (x >> np.uint64(31))


def _rand(event_id: jnp.ndarray, stream: int) -> jnp.ndarray:
    """uint64 uniform random, keyed on (event id, field stream)."""
    stream_key = np.uint64((stream * int(_K3)) & 0xFFFFFFFFFFFFFFFF)
    return _mix(event_id.astype(jnp.uint64) * _K1 ^ stream_key)


def _rand_int(event_id, stream: int, bound: int) -> jnp.ndarray:
    return (_rand(event_id, stream) % np.uint64(bound)).astype(jnp.int64)


def _rand_unit(event_id, stream: int) -> jnp.ndarray:
    """float64 in [0,1)."""
    return (_rand(event_id, stream) >> np.uint64(11)).astype(jnp.float64) / np.float64(
        1 << 53
    )


# ---------------------------------------------------------------------------
# id chaining (canonical generator arithmetic, vectorized)


def _last_base0_person_id(event_number: jnp.ndarray) -> jnp.ndarray:
    epoch = event_number // TOTAL_PROPORTION
    offset = jnp.minimum(event_number % TOTAL_PROPORTION, PERSON_PROPORTION - 1)
    return epoch * PERSON_PROPORTION + offset


def _last_base0_auction_id(event_number: jnp.ndarray) -> jnp.ndarray:
    epoch = event_number // TOTAL_PROPORTION
    offset = event_number % TOTAL_PROPORTION
    before_auctions = offset < PERSON_PROPORTION
    epoch = jnp.where(before_auctions, epoch - 1, epoch)
    offset = jnp.where(
        before_auctions,
        AUCTION_PROPORTION - 1,
        jnp.minimum(offset - PERSON_PROPORTION, AUCTION_PROPORTION - 1),
    )
    return epoch * AUCTION_PROPORTION + offset


def _next_base0_person_id(event_id: jnp.ndarray, stream: int) -> jnp.ndarray:
    """A person among the last ACTIVE_PEOPLE (canonical nextBase0PersonId)."""
    num_people = _last_base0_person_id(event_id) + 1
    active = jnp.minimum(num_people, ACTIVE_PEOPLE)
    lo = num_people - active
    return lo + _rand_int(event_id, stream, ACTIVE_PEOPLE + 1).clip(max=active)


def _next_base0_auction_id(event_id: jnp.ndarray, stream: int) -> jnp.ndarray:
    min_auction = jnp.maximum(
        _last_base0_auction_id(event_id) - IN_FLIGHT_AUCTIONS, 0
    )
    max_auction = _last_base0_auction_id(event_id)
    span = max_auction - min_auction + 1
    return min_auction + (_rand(event_id, stream) % span.astype(jnp.uint64)).astype(
        jnp.int64
    )


def _next_price(event_id: jnp.ndarray, stream: int) -> jnp.ndarray:
    """Canonical nextPrice: round(10^(U*6) * 100) — long-tail prices."""
    u = _rand_unit(event_id, stream)
    return jnp.round(10.0 ** (u * 6.0) * 100.0).astype(jnp.int64)


# ---------------------------------------------------------------------------
# schemas (ref: e2e_test/nexmark/create_sources.slt.part)

BID_SCHEMA = Schema(
    (
        Field("auction", DataType.INT64),
        Field("bidder", DataType.INT64),
        Field("price", DataType.INT64),
        Field("channel", DataType.VARCHAR, str_width=16),
        Field("url", DataType.VARCHAR, str_width=40),
        Field("date_time", DataType.TIMESTAMP),
    )
)

AUCTION_SCHEMA = Schema(
    (
        Field("id", DataType.INT64),
        Field("item_name", DataType.VARCHAR, str_width=24),
        Field("description", DataType.VARCHAR, str_width=32),
        Field("initial_bid", DataType.INT64),
        Field("reserve", DataType.INT64),
        Field("date_time", DataType.TIMESTAMP),
        Field("expires", DataType.TIMESTAMP),
        Field("seller", DataType.INT64),
        Field("category", DataType.INT64),
    )
)

PERSON_SCHEMA = Schema(
    (
        Field("id", DataType.INT64),
        Field("name", DataType.VARCHAR, str_width=24),
        Field("email_address", DataType.VARCHAR, str_width=32),
        Field("credit_card", DataType.VARCHAR, str_width=20),
        Field("city", DataType.VARCHAR, str_width=16),
        Field("state", DataType.VARCHAR, str_width=4),
        Field("date_time", DataType.TIMESTAMP),
    )
)

_CHANNELS = ["Google", "Facebook", "Baidu", "Apple"]
_CITIES = ["Phoenix", "Los Angeles", "San Francisco", "Boise", "Portland",
           "Bend", "Redmond", "Seattle", "Kent", "Cheyenne"]
_STATES = ["AZ", "CA", "ID", "OR", "WA", "WY"]
_FIRST_NAMES = ["Peter", "Paul", "Luke", "John", "Saul", "Vicky", "Kate",
                "Julie", "Sarah", "Deiter", "Walter"]
_LAST_NAMES = ["Shultz", "Abrams", "Spencer", "White", "Bartels", "Walton",
               "Smith", "Jones", "Noris"]


def _codebook(values: list[str], width: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    data, lens = encode_strings(values, width)
    return jnp.asarray(data), jnp.asarray(lens)


def _gather_str(codebook, idx) -> StrCol:
    data, lens = codebook
    return StrCol(data[idx], lens[idx])


@dataclass(frozen=True)
class NexmarkConfig:
    """Generator knobs (ref NexmarkProperties, nexmark/mod.rs:50)."""

    #: microseconds between consecutive events (event time)
    inter_event_us: int = 10
    base_time_us: int = BASE_TIME_US
    seed: int = 0


class NexmarkGenerator:
    """Vectorized generator addressed by per-table ordinal ranges.

    ``gen_bids(k0, cap)`` returns a Chunk of bids number ``k0..k0+cap``
    (in bid ordinal space), fully on device.  The k-th bid corresponds to
    global event number ``(k // 46) * 50 + 4 + (k % 46)``; analogous maps
    for persons/auctions.  Generation-from-ordinal makes source splits
    and resume offsets pure arithmetic.
    """

    def __init__(self, config: NexmarkConfig = NexmarkConfig()):
        self.config = config
        self._channels = _codebook(_CHANNELS, 16)
        self._cities = _codebook(_CITIES, 16)
        self._states = _codebook(_STATES, 4)
        urls = [f"https://nexmark.io/page{i}/item" for i in range(32)]
        self._urls = _codebook(urls, 40)
        names = [f"{f} {l}" for f in _FIRST_NAMES for l in _LAST_NAMES]
        self._names = _codebook(names, 24)
        emails = [f"{f.lower()}.{l.lower()}@nexmark.io"
                  for f in _FIRST_NAMES for l in _LAST_NAMES]
        self._emails = _codebook(emails, 32)
        items = [f"item-lot-{i:04d}" for i in range(64)]
        self._items = _codebook(items, 24)
        descs = [f"auction description {i}" for i in range(32)]
        self._descs = _codebook(descs, 32)
        cards = [f"{i:04d} {i+1:04d} {i+2:04d} {i+3:04d}" for i in range(16)]
        self._cards = _codebook(cards, 20)
        # jit per-table chunk builders once; ordinal start is traced
        self._gen_bids = jax.jit(self._bids_impl, static_argnums=(1,))
        self._gen_auctions = jax.jit(self._auctions_impl, static_argnums=(1,))
        self._gen_persons = jax.jit(self._persons_impl, static_argnums=(1,))

    # -- event-number math ---------------------------------------------
    def _timestamp(self, event_number: jnp.ndarray) -> jnp.ndarray:
        return (
            np.int64(self.config.base_time_us)
            + event_number * np.int64(self.config.inter_event_us)
        )

    def _event_id(self, event_number: jnp.ndarray) -> jnp.ndarray:
        # seed folds into the randomness key, not the id chain
        return event_number + np.int64(self.config.seed) * np.int64(2**40)

    # -- bids -----------------------------------------------------------
    def _bids_impl(self, k0, cap: int) -> Chunk:
        k = k0 + jnp.arange(cap, dtype=jnp.int64)
        n = (k // BID_PROPORTION) * TOTAL_PROPORTION + PERSON_PROPORTION + \
            AUCTION_PROPORTION + (k % BID_PROPORTION)
        eid = self._event_id(n)
        # hot auction: (ratio-1)/ratio of bids hit the most recent "hot" id
        hot = _rand_int(eid, 1, HOT_AUCTION_RATIO) > 0
        hot_auction = (_last_base0_auction_id(n) // HOT_AUCTION_RATIO) * \
            HOT_AUCTION_RATIO
        auction = jnp.where(hot, hot_auction, _next_base0_auction_id(eid, 2)) + \
            FIRST_AUCTION_ID
        hot_b = _rand_int(eid, 3, HOT_BIDDER_RATIO) > 0
        hot_bidder = (_last_base0_person_id(n) // HOT_BIDDER_RATIO) * \
            HOT_BIDDER_RATIO + 1
        bidder = jnp.where(hot_b, hot_bidder, _next_base0_person_id(eid, 4)) + \
            FIRST_PERSON_ID
        price = _next_price(eid, 5)
        channel = _gather_str(self._channels, _rand_int(eid, 6, len(_CHANNELS)))
        url = _gather_str(self._urls, _rand_int(eid, 7, 32))
        ts = self._timestamp(n)
        ops = jnp.zeros(cap, jnp.int8)
        valid = jnp.ones(cap, jnp.bool_)
        return Chunk(
            (auction, bidder, price, channel, url, ts), ops, valid, BID_SCHEMA
        )

    def gen_bids(self, k0: int, cap: int) -> Chunk:
        return self._gen_bids(jnp.int64(k0), cap)

    # -- auctions --------------------------------------------------------
    def _auctions_impl(self, k0, cap: int) -> Chunk:
        k = k0 + jnp.arange(cap, dtype=jnp.int64)
        n = (k // AUCTION_PROPORTION) * TOTAL_PROPORTION + PERSON_PROPORTION + \
            (k % AUCTION_PROPORTION)
        eid = self._event_id(n)
        auction_id = _last_base0_auction_id(n) + FIRST_AUCTION_ID
        initial_bid = _next_price(eid, 10)
        reserve = initial_bid + _next_price(eid, 11)
        hot = _rand_int(eid, 12, HOT_SELLER_RATIO) > 0
        hot_seller = (_last_base0_person_id(n) // HOT_SELLER_RATIO) * \
            HOT_SELLER_RATIO
        seller = jnp.where(hot, hot_seller, _next_base0_person_id(eid, 13)) + \
            FIRST_PERSON_ID
        category = FIRST_CATEGORY_ID + _rand_int(eid, 14, NUM_CATEGORIES)
        ts = self._timestamp(n)
        # canonical: expires = ts + rand over ~ next in-flight auction horizon
        expires = ts + (_rand_int(eid, 15, 4) + 1) * np.int64(
            self.config.inter_event_us
        ) * TOTAL_PROPORTION * 2
        item = _gather_str(self._items, _rand_int(eid, 16, 64))
        desc = _gather_str(self._descs, _rand_int(eid, 17, 32))
        ops = jnp.zeros(cap, jnp.int8)
        valid = jnp.ones(cap, jnp.bool_)
        return Chunk(
            (auction_id, item, desc, initial_bid, reserve, ts, expires,
             seller, category),
            ops, valid, AUCTION_SCHEMA,
        )

    def gen_auctions(self, k0: int, cap: int) -> Chunk:
        return self._gen_auctions(jnp.int64(k0), cap)

    # -- persons ---------------------------------------------------------
    def _persons_impl(self, k0, cap: int) -> Chunk:
        k = k0 + jnp.arange(cap, dtype=jnp.int64)
        n = k * TOTAL_PROPORTION
        eid = self._event_id(n)
        person_id = _last_base0_person_id(n) + FIRST_PERSON_ID
        name = _gather_str(self._names, _rand_int(eid, 20, len(_FIRST_NAMES) * len(_LAST_NAMES)))
        email = _gather_str(self._emails, _rand_int(eid, 21, len(_FIRST_NAMES) * len(_LAST_NAMES)))
        card = _gather_str(self._cards, _rand_int(eid, 22, 16))
        city = _gather_str(self._cities, _rand_int(eid, 23, len(_CITIES)))
        state = _gather_str(self._states, _rand_int(eid, 24, len(_STATES)))
        ts = self._timestamp(n)
        ops = jnp.zeros(cap, jnp.int8)
        valid = jnp.ones(cap, jnp.bool_)
        return Chunk(
            (person_id, name, email, card, city, state, ts),
            ops, valid, PERSON_SCHEMA,
        )

    def gen_persons(self, k0: int, cap: int) -> Chunk:
        return self._gen_persons(jnp.int64(k0), cap)


class NexmarkSplitReader:
    """A source split: strided ordinal subsequence of one table.

    ref: ``SplitReader`` (src/connector/src/source/base.rs:596) and
    nexmark split assignment.  Split ``i`` of ``m`` reads ordinals
    ``i, i+m, i+2m, …`` — implemented by generating a contiguous ordinal
    block per split instead (equivalent stream content, better locality;
    offsets are still exact for checkpointing).
    """

    def __init__(
        self,
        table: str,
        generator: NexmarkGenerator | None = None,
        chunk_capacity: int = 4096,
        split_id: int = 0,
        num_splits: int = 1,
        offset: int = 0,
    ):
        self.table = table
        self.gen = generator or NexmarkGenerator()
        self.cap = chunk_capacity
        self.split_id = split_id
        self.num_splits = num_splits
        self.offset = offset  # ordinal of the next event for this split
        self._fn = {
            "bid": self.gen.gen_bids,
            "auction": self.gen.gen_auctions,
            "person": self.gen.gen_persons,
        }[table]
        #: traceable generator body — runtimes fuse this into the
        #: fragment step so chunk generation never materializes
        #: standalone in HBM (impl(k0, cap) -> Chunk)
        self.impl = {
            "bid": self.gen._bids_impl,
            "auction": self.gen._auctions_impl,
            "person": self.gen._persons_impl,
        }[table]

    @property
    def events_per_row(self):
        """Global events consumed per emitted row (Fraction) — pacing
        hint so multi-source jobs advance event time in lockstep (the
        reference's single interleaved stream does this implicitly)."""
        from fractions import Fraction
        return {
            "bid": Fraction(TOTAL_PROPORTION, BID_PROPORTION),
            "auction": Fraction(TOTAL_PROPORTION, AUCTION_PROPORTION),
            "person": Fraction(TOTAL_PROPORTION, PERSON_PROPORTION),
        }[self.table]

    @property
    def schema(self) -> Schema:
        return {
            "bid": BID_SCHEMA, "auction": AUCTION_SCHEMA,
            "person": PERSON_SCHEMA,
        }[self.table]

    def next_base(self) -> int:
        """Advance the cursor and return the global ordinal of the next
        cap-row block (host arithmetic; feeds the fused step)."""
        base = (self.offset // self.cap) * self.cap * self.num_splits + \
            self.split_id * self.cap + (self.offset % self.cap)
        self.offset += self.cap
        return base

    def next_chunk(self) -> Chunk:
        return self._fn(self.next_base(), self.cap)

    def state(self) -> dict:
        """Checkpointable offset (rides the barrier, ref SourceChangeSplit)."""
        return {"table": self.table, "split_id": self.split_id,
                "offset": self.offset}
