"""Device-resident state: hash tables and epoch-checkpointed stores.

Reference counterpart: ``StateTable`` (src/stream/src/common/table/
state_table.rs:187) over ``LocalStateStore`` (src/storage).  The TPU
restructuring keeps hot state as preallocated dense arrays in HBM
(open-addressing hash tables), snapshotted host-side at checkpoint
barriers (SURVEY.md §7.1 "State = device-resident preallocated tables").
"""

from risingwave_tpu.state.hash_table import HashTable

__all__ = ["HashTable"]
