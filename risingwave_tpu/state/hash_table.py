"""Open-addressing device hash table, fully vectorized.

Reference counterpart: the per-key state maps inside stateful executors —
``AggGroup`` cache (src/stream/src/executor/aggregate/hash_agg.rs:64) and
``JoinHashMap`` (src/stream/src/executor/join/hash_join.rs:169) — which
on CPU are per-row HashMap probes behind an LRU.

TPU-first design
----------------
State is a *dense, preallocated* table in HBM:

- ``key_cols``: one ``[size]`` array per key column (``StrCol`` for
  strings) — the slot's group key;
- ``occupied``: ``bool [size]``.

``lookup_or_insert`` resolves a whole chunk of keys in one pass of a
``lax.while_loop``: every pending row probes its candidate slot
simultaneously; rows hitting an empty slot *claim* it with a
scatter-min of their row index (first-writer-wins, deterministic), and
losers simply re-check the slot on the next iteration (where they will
either match the winner's key or move on with linear probing).  The loop
runs until all rows resolve — worst case bounded, typical case 2-4
iterations — and every iteration is a handful of gathers/scatters over
the chunk, so a 4k-row chunk against a 256k-slot table is a few fused
XLA kernels rather than 4k pointer chases.

Deletion uses tombstones: a cleared slot stops matching but keeps the
probe chain intact (``~occupied & tombstone`` ⇒ keep probing, never
claim).  Bulk eviction is a vectorized mask sweep (``clear_where``) —
this is how watermark state-cleaning works (the reference cleans per-key
on commit, state_table.rs:223) — and ``needs_rehash``/``rehashed``
rebuild the table once tombstones accumulate.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import NCol, StrCol
from risingwave_tpu.common.hash import (
    hash64_columns,
    hash64_extend,
    hash64_finish,
    hash64_partial,
)

#: trace-time probe accounting: how many table-probe loops a compiled
#: program contains.  Incremented while TRACING (each jitted program
#: traces once), so wrapping a trace of an update function between
#: ``reset_probe_stats()`` and a read yields exactly the per-dispatch
#: probe-call count of the compiled artifact — the regression guard for
#: "one lookup_or_insert per side per chunk" (scripts/profile_q8.py
#: --assert and tests/test_join_pool_fused.py).
PROBE_STATS = {"lookup": 0, "lookup_or_insert": 0}


def reset_probe_stats() -> None:
    for k in PROBE_STATS:
        PROBE_STATS[k] = 0


def _gather_key(col, idx):
    if isinstance(col, NCol):
        return NCol(_gather_key(col.data, idx), col.null[idx])
    if isinstance(col, StrCol):
        return StrCol(col.data[idx], col.lens[idx])
    return col[idx]


def _scatter_key(col, pos, values, size):
    """Write values at pos (mode=drop for sentinel positions)."""
    if isinstance(col, NCol):
        return NCol(
            _scatter_key(col.data, pos, values.data, size),
            col.null.at[pos].set(values.null, mode="drop"),
        )
    if isinstance(col, StrCol):
        return StrCol(
            col.data.at[pos].set(values.data, mode="drop"),
            col.lens.at[pos].set(values.lens, mode="drop"),
        )
    return col.at[pos].set(values, mode="drop")


def _keys_equal(a, b) -> jnp.ndarray:
    """Rowwise *grouping* equality of two key column values.

    NULL == NULL here (GROUP BY/DISTINCT semantics, matching the
    reference's HashKey serde); join executors mask null keys out
    BEFORE key lookup, so join equality never reaches this."""
    if isinstance(a, NCol) or isinstance(b, NCol):
        ad, an = (a.data, a.null) if isinstance(a, NCol) else (a, None)
        bd, bn = (b.data, b.null) if isinstance(b, NCol) else (b, None)
        data_eq = _keys_equal(ad, bd)
        if an is None:
            an = jnp.zeros_like(bn)
        if bn is None:
            bn = jnp.zeros_like(an)
        return (an & bn) | (~an & ~bn & data_eq)
    if isinstance(a, StrCol):
        return jnp.all(a.data == b.data, axis=-1) & (a.lens == b.lens)
    return a == b


# public aliases for executors that pre-sort/compare key columns
# (chunk pre-aggregation in hash_agg, join bucket paths)
gather_key = _gather_key
keys_equal = _keys_equal


def permute_dense(arr, moved: jnp.ndarray, init=None):
    """Move dense per-slot values ``arr[[old]] -> out[[moved[old]]]``.

    ``moved`` comes from ``HashTable.rehashed``; dead slots carry the
    drop sentinel.  ``init`` fills untouched slots (monoid identity for
    min/max states; zero otherwise).
    """
    if isinstance(arr, NCol):
        return NCol(
            permute_dense(arr.data, moved), permute_dense(arr.null, moved)
        )
    if isinstance(arr, StrCol):
        return StrCol(
            permute_dense(arr.data, moved), permute_dense(arr.lens, moved)
        )
    if init is None:
        out = jnp.zeros_like(arr)
    else:
        out = jnp.full_like(arr, init)
    return out.at[moved].set(arr, mode="drop")


def _empty_key_col(col_proto, size: int):
    if isinstance(col_proto, NCol):
        return NCol(
            _empty_key_col(col_proto.data, size),
            jnp.zeros((size,), jnp.bool_),
        )
    if isinstance(col_proto, StrCol):
        return StrCol(
            jnp.zeros((size, col_proto.data.shape[1]), jnp.uint8),
            jnp.zeros((size,), jnp.int32),
        )
    return jnp.zeros((size,), col_proto.dtype)


@jax.tree_util.register_pytree_node_class
class HashTable:
    """Keys + occupancy; value arrays live beside it in the executor state."""

    __slots__ = ("key_cols", "occupied", "tombstone", "size")

    def __init__(
        self,
        key_cols: tuple,
        occupied: jnp.ndarray,
        tombstone: jnp.ndarray,
        size: int,
    ):
        self.key_cols = tuple(key_cols)
        self.occupied = occupied
        self.tombstone = tombstone
        self.size = size

    def tree_flatten(self):
        return (self.key_cols, self.occupied, self.tombstone), self.size

    @classmethod
    def tree_unflatten(cls, size, children):
        key_cols, occupied, tombstone = children
        return cls(key_cols, occupied, tombstone, size)

    # ------------------------------------------------------------------
    @staticmethod
    def create(key_protos: Sequence, size: int) -> "HashTable":
        """Empty table; ``key_protos`` supply per-column dtype/width."""
        if size & (size - 1):
            raise ValueError(f"size {size} must be a power of two")
        cols = tuple(_empty_key_col(p, size) for p in key_protos)
        return HashTable(
            cols,
            jnp.zeros((size,), jnp.bool_),
            jnp.zeros((size,), jnp.bool_),
            size,
        )

    def count(self) -> jnp.ndarray:
        return jnp.sum(self.occupied.astype(jnp.int32))

    # ------------------------------------------------------------------
    def lookup(self, key_cols: Sequence, valid: jnp.ndarray,
               hashes: jnp.ndarray | None = None):
        """Find slots without inserting.

        Returns ``(slots int32 [cap], found bool [cap])``; unfound/invalid
        rows get slot == size (a drop sentinel for downstream gathers).
        """
        slots, found, _ = self.lookup_counted(key_cols, valid, hashes)
        return slots, found

    def lookup_counted(self, key_cols: Sequence, valid: jnp.ndarray,
                       hashes: jnp.ndarray | None = None):
        """``lookup`` that also returns the probe-bound overflow count.

        A probe chain exhausting the iteration bound reports found=False
        for a key that may be present; callers on correctness-critical
        paths (join probes) must accumulate the count into an error
        counter so maintenance fails loudly instead of silently
        dropping matches."""
        table, slots, found, overflow = self._probe(
            key_cols, valid, insert=False, hashes=hashes
        )
        return slots, found, jnp.sum((overflow & valid).astype(jnp.int64))

    def lookup_or_insert(self, key_cols: Sequence, valid: jnp.ndarray,
                         hashes: jnp.ndarray | None = None):
        """Find-or-claim slots for a chunk of keys.

        ``hashes`` optionally supplies precomputed ``hash64_columns``
        values (callers that already hashed for a pre-aggregation sort
        avoid a second full-chunk hash pass).

        Returns ``(table', slots, inserted, overflow)``:
        - ``slots int32 [cap]`` — resolved slot per row (size if overflow
          or invalid);
        - ``inserted bool [cap]`` — row claimed a fresh slot;
        - ``overflow bool [cap]`` — table was full for this row.
        """
        return self._probe(key_cols, valid, insert=True, hashes=hashes)

    # ------------------------------------------------------------------
    def _probe(self, key_cols: Sequence, valid: jnp.ndarray, insert: bool,
               hashes: jnp.ndarray | None = None):
        PROBE_STATS["lookup_or_insert" if insert else "lookup"] += 1
        size = self.size
        cap = valid.shape[0]
        if hashes is None:
            hashes = hash64_columns(key_cols)
        h = (hashes % np.uint64(size)).astype(jnp.int32)
        row_idx = jnp.arange(cap, dtype=jnp.int32)
        sentinel = jnp.int32(size)

        # probe-length bound: at sane load factors chains are a handful
        # of slots; a pathological (near-full) table must degrade to
        # overflow counters, not O(size) loop iterations
        max_iters = min(size + 2, 1024)

        def cond(carry):
            _, _, _, done, _, _, iters = carry
            return jnp.any(~done) & (iters < max_iters)

        def body(carry):
            occupied, key_store, slots, done, inserted, off, iters = carry
            cand = (h + off) % size
            occ = occupied[cand]
            tomb = self.tombstone[cand] & ~occ
            stored = tuple(_gather_key(c, cand) for c in key_store)
            match = occ
            for s, k in zip(stored, key_cols):
                match = match & _keys_equal(s, k)
            hit = ~done & match
            slots = jnp.where(hit, cand, slots)
            done = done | hit
            if insert:
                # only a *true-empty* slot (no tombstone) is claimable:
                # claiming a tombstone could shadow the same key further
                # along a probe chain.  Intra-chunk claim races resolve
                # by scatter-min of the row index into a chunk-sized
                # scratch (hashed by candidate slot): exact for same-slot
                # contenders; cross-slot scratch collisions only delay a
                # row to the next iteration.  O(cap), never touching a
                # table-sized array.
                want = ~done & ~occ & ~tomb
                m = 4 * cap
                scratch_idx = cand % m
                claim = jnp.full((m,), cap, jnp.int32).at[
                    jnp.where(want, scratch_idx, m)
                ].min(jnp.where(want, row_idx, cap), mode="drop")
                won = want & (claim[scratch_idx] == row_idx)
                pos = jnp.where(won, cand, sentinel)
                occupied = occupied.at[pos].set(True, mode="drop")
                key_store = tuple(
                    _scatter_key(c, pos, k, size)
                    for c, k in zip(key_store, key_cols)
                )
                slots = jnp.where(won, cand, slots)
                inserted = inserted | won
                done = done | won
                # losers of the claim re-check cand next iteration (it is
                # now occupied — match if same key, else advance);
                # tombstones are skipped, keeping probe chains intact
                advance = (~done & occ & ~match) | (~done & tomb)
            else:
                # probe-only: true-empty slot ⇒ key absent ⇒ miss
                miss = ~done & ~occ & ~tomb
                done = done | miss
                advance = (~done & occ & ~match) | (~done & tomb)
            off = jnp.where(advance, off + 1, off)
            return occupied, key_store, slots, done, inserted, off, iters + 1

        init = (
            self.occupied,
            self.key_cols,
            jnp.full((cap,), sentinel, jnp.int32),
            ~valid,
            jnp.zeros((cap,), jnp.bool_),
            jnp.zeros((cap,), jnp.int32),
            jnp.int32(0),
        )
        # the first probe round is unrolled into the enclosing program:
        # at sane load factors most rows resolve immediately, and a
        # while_loop iteration carries fixed launch overhead (~0.5ms on
        # the dev chip) that the common case should not pay
        carry = body(init)
        occupied, key_store, slots, done, inserted, _, _ = jax.lax.while_loop(
            cond, body, carry
        )
        overflow = ~done
        found = valid & done & ~inserted & (slots < size)
        if insert:
            table = HashTable(key_store, occupied, self.tombstone, size)
            return table, slots, inserted, overflow
        return self, slots, found, overflow

    # ------------------------------------------------------------------
    def clear_where(self, pred: jnp.ndarray) -> "HashTable":
        """Bulk-evict slots where ``pred [size]`` is True (state cleaning).

        Cleared slots become tombstones so probe chains stay intact;
        call ``rehashed()`` periodically to reclaim them.
        """
        dead = pred & self.occupied
        return HashTable(
            self.key_cols,
            self.occupied & ~dead,
            self.tombstone | dead,
            self.size,
        )

    def clear_slots(self, slots: jnp.ndarray, mask: jnp.ndarray) -> "HashTable":
        """Tombstone specific slots (per-row deletes, e.g. MV conflict ops)."""
        pos = jnp.where(mask, slots, jnp.int32(self.size))
        return HashTable(
            self.key_cols,
            self.occupied.at[pos].set(False, mode="drop"),
            self.tombstone.at[pos].set(True, mode="drop"),
            self.size,
        )

    def tombstone_count(self) -> jnp.ndarray:
        return jnp.sum((self.tombstone & ~self.occupied).astype(jnp.int32))

    def rehashed(self) -> tuple["HashTable", jnp.ndarray]:
        """Rebuild without tombstones.

        Returns ``(fresh_table, moved)`` where ``moved int32 [size]`` maps
        old slot -> new slot (size for dead slots), so callers can
        permute their value arrays alongside.
        """
        fresh = HashTable.create(
            tuple(_gather_key(c, jnp.arange(1)) for c in self.key_cols),
            self.size,
        )
        live = self.occupied
        fresh, new_slots, _, _ = fresh.lookup_or_insert(self.key_cols, live)
        return fresh, new_slots

    def gather_keys(self, slots: jnp.ndarray) -> tuple:
        """Key column values at ``slots`` (drop-sentinel aware gathers)."""
        return tuple(_gather_key(c, jnp.minimum(slots, self.size - 1))
                     for c in self.key_cols)


# ---------------------------------------------------------------------------
# TagTable: the fused (key-hash, rank) table behind pool join sides.
# ---------------------------------------------------------------------------

#: reserved tag values (the tag hash remaps into [2, 2^64))
EMPTY_TAG = np.uint64(0)
TOMB_TAG = np.uint64(1)


def pair_tag(hashes: jnp.ndarray, rank: jnp.ndarray) -> jnp.ndarray:
    """The 64-bit identity tag of a ``(key-hash, rank)`` pair.

    ``hash64_columns([h, rank])`` remapped off the EMPTY/TOMB
    sentinels.  The tag doubles as the slot hash (``tag % size``), so a
    probe costs ONE random gather per iteration."""
    return finish_tag(hash64_extend(hash64_partial([hashes]), rank))


def finish_tag(state: jnp.ndarray) -> jnp.ndarray:
    raw = hash64_finish(state)
    return jnp.where(raw < np.uint64(2), raw + np.uint64(2), raw)


@jax.tree_util.register_pytree_node_class
class TagTable:
    """Open-addressing table over ONE packed uint64 tag array.

    The generic ``HashTable`` gathers occupied + tombstone + every key
    column per probe iteration — ~5 random DRAM reads per row per
    round, which IS the probe cost at multi-M-entry sizes.  Pool join
    sides only ever key by ``(key-hash, rank)``, whose identity
    compresses into a single 64-bit tag with reserved values for
    empty/tombstone: a probe iteration is ONE gather, a claim ONE
    scatter.  Tag collisions merge two (hash, rank) pairs with
    probability ~n²/2⁶⁴ — the same order as the key-hash collisions
    the pool design already accepts.

    Value arrays (pool position, degree, clean key) live beside the
    table in the executor state, addressed by slot.
    """

    __slots__ = ("tags", "size")

    def __init__(self, tags: jnp.ndarray, size: int):
        self.tags = tags
        self.size = size

    def tree_flatten(self):
        return (self.tags,), self.size

    @classmethod
    def tree_unflatten(cls, size, children):
        return cls(children[0], size)

    @staticmethod
    def create(size: int) -> "TagTable":
        if size & (size - 1):
            raise ValueError(f"size {size} must be a power of two")
        return TagTable(jnp.zeros((size,), jnp.uint64), size)

    # -- occupancy ------------------------------------------------------
    @property
    def occupied(self) -> jnp.ndarray:
        return self.tags >= np.uint64(2)

    def count(self) -> jnp.ndarray:
        return jnp.sum((self.tags >= np.uint64(2)).astype(jnp.int32))

    def tombstone_count(self) -> jnp.ndarray:
        return jnp.sum((self.tags == TOMB_TAG).astype(jnp.int32))

    # -- probes ---------------------------------------------------------
    def _probe_tags(self, tag_vals: jnp.ndarray, valid: jnp.ndarray,
                    insert: bool):
        """Generic one-gather probe over precomputed tags.

        Returns ``(tags', slots, found, inserted, overflow)``."""
        PROBE_STATS["lookup_or_insert" if insert else "lookup"] += 1
        size = self.size
        cap = valid.shape[0]
        row_idx = jnp.arange(cap, dtype=jnp.int32)
        sentinel = jnp.int32(size)
        home = (tag_vals % np.uint64(size)).astype(jnp.int32)
        max_iters = min(size + 2, 1024)

        def cond(carry):
            _, _, done, _, _, iters = carry
            return jnp.any(~done) & (iters < max_iters)

        def body(carry):
            tags, slots, done, inserted, off, iters = carry
            cand = (home + off) % size
            t = tags[cand]  # THE one random gather
            tomb = t == TOMB_TAG
            empty = t == EMPTY_TAG
            match = t == tag_vals
            hit = ~done & match
            slots = jnp.where(hit, cand, slots)
            done = done | hit
            if insert:
                want = ~done & empty
                m = 4 * cap
                scratch_idx = cand % m
                claim = jnp.full((m,), cap, jnp.int32).at[
                    jnp.where(want, scratch_idx, m)
                ].min(jnp.where(want, row_idx, cap), mode="drop")
                won = want & (claim[scratch_idx] == row_idx)
                pos = jnp.where(won, cand, sentinel)
                tags = tags.at[pos].set(tag_vals, mode="drop")
                slots = jnp.where(won, cand, slots)
                inserted = inserted | won
                done = done | won
                advance = ~done & ((~empty & ~match) | tomb)
            else:
                miss = ~done & empty
                done = done | miss
                advance = ~done & ((~empty & ~match) | tomb)
            off = jnp.where(advance, off + 1, off)
            return tags, slots, done, inserted, off, iters + 1

        init = (
            self.tags,
            jnp.full((cap,), sentinel, jnp.int32),
            ~valid,
            jnp.zeros((cap,), jnp.bool_),
            jnp.zeros((cap,), jnp.int32),
            jnp.int32(0),
        )
        carry = body(init)
        tags, slots, done, inserted, _, _ = jax.lax.while_loop(
            cond, body, carry
        )
        overflow = ~done
        found = valid & done & ~inserted & (slots < size)
        return tags, slots, found, overflow, inserted

    def lookup_pair_counted(self, hashes: jnp.ndarray, rank: jnp.ndarray,
                            valid: jnp.ndarray):
        """Find (hash, rank) entries; ``(slots, found, bound_count)``
        with the probe-bound overflow folded to a loud counter (the
        lookup_counted contract)."""
        _, slots, found, overflow, _ = self._probe_tags(
            pair_tag(hashes, rank), valid, insert=False
        )
        return slots, found, jnp.sum((overflow & valid).astype(jnp.int64))

    # -- the fused two-phase ranked insert ------------------------------
    def lookup_or_insert_ranked(self, hashes: jnp.ndarray,
                                chunk_rank: jnp.ndarray,
                                degree: jnp.ndarray,
                                valid: jnp.ndarray):
        """Fused two-phase find-or-claim of ``(hash, degree[head] +
        chunk_rank)`` — ONE probe loop replacing the former key-table +
        rank-index pair of ``lookup_or_insert`` passes (the q8
        join-update cost halver).

        Each valid row resolves its key's HEAD entry ``(hash, 0)``,
        reads the key's pre-chunk degree at the head slot, switches its
        target to ``(hash, degree + chunk_rank)``, and find-or-claims
        it, all in the same loop.  A row whose head chain hits
        true-empty knows its key is absent (degree 0) and jumps
        straight to phase 2; its ``chunk_rank == 0`` sibling claims the
        head in the same loop.

        ``degree`` is only read, never written — callers scatter the
        per-key insert totals at the returned head slots afterwards, so
        every row sees the PRE-chunk degree regardless of loop order.

        Returns ``(table', slots, rank, head_slot, inserted, existed,
        overflow, iters)``:

        - ``slots int32 [cap]`` — resolved (hash, rank) slot (size
          sentinel on overflow/invalid);
        - ``rank int32 [cap]`` — resolved target rank;
        - ``head_slot int32 [cap]`` — the key's (hash, 0) slot where
          this row learned it (rows that claimed or matched the head;
          size sentinel otherwise — every key's chunk_rank==0 row
          always knows it);
        - ``inserted bool [cap]`` — row claimed a fresh slot;
        - ``existed bool [cap]`` — target entry was already present (a
          stranded entry from an earlier overflow; callers overwrite
          its payload and count the loss loudly);
        - ``overflow bool [cap]`` — probe bound exhausted;
        - ``iters int32 ()`` — loop trips (device probe-effort counter).
        """
        PROBE_STATS["lookup_or_insert"] += 1
        size = self.size
        cap = valid.shape[0]
        row_idx = jnp.arange(cap, dtype=jnp.int32)
        sentinel = jnp.int32(size)
        # split hash: fold the 64-bit key hash once; re-finalize with
        # the (varying) rank on phase switches only
        base = hash64_partial([hashes])

        def tag_of(r):
            return finish_tag(hash64_extend(base, r))

        max_iters = min(2 * size + 4, 1024)

        def cond(carry):
            done = carry[2]
            iters = carry[-1]
            return jnp.any(~done) & (iters < max_iters)

        def body(carry):
            (tags, slots, done, inserted, existed, phase2, target,
             target_tag, head_slot, off, iters) = carry
            cand = ((target_tag % np.uint64(size)).astype(jnp.int32)
                    + off) % size
            t = tags[cand]  # THE one random gather
            tomb = t == TOMB_TAG
            empty = t == EMPTY_TAG
            match = t == target_tag

            # -- phase 1: resolve the head (hash, 0) -------------------
            p1 = ~done & ~phase2
            head_hit = p1 & match
            # gather degree only at head hits; other rows read slot 0
            # (one hot cache line) instead of a random miss
            d = degree[jnp.where(head_hit, cand, 0)]
            new_rank = d + chunk_rank
            head_slot = jnp.where(head_hit, cand, head_slot)
            # degree-0 head hit with chunk_rank 0: the target IS the
            # head entry, already present (stranded) — take it
            done_h = head_hit & (new_rank == 0)
            slots = jnp.where(done_h, cand, slots)
            existed = existed | done_h
            done = done | done_h
            sw_hit = head_hit & (new_rank > 0)
            # head absent (true empty terminates its chain): degree 0;
            # rows with chunk_rank > 0 move on — their rank-0 sibling
            # claims the head
            sw_empty = p1 & empty & (chunk_rank > 0)
            switched = sw_hit | sw_empty
            phase2 = phase2 | switched
            new_target = jnp.where(sw_hit, new_rank, chunk_rank)
            target = jnp.where(switched, new_target, target)
            target_tag = jnp.where(
                switched, tag_of(new_target), target_tag
            )
            off = jnp.where(switched, 0, off)

            # -- phase 2: find-or-claim (hash, target) -----------------
            hit2 = ~done & phase2 & ~switched & match
            slots = jnp.where(hit2, cand, slots)
            existed = existed | hit2
            done = done | hit2

            # claims (same scratch-race as _probe): phase-1 rank-0 rows
            # claim the head; phase-2 rows claim their target entry
            want = ~done & ~switched & empty & (phase2 | (chunk_rank == 0))
            m = 4 * cap
            scratch_idx = cand % m
            claim = jnp.full((m,), cap, jnp.int32).at[
                jnp.where(want, scratch_idx, m)
            ].min(jnp.where(want, row_idx, cap), mode="drop")
            won = want & (claim[scratch_idx] == row_idx)
            pos = jnp.where(won, cand, sentinel)
            tags = tags.at[pos].set(target_tag, mode="drop")
            slots = jnp.where(won, cand, slots)
            head_slot = jnp.where(won & (target == 0), cand, head_slot)
            inserted = inserted | won
            done = done | won
            advance = ~done & ~switched & ((~empty & ~match) | tomb)
            off = jnp.where(advance, off + 1, off)
            return (tags, slots, done, inserted, existed, phase2,
                    target, target_tag, head_slot, off, iters + 1)

        init = (
            self.tags,
            jnp.full((cap,), sentinel, jnp.int32),
            ~valid,
            jnp.zeros((cap,), jnp.bool_),
            jnp.zeros((cap,), jnp.bool_),
            jnp.zeros((cap,), jnp.bool_),
            jnp.zeros((cap,), jnp.int32),
            tag_of(jnp.zeros((cap,), jnp.int32)),
            jnp.full((cap,), sentinel, jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.int32(0),
        )
        # first round unrolled, as in _probe: most rows resolve both
        # phases in a couple of rounds at sane load factors
        carry = body(init)
        (tags, slots, done, inserted, existed, _, target, _,
         head_slot, _, iters) = jax.lax.while_loop(cond, body, carry)
        overflow = ~done
        table = TagTable(tags, size)
        return (table, slots, target, head_slot, inserted,
                existed & valid, overflow, iters)

    # -- maintenance ----------------------------------------------------
    def clear_where(self, pred: jnp.ndarray) -> "TagTable":
        """Bulk-evict slots where ``pred [size]`` (state cleaning);
        cleared slots become tombstones so probe chains stay intact."""
        dead = pred & self.occupied
        return TagTable(
            jnp.where(dead, TOMB_TAG, self.tags), self.size
        )

    def clear_slots(self, slots: jnp.ndarray,
                    mask: jnp.ndarray) -> "TagTable":
        """Tombstone specific slots (e.g. un-claim on pool overflow)."""
        pos = jnp.where(mask, slots, jnp.int32(self.size))
        return TagTable(
            self.tags.at[pos].set(TOMB_TAG, mode="drop"), self.size
        )

    def rehashed(self) -> tuple["TagTable", jnp.ndarray]:
        """Rebuild without tombstones; ``(fresh, moved int32 [size])``
        maps old slot -> new slot (size sentinel for dead slots) so
        callers permute their per-slot value arrays alongside."""
        live = self.occupied
        fresh = TagTable.create(self.size)
        tags, new_slots, _, _, _ = fresh._probe_tags(
            self.tags, live, insert=True
        )
        return TagTable(tags, self.size), new_slots
