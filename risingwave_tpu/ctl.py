"""Admin/introspection surface (the risectl + dashboard analog).

Reference counterparts: ``src/ctl`` (risectl: cluster-info, pause/
resume-barrier, await-tree dump) and the meta dashboard's fragment
graph / ``EXPLAIN ANALYZE`` for streaming jobs
(``GetStreamingStats``, proto/monitor_service.proto:152).

``describe_job`` is the await-tree analog: instead of async stack
traces (there are no tasks to trace — fragments are jitted programs),
it reports the executor tree with live state-occupancy gauges, the
consistency counters, and the job's epoch/offset positions — what an
operator actually needs to see for a stuck or skewed job.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp


def _state_gauges(executor, state) -> dict:
    out: dict[str, Any] = {}
    table = getattr(state, "table", None)
    if table is not None and hasattr(table, "occupied"):
        out["groups"] = int(jnp.sum(table.occupied))
        out["tombstones"] = int(table.tombstone_count())
        out["table_size"] = table.size
    if hasattr(state, "valid") and getattr(state, "valid", None) is not None \
            and hasattr(state.valid, "dtype"):
        out["pool_rows"] = int(jnp.sum(state.valid))
    if hasattr(state, "cursor"):
        out["rows_written"] = int(state.cursor)
    if hasattr(state, "dirty"):
        out["dirty"] = int(jnp.sum(state.dirty))
    if hasattr(state, "wm"):
        out["watermark"] = int(state.wm)
    if hasattr(state, "max_ts"):
        out["max_event_time"] = int(state.max_ts)
    for counter in ("overflow", "inconsistency", "late_rows",
                    "emit_overflow"):
        if hasattr(state, counter):
            v = getattr(state, counter)
            out[counter] = int(jnp.sum(v))
    # join side states
    for side in ("left", "right"):
        if hasattr(state, side):
            s = getattr(state, side)
            out[side] = {
                "keys": int(jnp.sum(s.key_table.occupied)),
                "rows": int(jnp.sum(s.occupied)),
                "overflow": int(s.overflow),
                "inconsistency": int(s.inconsistency),
            }
    return out


def describe_job(job) -> dict:
    """Executor tree + state gauges for one streaming job."""
    from risingwave_tpu.stream.dag import DagJob, FragNode
    from risingwave_tpu.stream.runtime import StreamingJob
    from risingwave_tpu.stream.sharded import ShardedStreamingJob

    info: dict[str, Any] = {
        "name": job.name,
        "kind": type(job).__name__,
        "committed_epoch": job.committed_epoch,
        "barriers": job.barriers_seen,
        "paused": getattr(job, "paused", False),
    }
    if isinstance(job, StreamingJob):
        info["source_offset"] = getattr(job.source, "offset", None)
        info["executors"] = [
            {"executor": repr(ex), **_state_gauges(ex, job.states[i])}
            for i, ex in enumerate(job.fragment.executors)
        ]
    elif isinstance(job, DagJob):
        info["sources"] = {
            name: getattr(src, "offset", None)
            for name, src in job.sources.items()
        }
        info["executors"] = []
        for idx, node in enumerate(job.nodes):
            if node is None:
                continue
            if isinstance(node, FragNode):
                for i, ex in enumerate(node.fragment.executors):
                    info["executors"].append({
                        "executor": f"[n{idx}<-{node.input}] {ex!r}",
                        **_state_gauges(ex, job.states[idx][i]),
                    })
            else:
                info["executors"].append({
                    "executor": f"[n{idx}<-{node.left},{node.right}] "
                                "HashJoinExecutor",
                    **_state_gauges(node.join, job.states[idx]),
                })
    elif isinstance(job, ShardedStreamingJob):
        info["n_shards"] = job.sharded.n_shards
        info["source_offset"] = getattr(job.reader, "offset", None)
        info["executors"] = [
            {"executor": f"[sharded] {ex!r}",
             **_state_gauges(ex, job.states[i])}
            for i, ex in enumerate(job.sharded.executors)
        ]
    return info


def cluster_info(engine) -> dict:
    """risectl cluster-info analog."""
    import jax

    return {
        "devices": [str(d) for d in jax.devices()],
        "jobs": [describe_job(j) for j in engine.jobs],
        "catalog": [
            {"name": e.name, "kind": e.kind,
             "columns": [f"{f.name}:{f.data_type.name.lower()}"
                         for f in e.schema]}
            for e in engine.catalog.list()
        ],
        "system_params": engine.system_params.to_dict(),
        "storage": storage_info(engine) if engine.hummock is not None
        else None,
    }


# -- storage service (risectl hummock ... analog) -----------------------
def storage_info(engine) -> dict:
    """``storage version``: current version id/epoch, per-level file
    counts and bytes, pin count, stall state, compactor liveness."""
    if engine.hummock is None:
        return {"enabled": False}
    info = {"enabled": True, **engine.hummock.stats()}
    if engine.compactor is not None:
        info["compactor"] = {
            "running": engine.compactor.running,
            "tasks_run": engine.compactor.tasks_run,
            "errors": engine.compactor.errors,
        }
    return info


def storage_gc(engine) -> dict:
    """``storage gc``: run one vacuum pass (delete SST objects no
    pinned version references) and report the result."""
    return engine.storage_vacuum()


def _open_storage(data_dir: str):
    """Read-only-ish HummockStorage over an existing data_dir (for the
    offline CLI: inspect/GC without a running node)."""
    import os

    from risingwave_tpu.storage.hummock import (
        HummockStorage,
        LocalFsObjectStore,
    )

    return HummockStorage(
        LocalFsObjectStore(os.path.join(data_dir, "hummock"))
    )


def storage_scrub(data_dir: str) -> dict:
    """``ctl storage scrub <data_dir>`` — OFFLINE integrity scrub of a
    node's durable state: every SST in the version (footer crc, index,
    every block's crc32c trailer), the version log's hash chain, and
    every retained checkpoint epoch object vs its manifest-recorded
    crc.  Report-only (no node running, nothing to repair FROM): a
    corrupt object is listed, never silently read."""
    from risingwave_tpu.storage.hummock import LocalFsObjectStore
    from risingwave_tpu.storage.hummock.scrubber import ScrubberService
    from risingwave_tpu.storage.integrity import (
        ManifestCorruption,
        quarantine_list,
    )

    try:
        storage = _open_storage(data_dir)
    except ManifestCorruption as e:
        # the version log itself is damaged: report instead of crashing
        return {"ssts_verified": 0, "blocks_verified": 0,
                "checkpoints_verified": 0,
                "corrupt": [("manifest", e.key)], "ok": False}
    scrub = ScrubberService(
        storage,
        ckpt_object_store=LocalFsObjectStore(data_dir),
        pace_s=0.0,
    )
    report = scrub.run_once()
    report["quarantined"] = [
        n.get("key") for n in quarantine_list(storage.store)
    ]
    report["ok"] = not report["corrupt"]
    return report


def _storage_main(argv: list[str]) -> None:
    """``python -m risingwave_tpu.ctl storage
    {version|gc|scrub|compact|policy} <data_dir>`` — offline
    inspection/GC/integrity-scrub/compaction of a node's storage
    service state (risectl hummock list-version / trigger-full-gc
    analogs); ``policy`` prints the manifest-carried expiry policy
    docs the compaction filter enforces."""
    import json

    sub, data_dir = argv[0], argv[1]
    if sub == "scrub":
        report = storage_scrub(data_dir)
        print(json.dumps(report, indent=1))
        if not report["ok"]:
            raise SystemExit(1)
        return
    storage = _open_storage(data_dir)
    if sub == "version":
        print(json.dumps(storage.stats(), indent=1))
    elif sub == "gc":
        deleted = storage.vacuum()
        print(json.dumps({
            "deleted_objects": deleted,
            "remaining_objects": storage.stats()["objects"],
        }, indent=1))
    elif sub == "compact":
        n = 0
        while storage.compact_once():
            n += 1
        print(json.dumps({"tasks_run": n, **storage.stats()}, indent=1))
    elif sub == "policy":
        # the policy docs the manifest carries — exactly what an
        # offline ``storage compact`` run would enforce, so a live
        # compactor and this CLI can never disagree on a horizon
        print(json.dumps({
            "version_id": storage.stats()["version_id"],
            "policies": storage.versions.current.policy_docs(),
        }, indent=1))
    else:
        raise SystemExit(f"unknown storage subcommand: {sub}")


# -- cluster control plane (risectl cluster ... analog) -----------------
def _meta_state(meta_addr: str) -> dict:
    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr

    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=30.0)
    try:
        return client.call("cluster_state")
    finally:
        client.close()


def cluster_workers(meta_addr: str) -> list[dict]:
    """``ctl cluster workers``: live/dead workers with heartbeat ages
    and their job assignments (risectl cluster-info's worker table)."""
    return _meta_state(meta_addr)["workers"]


def cluster_jobs(meta_addr: str) -> list[dict]:
    """``ctl cluster jobs``: placed streaming jobs — owner worker,
    sealed rounds, last committed and pinned epochs."""
    return _meta_state(meta_addr)["jobs"]


def cluster_serving(meta_addr: str) -> list[dict]:
    """``ctl cluster serving``: registered serving replicas — address,
    liveness, heartbeat age, the granted manifest vid, and the epoch
    pin lease (the vids vacuum keeps alive for each replica)."""
    return _meta_state(meta_addr).get("serving", [])


def cluster_faults(meta_addr: str) -> dict:
    """``ctl cluster faults``: the chaos observability surface — the
    meta's (and every live worker's/replica's) injected-fault
    counters, retry budget spend, and gave-up totals from the
    deterministic fault fabric (common/faults.py)."""
    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr

    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=30.0)
    try:
        return client.call("cluster_faults")
    finally:
        client.close()


def cluster_pushdown(meta_addr: str) -> dict:
    """``ctl cluster pushdown <meta_addr>``: the pushdown-plane view —
    the manifest's per-table expiry policy docs (TTL horizons the
    compaction filter enforces), the meta-side compactor elision
    counters, and each live serving replica's negative-cache /
    warmup-replay numbers."""
    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr

    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=120.0)
    try:
        return client.call("cluster_pushdown")
    finally:
        client.close()


def cluster_scale(meta_addr: str, n: int) -> dict:
    """``ctl cluster scale N <meta_addr>``: resize the active worker
    set online — the meta rebalances the vnode map minimally and
    hands the moved vnodes' state over through a checkpoint epoch
    (reads stay zero-error throughout)."""
    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr

    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=600.0)
    try:
        return client.call("cluster_scale", n=int(n))
    finally:
        client.close()


def cluster_vnodes(meta_addr: str) -> dict:
    """``ctl cluster vnodes``: the scale plane's view — active worker
    set, per-worker vnode counts, and each partitioned job's
    partition layout."""
    s = _meta_state(meta_addr)
    return {
        "scale": s.get("scale"),
        "partitions": {
            j["name"]: j["partitions"]
            for j in s["jobs"] if j.get("partitions")
        },
    }


def cluster_exchange(meta_addr: str) -> dict:
    """``ctl cluster exchange``: the compiled Exchange-lite
    choreography — per-table shuffle mode, routing key column, ingest
    leader + standby, and the full edge-spec list (source / join /
    attach edges).  Compile once, execute forever: what this prints
    is exactly what every worker's per-chunk data path executes."""
    s = _meta_state(meta_addr)
    return s.get("exchange") or {}


def cluster_scrub(meta_addr: str) -> dict:
    """``ctl cluster scrub <meta_addr>``: drive ONE full ONLINE scrub
    cycle on the running meta — every pinned-version SST and retained
    checkpoint lineage verified, with quarantine + self-healing repair
    armed (corrupt MV exports re-export from live job state, corrupt
    checkpoint lineages rewind to the last verified epoch)."""
    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr

    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=600.0)
    try:
        return client.call("cluster_scrub")
    finally:
        client.close()


def cluster_metrics(meta_addr: str) -> str:
    """``ctl cluster metrics <meta_addr>``: ONE aggregated Prometheus
    scrape for the whole cluster — the meta pulls every live worker's
    and serving replica's registry over RPC and merges them with
    ``role``/``worker``/``replica`` identity labels injected per
    sample (common/metrics.py merge_prometheus)."""
    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr

    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=120.0)
    try:
        return client.call("cluster_metrics")["prometheus"]
    finally:
        client.close()


def cluster_trace(meta_addr: str, round: "int | None" = None,
                  chrome: str | None = None) -> dict:
    """``ctl cluster trace <meta_addr> [--round N] [--chrome out]``:
    assemble the merged cross-role span tree for one committed round
    (meta round span parenting worker barrier-phase spans, uploader
    prepare/commit spans, sampled serving reads).  ``--chrome`` also
    writes Chrome ``trace_event`` JSON loadable in chrome://tracing
    or Perfetto."""
    import json

    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr
    from risingwave_tpu.common.trace import to_chrome_trace

    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=120.0)
    try:
        out = client.call("cluster_trace", round=round)
    finally:
        client.close()
    if chrome:
        with open(chrome, "w") as f:
            json.dump(to_chrome_trace(out["spans"]), f)
        out["chrome"] = chrome
    return out


def cluster_epochs(meta_addr: str) -> dict:
    """``ctl cluster epochs``: the global checkpoint positions — the
    committed cluster epoch (round), the manifest's epoch stamp, each
    job's serving pin, and the async-checkpoint split (sealed vs
    durable epoch + upload lag per job)."""
    s = _meta_state(meta_addr)
    return {
        "cluster_epoch": s["cluster_epoch"],
        "manifest_epoch": s["manifest_epoch"],
        "failovers": s["failovers"],
        "jobs": {
            j["name"]: {"pinned_epoch": j["pinned_epoch"],
                        "committed_epoch": j["committed_epoch"],
                        "sealed_epoch": j.get("sealed_epoch", 0),
                        "durable_epoch": j.get("durable_epoch", 0),
                        "upload_lag_epochs": max(
                            0, j.get("sealed_epoch", 0)
                            - j.get("durable_epoch", 0)),
                        "rounds": j["rounds"]}
            for j in s["jobs"]
        },
    }


def cluster_batch(meta_addr: str, sqls: list) -> dict:
    """``ctl cluster batch <meta_addr> <sql> [sql ...]``: N SELECTs
    through ONE serving-tier RPC frame (the batched multi-get
    protocol) — per-item owner fallback keeps the surface identical
    to single reads."""
    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr

    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=120.0)
    try:
        return client.call("serve_batch", sqls=list(sqls))
    finally:
        client.close()


def cluster_multiget(meta_addr: str, mv: str, pks: list) -> dict:
    """``ctl cluster multiget <meta_addr> <mv> <pk> [pk ...]``:
    first-class multi-get — one MV + N pks in one frame, rows back in
    encoded-pk order (missing pks omitted).  Composite pks pass as
    comma-joined values (``3,foo``); bare integers coerce."""
    from risingwave_tpu.cluster.rpc import RpcClient, parse_addr

    def _coerce(s: str):
        try:
            return int(s)
        except ValueError:
            try:
                return float(s)
            except ValueError:
                return s

    keys = [[_coerce(part) for part in str(pk).split(",")]
            for pk in pks]
    host, port = parse_addr(meta_addr)
    client = RpcClient(host, port, timeout=120.0)
    try:
        return client.call("serve_multi_get", mv=mv, pks=keys)
    finally:
        client.close()


def _cluster_main(argv: list[str]) -> None:
    """``python -m risingwave_tpu.ctl cluster
    {workers|jobs|epochs|serving|faults} <meta_host:rpc_port>`` —
    online introspection of a running meta (mirrors the offline
    ``ctl storage`` pattern, but against the live control plane)."""
    import json

    sub = argv[0]
    if sub == "scale":
        # ctl cluster scale <N> <meta_addr>
        print(json.dumps(cluster_scale(argv[2], int(argv[1])),
                         indent=1))
        return
    if sub == "batch":
        # ctl cluster batch <meta_addr> <sql> [sql ...]
        print(json.dumps(cluster_batch(argv[1], argv[2:]), indent=1))
        return
    if sub == "multiget":
        # ctl cluster multiget <meta_addr> <mv> <pk> [pk ...]
        print(json.dumps(cluster_multiget(argv[1], argv[2], argv[3:]),
                         indent=1))
        return
    if sub == "metrics":
        # ctl cluster metrics <meta_addr> — raw exposition text
        print(cluster_metrics(argv[1]), end="")
        return
    if sub == "trace":
        # ctl cluster trace <meta_addr> [--round N] [--chrome out]
        addr, rnd, chrome = argv[1], None, None
        rest = argv[2:]
        while rest:
            flag = rest.pop(0)
            if flag == "--round":
                rnd = int(rest.pop(0))
            elif flag == "--chrome":
                chrome = rest.pop(0)
            else:
                raise SystemExit(f"unknown trace flag: {flag}")
        print(json.dumps(cluster_trace(addr, rnd, chrome), indent=1))
        return
    addr = argv[1]
    fn = {"workers": cluster_workers, "jobs": cluster_jobs,
          "epochs": cluster_epochs,
          "serving": cluster_serving,
          "vnodes": cluster_vnodes,
          "exchange": cluster_exchange,
          "scrub": cluster_scrub,
          "pushdown": cluster_pushdown,
          "faults": cluster_faults}.get(sub)
    if fn is None:
        raise SystemExit(f"unknown cluster subcommand: {sub}")
    print(json.dumps(fn(addr), indent=1))


def main() -> None:  # pragma: no cover - thin CLI
    """``python -m risingwave_tpu.ctl <host> <port> <sql>`` — send one
    statement to a running node over pgwire (risectl's transport is
    gRPC; ours is the SQL front door).  ``... ctl storage
    {version|gc|compact} <data_dir>`` operates on storage offline;
    ``... ctl cluster {workers|jobs|epochs} <meta_addr>`` talks to a
    running meta service."""
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "storage":
        _storage_main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "cluster":
        _cluster_main(sys.argv[2:])
        return

    from risingwave_tpu.pgwire import SimpleClient

    host, port, sql = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    c = SimpleClient(host, port)
    cols, rows = c.query(sql)
    if cols:
        print("\t".join(cols))
    for r in rows:
        print("\t".join("" if v is None else str(v) for v in r))
    c.close()


if __name__ == "__main__":
    main()
