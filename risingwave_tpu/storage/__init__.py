"""Storage layer: epoch-versioned persistence (the Hummock analog).

Reference counterpart: ``src/storage`` (SURVEY.md §2.5) — an LSM over
object storage.  Round-1 shape:

- ``codec``          — C++ native memcomparable/varint-block codec
- ``sst``            — block-based sorted-string-table files + merge reads
- ``checkpoint_store`` — epoch-versioned snapshot persistence + manifest

Device state stays dense in HBM; the storage layer owns the host-side
durability path (checkpoint upload, serving from closed epochs,
restart recovery), exactly the split the reference draws between
executor caches and Hummock.
"""

from risingwave_tpu.storage.checkpoint_store import CheckpointStore

__all__ = ["CheckpointStore"]
