"""Storage layer: epoch-versioned persistence (the Hummock analog).

Reference counterpart: ``src/storage`` (SURVEY.md §2.5) — an LSM over
object storage.  Current shape:

- ``codec``            — C++ native memcomparable/varint-block codec
- ``sst``              — block-based SSTs (bloom filters, k-way merge
  reads) + the inline ``LsmTree`` lifecycle
- ``checkpoint_store`` — epoch-versioned snapshot persistence + manifest
- ``hummock``          — the storage *service*: object-store seam,
  versioned manifest with pin/unpin, background compactor with write
  stall, vacuum GC (the reference's fourth node role)

Device state stays dense in HBM; the storage layer owns the host-side
durability path (checkpoint upload, serving from closed epochs,
restart recovery), exactly the split the reference draws between
executor caches and Hummock.
"""

from risingwave_tpu.storage.checkpoint_store import CheckpointStore
from risingwave_tpu.storage.hummock import (
    CompactorService,
    HummockStorage,
    InMemObjectStore,
    LocalFsObjectStore,
    ObjectStore,
    StoreFaults,
)

__all__ = [
    "CheckpointStore",
    "CompactorService",
    "HummockStorage",
    "InMemObjectStore",
    "LocalFsObjectStore",
    "ObjectStore",
    "StoreFaults",
]
