"""Storage layer: epoch-versioned persistence (the Hummock analog).

Reference counterpart: ``src/storage`` (SURVEY.md §2.5) — an LSM over
object storage.  Current shape:

- ``codec``            — C++ native memcomparable/varint-block codec
- ``sst``              — block-based SSTs (bloom filters, k-way merge
  reads) + the inline ``LsmTree`` lifecycle
- ``checkpoint_store`` — epoch-versioned snapshot persistence + manifest
- ``hummock``          — the storage *service*: object-store seam,
  versioned manifest with pin/unpin, background compactor with write
  stall, vacuum GC (the reference's fourth node role)

Device state stays dense in HBM; the storage layer owns the host-side
durability path (checkpoint upload, serving from closed epochs,
restart recovery), exactly the split the reference draws between
executor caches and Hummock.

Exports resolve lazily (PEP 562): ``checkpoint_store`` imports jax, but
the engine-free serving tier reads SSTs through ``sst``/``hummock``
from a process that must never load jax.
"""

_LAZY = {
    "CheckpointStore": ("risingwave_tpu.storage.checkpoint_store",
                        "CheckpointStore"),
    "CompactorService": ("risingwave_tpu.storage.hummock",
                         "CompactorService"),
    "HummockStorage": ("risingwave_tpu.storage.hummock",
                       "HummockStorage"),
    "InMemObjectStore": ("risingwave_tpu.storage.hummock",
                         "InMemObjectStore"),
    "LocalFsObjectStore": ("risingwave_tpu.storage.hummock",
                           "LocalFsObjectStore"),
    "ObjectStore": ("risingwave_tpu.storage.hummock", "ObjectStore"),
    "StoreFaults": ("risingwave_tpu.storage.hummock", "StoreFaults"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    value = getattr(importlib.import_module(mod_name), attr)
    globals()[name] = value
    return value
