"""Pushdown plane: near-data compute for the storage + serving tiers.

Reference counterpart: *Taurus*'s near-data processing (PAPERS.md) —
move compute to where the bytes already are — mapped onto this repo's
disaggregation seams:

- **Compaction-time operators** (``ExpiryPolicy`` / ``PolicySet``):
  meta-pushed per-table policy docs (TTL / EOWC expiry horizons,
  derived from watermark state at barrier commit) that ``compact_once``
  executes as a compaction filter.  Expired rows drop and whole dead
  key ranges — tombstones included — elide without a block read, but
  ONLY when the compaction output is the bottommost non-empty level:
  the same legality rule as the tombstone drop
  (``sst.output_is_bottommost``), because a dropped range above deeper
  live data would resurrect it.  The policy rides the version manifest
  (``HummockVersion.policies``), so compactor restarts and the offline
  ``ctl storage compact`` path agree with the owning engine.

- **Scan-side predicate + projection pushdown** (``BlockEvaluator`` /
  ``scan_filtered``): the serving replica's residual filters and
  projections execute per block DURING the k-way merge scan instead of
  after full-row materialization.  The evaluator is jax-free and
  memcomparable-aware: predicates on pk columns at a fixed byte offset
  compile to slice compares against the mc-encoded literal, eliding
  non-matching rows before the pickled payload is ever decoded.

Both sides report into the shared counter surface:
``pushdown_rows_elided_total{where=compactor|replica}`` and
``pushdown_blocks_skipped_total``.

Everything here is jax-free (imported by the serving tier under
RWT_NO_JAX) and value-codec-free: keys are compared as bytes, which is
exactly what the memcomparable export encoding guarantees is the value
order.
"""

from __future__ import annotations

import heapq
import json
from dataclasses import dataclass, field

from risingwave_tpu.storage.sst import TOMBSTONE


def table_prefix(table: str) -> bytes:
    """Storage-key prefix of one exported table/MV (mirrors
    serve.reader.mv_key_range / Engine._mv_storage_range)."""
    return b"m:" + table.encode() + b"\x00"


@dataclass(frozen=True)
class ExpiryPolicy:
    """One table's expiry horizon as a byte-range over storage keys.

    ``expire_below`` is a FULL storage-key bound: ``prefix`` +
    mc-encoded horizon value of the leading export-pk column.  A key is
    expired iff ``prefix <= key < expire_below`` — pure byte compares,
    so the compactor needs neither the schema nor the codec.  The raw
    ``horizon`` (and how it was derived) travels alongside for the ctl
    surface and for the engine's own export-side filtering.
    """

    table: str
    prefix: bytes
    expire_below: bytes
    #: raw leading-pk horizon value (rows with pk0 < horizon expire)
    horizon: int
    #: retention in leading-pk units (the WITH (ttl = ...) option)
    ttl: int
    #: leading export-pk column name (doc/ctl surface only)
    column: str = ""
    #: epoch the horizon was derived at (watermark state at barrier
    #: commit) — monotone per table, newest doc wins
    epoch: int = 0

    def covers(self, key: bytes) -> bool:
        return self.prefix <= key < self.expire_below

    def to_doc(self) -> dict:
        return {
            "table": self.table,
            "mode": "ttl",
            "prefix": self.prefix.hex(),
            "expire_below": self.expire_below.hex(),
            "horizon": self.horizon,
            "ttl": self.ttl,
            "column": self.column,
            "epoch": self.epoch,
        }

    @staticmethod
    def from_doc(d: dict) -> "ExpiryPolicy":
        return ExpiryPolicy(
            table=d["table"],
            prefix=bytes.fromhex(d["prefix"]),
            expire_below=bytes.fromhex(d["expire_below"]),
            horizon=int(d["horizon"]),
            ttl=int(d["ttl"]),
            column=d.get("column", ""),
            epoch=int(d.get("epoch", 0)),
        )


class PolicySet:
    """The compaction filter: every table's current expiry policy.

    Built from the manifest's ``policies`` map (table → doc), so every
    consumer — the owning storage service, a restarted compactor, the
    offline ``ctl storage compact`` path — evaluates the SAME filter
    for a given version.
    """

    def __init__(self, policies: "list[ExpiryPolicy] | None" = None):
        self.policies = list(policies or ())

    def __len__(self) -> int:
        return len(self.policies)

    def __bool__(self) -> bool:
        return bool(self.policies)

    @staticmethod
    def from_docs(docs: "dict[str, dict] | None") -> "PolicySet":
        if not docs:
            return PolicySet()
        return PolicySet(
            [ExpiryPolicy.from_doc(d) for d in docs.values()]
        )

    def expired(self, key: bytes) -> bool:
        """Is this storage key below its table's horizon?"""
        for p in self.policies:
            if p.prefix <= key < p.expire_below:
                return True
        return False

    def range_dead(self, first_key: bytes, last_key: bytes) -> bool:
        """True iff EVERY key in [first_key, last_key] is expired —
        the whole-SST / whole-range elision test.  Sound because
        ``prefix <= k < expire_below`` implies ``k`` starts with
        ``prefix`` (expire_below itself starts with prefix), so one
        policy covering both endpoints covers everything between."""
        if not first_key and not last_key:
            return False
        for p in self.policies:
            if p.prefix <= first_key and last_key < p.expire_below:
                return True
        return False

    def to_docs(self) -> dict:
        return {p.table: p.to_doc() for p in self.policies}

    def get(self, table: str) -> "ExpiryPolicy | None":
        for p in self.policies:
            if p.table == table:
                return p
        return None


def merge_policy_docs(current: "dict[str, dict] | None",
                      updates: "dict[str, dict | None]") -> dict:
    """Fold policy updates into a manifest policy map: newest epoch
    wins per table, ``None`` removes (DROP).  Pure — used by
    ``apply_delta`` so replay folds identically everywhere."""
    out = dict(current or {})
    for table, doc in updates.items():
        if doc is None:
            out.pop(table, None)
        elif table not in out \
                or int(doc.get("epoch", 0)) \
                >= int(out[table].get("epoch", 0)):
            out[table] = doc
    return out


# -- scan-side block-walk evaluation ------------------------------------


@dataclass
class PushdownStats:
    """Per-scan counters the serving/compactor paths export."""

    rows_elided: int = 0
    blocks_skipped: int = 0
    rows_out: int = 0
    #: rows elided on key bytes alone (subset of rows_elided; these
    #: never paid the pickle decode)
    key_elided: int = 0


#: encoded byte widths of fixed-width pk kinds (mc_encode_i64/f64)
_FIXED_WIDTH = {"int": 8, "decimal": 8, "float": 8}

_KEY_OPS = {"=", "==", "!=", "<>", "<", "<=", ">", ">="}


def _slice_pass(got: bytes, op: str, want: bytes) -> bool:
    """Evaluate one mc-encoded slice compare: byte order == value
    order for memcomparable encodings, so the SQL comparison maps to
    the byte comparison directly."""
    if op in ("=", "=="):
        return got == want
    if op in ("!=", "<>"):
        return got != want
    if op == "<":
        return got < want
    if op == "<=":
        return got <= want
    if op == ">":
        return got > want
    return got >= want


class BlockEvaluator:
    """Compiled residual-predicate + projection evaluator for one MV's
    block walk.

    Predicates on pk columns whose key-slice offset is computable
    (every earlier pk component fixed-width and non-nullable) become
    byte compares on the storage key — non-matching rows elide before
    the pickled row is decoded.  Everything else evaluates on the
    decoded row with SQL comparison semantics (NULL never matches).
    Projection applies in the same pass, so the scan emits exactly the
    output tuples.
    """

    def __init__(self, schema, residual, cols: "list[int] | None",
                 stats: "PushdownStats | None" = None):
        self.schema = schema
        self.cols = cols
        self.stats = stats if stats is not None else PushdownStats()
        #: (offset, end, op, encoded literal) — key-byte predicates
        self.key_preds: list[tuple[int, int, str, bytes]] = []
        #: (col_idx, op, value) — decoded-row predicates
        self.row_preds: list[tuple[int, str, object]] = []
        offsets = self._pk_offsets(schema)
        for col_idx, op, value in residual:
            enc = self._compile_key_pred(schema, offsets, col_idx, op,
                                         value)
            if enc is not None:
                self.key_preds.append(enc)
            else:
                self.row_preds.append((col_idx, op, value))

    @staticmethod
    def _pk_offsets(schema) -> dict[int, tuple[int, int]]:
        """col_idx → (offset, width) within the key bytes AFTER the
        table prefix, for the fixed-offset prefix of the pk."""
        out: dict[int, tuple[int, int]] = {}
        off = 0
        for col_idx in schema.pk:
            c = schema.columns[col_idx]
            if c.nullable:
                break  # presence prefix makes the width data-dependent
            w = _FIXED_WIDTH.get(c.kind)
            if w is None:
                break  # strings are variable-width: stop the prefix
            out[col_idx] = (off, w)
            off += w
        return out

    def _compile_key_pred(self, schema, offsets, col_idx, op, value):
        if op not in _KEY_OPS or value is None:
            return None
        loc = offsets.get(col_idx)
        if loc is None:
            return None
        try:
            enc = schema.encode_pk_value(col_idx, value)
        except (TypeError, ValueError, OverflowError):
            return None
        off, w = loc
        if len(enc) != w:
            return None
        return (off, off + w, op, enc)

    # -- evaluation -----------------------------------------------------
    def eval_key(self, key_tail: bytes) -> bool:
        """``key_tail`` = storage key minus the table prefix."""
        for off, end, op, want in self.key_preds:
            if not _slice_pass(key_tail[off:end], op, want):
                return False
        return True

    def eval_row(self, row) -> bool:
        for col_idx, op, value in self.row_preds:
            if not _row_cmp(row[col_idx], op, value):
                return False
        return True

    def project(self, row):
        if self.cols is None:
            return tuple(row)
        return tuple(row[i] for i in self.cols)


def _row_cmp(a, op: str, b) -> bool:
    """SQL comparison semantics on decoded values (NULL never
    matches) — mirrors serve.worker._cmp so pushed-down and
    materialize-then-filter reads agree bit-for-bit."""
    if a is None or b is None:
        return False
    if op in ("=", "=="):
        return a == b
    if op in ("!=", "<>"):
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise ValueError(f"unknown operator {op!r}")


def scan_filtered(readers, lo: bytes, hi: "bytes | None",
                  prefix: bytes, evaluator: BlockEvaluator,
                  loads) -> "list[tuple]":
    """The pushdown merge scan: k-way merge over ``readers`` (newest
    first) with the evaluator applied per block, not per materialized
    result set.

    - Readers whose key range misses the window never open a block
      (counted into ``blocks_skipped``), and the in-range block walk
      counts front/back blocks the bisect pruned.
    - Key-byte predicates run in the PER-READER iterators, before the
      heap: a key the newest generation elides is elided in every
      older generation too (the predicate is a pure function of the
      key bytes), so merge semantics — newest wins, tombstones
      suppress — are unchanged for surviving keys.
    - Row predicates + projection run post-merge on the single winning
      value per key, inside the same pass.

    Returns the projected output rows in key order.
    """
    stats = evaluator.stats
    plen = len(prefix)

    def reader_iter(r):
        for k, v in r.scan(lo, hi, stats=stats):
            if evaluator.key_preds \
                    and not evaluator.eval_key(k[plen:]):
                stats.rows_elided += 1
                stats.key_elided += 1
                continue
            yield k, v

    iters = []
    for gen, r in enumerate(readers):
        if not r.overlaps(lo, hi):
            stats.blocks_skipped += len(r.index["blocks"])
            continue
        it = reader_iter(r)
        first = next(it, None)
        if first is not None:
            iters.append((first[0], gen, first[1], it))
    heapq.heapify(iters)
    out: list[tuple] = []
    last_key = None
    while iters:
        k, gen, v, it = heapq.heappop(iters)
        nxt = next(it, None)
        if nxt is not None:
            heapq.heappush(iters, (nxt[0], gen, nxt[1], it))
        if k == last_key:
            continue
        last_key = k
        if v == TOMBSTONE:
            continue
        row = loads(v)
        if not evaluator.eval_row(row):
            stats.rows_elided += 1
            continue
        out.append(evaluator.project(row))
        stats.rows_out += 1
    return out


# -- compaction-side execution ------------------------------------------


@dataclass
class CompactionFilterStats:
    """What one compaction task's filter pass did (ctl surface)."""

    rows_elided: int = 0
    blocks_skipped: int = 0
    ssts_elided: int = 0
    tables: set = field(default_factory=set)


def partition_elidable(inputs, policies: PolicySet):
    """Split compaction inputs into (fully-dead, must-merge) by the
    manifest-recorded key range of each SST: an input whose whole
    [first_key, last_key] lies below its table's horizon is elided
    outright — never read, never merged — its rows accounted via the
    manifest's ``n_records``."""
    dead, live = [], []
    for s in inputs:
        if policies and policies.range_dead(s.first_key, s.last_key):
            dead.append(s)
        else:
            live.append(s)
    return dead, live
