"""Epoch-versioned checkpoint persistence + manifest (incremental).

Reference counterpart: the Hummock commit path — shared-buffer upload on
checkpoint (uploader/mod.rs:1478), ``commit_epoch`` version bump
(src/meta/src/hummock/manager/commit_epoch.rs:73), and meta-backed
recovery (SURVEY.md §3.5).  The reference uploads per-epoch DELTAS (the
epoch's dirty key-value batches become SSTs); a full snapshot never
crosses the wire.

TPU-first incremental design
----------------------------
Executor state here is a pytree of dense device arrays, not a KV map —
so the natural delta is *dirty blocks of those arrays*:

1. A jitted digest program hashes every state leaf in fixed-size blocks
   ON DEVICE (splitmix-style position-mixed sum).  One small transfer
   fetches all block digests.
2. Blocks whose digest changed since the last checkpoint are fetched as
   flat slices (adjacent dirty blocks coalesce into runs) and written
   as a delta file — device→host traffic and disk bytes scale with the
   epoch's actual write set, not the state size.
3. Every ``full_interval`` checkpoints (or when >50% of blocks are
   dirty) a full snapshot re-bases the chain, bounding restore length
   and letting GC reclaim old chains.

Restore = nearest full ≤ target epoch + deltas replayed forward —
exactly the reference's version + version-delta reconstruction.  MV
contents can additionally be exported as SSTs for engine-free serving
(``export_mv_sst``).
"""

from __future__ import annotations

import io
import json
import os
import pickle
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.hash import _MIX_K1 as _GOLD, _mix64


def _normalize_u64(x):
    """Change-faithful view of any leaf as flat uint64 (1:1 elements).

    float64 avoids 64-bit float bitcasts (unimplemented by the TPU x64
    rewrite — see common/hash._key_words): frexp decomposes exactly
    into a 53-bit integer mantissa + exponent, with inf/nan pinned to
    sentinels so value flips never alias zero."""
    if x.dtype == jnp.bool_:
        v = x.astype(jnp.uint64)
    elif x.dtype == jnp.float64:
        m, e = jnp.frexp(x)
        m2 = (m * (2.0 ** 53)).astype(jnp.int64)
        m2 = jnp.where(jnp.isnan(x), jnp.int64(-(2 ** 62)), m2)
        m2 = jnp.where(jnp.isposinf(x), jnp.int64(2 ** 62), m2)
        m2 = jnp.where(jnp.isneginf(x), jnp.int64(-(2 ** 62) + 1), m2)
        v = m2.astype(jnp.uint64) ^ (e.astype(jnp.uint64)
                                     << np.uint64(53))
    elif x.dtype == jnp.float32:
        v = jax.lax.bitcast_convert_type(x, jnp.uint32).astype(jnp.uint64)
    elif x.dtype.itemsize == 8:
        v = jax.lax.bitcast_convert_type(x, jnp.uint64)
    else:
        u = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[x.dtype.itemsize]
        v = jax.lax.bitcast_convert_type(x, u).astype(jnp.uint64)
    return v.reshape(-1)


def _leaf_block_count(shape, dtype, block: int) -> int:
    n = int(np.prod(shape)) if shape else 1
    return max(1, -(-n // block))


class CheckpointStore:
    """All durable I/O goes through an ``ObjectStore``
    (storage/hummock/object_store.py) — the same seam the SST layer
    uses, so chaos tests can swap an in-memory or fault-injecting
    backend under the whole durability path."""

    _MANIFEST = "MANIFEST.json"

    def __init__(self, root: str, keep_epochs: int = 2,
                 full_interval: int = 16, block_elems: int = 1 << 9,
                 object_store=None):
        from risingwave_tpu.storage.hummock.object_store import (
            LocalFsObjectStore,
        )
        self.root = root
        self.keep_epochs = keep_epochs
        #: checkpoints between forced fulls (chain-length bound)
        self.full_interval = full_interval
        self.block_elems = block_elems
        self.store = object_store if object_store is not None \
            else LocalFsObjectStore(root)
        #: per-job digest program + last digests (in-memory fast path;
        #: a restarted process re-bases with a full snapshot)
        self._digest_fns: dict[str, Any] = {}
        self._last_digests: dict[str, tuple[int, np.ndarray]] = {}
        self._since_full: dict[str, int] = {}

    def _abs(self, key: str) -> str:
        """Filesystem path for a key when the backend is local (the
        legacy return-a-path surfaces, e.g. ``export_mv_sst``)."""
        root = getattr(self.store, "root", None)
        return os.path.join(root, key) if root is not None else key

    # -- manifest -------------------------------------------------------
    def _load_manifest(self) -> dict:
        if not self.store.exists(self._MANIFEST):
            return {"jobs": {}}
        return json.loads(self.store.get(self._MANIFEST))

    def _store_manifest(self, m: dict) -> None:
        self.store.put(self._MANIFEST, json.dumps(m, indent=1).encode())

    # -- digests --------------------------------------------------------
    def _digest_fn(self, job_name: str, leaves):
        """Cached jitted digest program, keyed by the state SHAPE: a
        dropped-and-recreated job with a different plan (different leaf
        list) must rebuild — and its first save re-bases with a full
        (stale digests are discarded with the program)."""
        sig = tuple((str(np.asarray(x).dtype) if not hasattr(x, "dtype")
                     else str(x.dtype), np.shape(x)) for x in leaves)
        cached = self._digest_fns.get(job_name)
        if cached is not None and cached[2] == sig:
            return cached[0], cached[1]
        if cached is not None:
            self._last_digests.pop(job_name, None)
            self._since_full.pop(job_name, None)
        block = self.block_elems
        nblocks = [
            _leaf_block_count(np.shape(x), None, block) for x in leaves
        ]

        def digest(leaves):
            outs = []
            for x, nb in zip(leaves, nblocks):
                v = _normalize_u64(jnp.asarray(x))
                pad = nb * block - v.shape[0]
                v = jnp.pad(v, (0, pad))
                idx = jnp.arange(v.shape[0], dtype=jnp.uint64)
                h = _mix64(v ^ (idx * _GOLD) ^ _GOLD)
                outs.append(jnp.sum(h.reshape(nb, block), axis=1))
            return jnp.concatenate(outs)

        self._digest_fns[job_name] = (jax.jit(digest), nblocks, sig)
        return self._digest_fns[job_name][0], nblocks

    # -- checkpoint save/load -------------------------------------------
    def save(self, job_name: str, epoch: int, states: Any,
             source_state: dict) -> None:
        """Persist one committed epoch (the 'SST upload' + commit).

        ``states`` may be a DEVICE pytree — only dirty blocks are
        fetched for delta checkpoints."""
        leaves, treedef = jax.tree.flatten(states)
        digest_jit, nblocks = self._digest_fn(job_name, leaves)
        digests = np.asarray(digest_jit(leaves))

        prev = self._last_digests.get(job_name)
        since_full = self._since_full.get(job_name, 0)
        dirty = None
        if prev is not None and prev[1].shape == digests.shape:
            dirty = digests != prev[1]
        kind = "delta"
        if (dirty is None or since_full >= self.full_interval - 1
                or int(dirty.sum()) * 2 > digests.shape[0]):
            kind = "full"
        # a re-save of an epoch already in the manifest (post-rescale
        # re-base, re-seal after a crashed commit) must be FULL: a
        # delta would overwrite a chain entry with a wrong-base delta
        if epoch in self._load_manifest()["jobs"].get(
                job_name, {}).get("epochs", []):
            kind = "full"

        key = f"{job_name}/epoch_{epoch}"
        if kind == "full":
            host = jax.device_get(leaves)
            buf = io.BytesIO()
            np.savez(buf, **{f"leaf_{i}": np.asarray(l)
                             for i, l in enumerate(host)})
            self.store.put(key + ".npz", buf.getvalue())
            self._since_full[job_name] = 0
        else:
            # fetch only dirty runs, flat per leaf
            payload: dict[str, np.ndarray] = {}
            off = 0
            block = self.block_elems
            for i, (x, nb) in enumerate(zip(leaves, nblocks)):
                leaf_dirty = dirty[off:off + nb]
                off += nb
                if not leaf_dirty.any():
                    continue
                flat = jnp.asarray(x).reshape(-1)
                n = flat.shape[0]
                # coalesce adjacent dirty blocks into runs
                b = 0
                while b < nb:
                    if not leaf_dirty[b]:
                        b += 1
                        continue
                    e = b
                    while e + 1 < nb and leaf_dirty[e + 1]:
                        e += 1
                    s_el = b * block
                    e_el = min((e + 1) * block, n)
                    payload[f"r_{i}_{s_el}"] = np.asarray(
                        flat[s_el:e_el]
                    )
                    b = e + 1
            buf = io.BytesIO()
            np.savez(buf, **payload)
            self.store.put(key + ".npz", buf.getvalue())
            self._since_full[job_name] = since_full + 1

        self.store.put(key + ".meta", pickle.dumps({
            "treedef": treedef, "source_state": source_state,
            "epoch": epoch, "kind": kind,
        }))

        m = self._load_manifest()
        job = m["jobs"].setdefault(job_name, {"epochs": []})
        # idempotent per epoch: a re-save of an already-committed epoch
        # (e.g. ALTER PARALLELISM re-basing state at the current epoch)
        # REPLACES the entry — appending would leave duplicate epochs
        # in GC/load bookkeeping (advisor r4)
        if epoch not in job["epochs"]:
            job["epochs"].append(epoch)
        job.setdefault("kind", {})[str(epoch)] = kind
        job["committed"] = epoch
        # GC beyond keep_epochs — but never break a delta chain: keep
        # everything back to the BASE FULL of the oldest epoch that
        # must stay readable (ref: hummock version GC keeps deltas
        # reachable from a checkpointed version)
        kinds = job["kind"]
        epochs_l = job["epochs"]
        if len(epochs_l) > self.keep_epochs:
            idx = len(epochs_l) - self.keep_epochs
            while idx > 0 and \
                    kinds.get(str(epochs_l[idx]), "full") != "full":
                idx -= 1
            for old in epochs_l[:idx]:
                kinds.pop(str(old), None)
                for suffix in (".npz", ".meta"):
                    self.store.delete(f"{job_name}/epoch_{old}{suffix}")
            job["epochs"] = epochs_l[idx:]
        self._store_manifest(m)
        # only after the manifest commit: a save that dies earlier must
        # not leave the digest cache pointing at an orphan file
        self._last_digests[job_name] = (epoch, digests)

    def invalidate(self, job_name: str) -> None:
        """Drop the in-memory digest cache for a job (called on any
        recovery rewind): the next save re-bases with a full snapshot
        instead of a delta computed against post-rewind live state."""
        self._last_digests.pop(job_name, None)
        self._since_full.pop(job_name, None)

    def committed_epoch(self, job_name: str) -> int | None:
        m = self._load_manifest()
        job = m["jobs"].get(job_name)
        return None if job is None else job.get("committed")

    def epochs(self, job_name: str) -> list[int]:
        """Retained (time-travel-readable) epochs, oldest first."""
        m = self._load_manifest()
        job = m["jobs"].get(job_name)
        return list(job.get("epochs", [])) if job else []

    def checkpoint_bytes(self, job_name: str, epoch: int) -> int:
        """Stored payload size of one epoch (soak-test observability)."""
        key = f"{job_name}/epoch_{epoch}.npz"
        return self.store.size(key) if self.store.exists(key) else 0

    def checkpoint_kind(self, job_name: str, epoch: int) -> str | None:
        m = self._load_manifest()
        job = m["jobs"].get(job_name)
        if job is None:
            return None
        return job.get("kind", {}).get(str(epoch), "full")

    def load(self, job_name: str, epoch: int | None = None):
        """Load (epoch, states_host, source_state); latest if epoch None.

        Reconstructs delta checkpoints from the nearest full plus the
        delta chain (the reference's version + version-deltas)."""
        if epoch is None:
            epoch = self.committed_epoch(job_name)
            if epoch is None:
                return None
        m = self._load_manifest()
        job = m["jobs"].get(job_name, {})
        kinds = job.get("kind", {})
        retained = [e for e in job.get("epochs", []) if e <= epoch]
        if not retained or retained[-1] != epoch:
            retained = retained + [epoch]  # legacy manifests
        # walk back to the base full
        chain: list[int] = []
        for e in reversed(retained):
            chain.append(e)
            if kinds.get(str(e), "full") == "full":
                break
        chain.reverse()
        base = chain[0]
        key = f"{job_name}/epoch_{base}"
        meta = pickle.loads(self.store.get(key + ".meta"))
        with np.load(io.BytesIO(self.store.get(key + ".npz"))) as z:
            leaves = [np.array(z[f"leaf_{i}"])
                      for i in range(len(z.files))]
        for e in chain[1:]:
            dkey = f"{job_name}/epoch_{e}"
            meta = pickle.loads(self.store.get(dkey + ".meta"))
            with np.load(io.BytesIO(self.store.get(dkey + ".npz"))) as z:
                for key in z.files:
                    _, li, s_el = key.split("_")
                    li, s_el = int(li), int(s_el)
                    data = z[key]
                    flat = leaves[li].reshape(-1)
                    flat[s_el:s_el + data.shape[0]] = data
        states = jax.tree.unflatten(meta["treedef"], leaves)
        return epoch, states, meta["source_state"]

    # -- MV export to SSTs ----------------------------------------------
    def export_mv_sst(self, job_name: str, epoch: int, mv_executor,
                      mv_state) -> str:
        """Write an MV's rows as an SST keyed by memcomparable pk.

        The serving path (or another process) can then read the MV at
        this epoch without the job's device state — the reference's
        batch-scan-from-Hummock pattern (SURVEY.md §3.4).
        """
        from risingwave_tpu.storage.sst import build_sst_bytes

        rows = mv_executor.to_host(mv_state)
        schema = mv_executor.in_schema
        pk = getattr(mv_executor, "pk_indices", tuple(range(len(schema))))
        encoded: list[tuple[bytes, bytes]] = []
        for row in rows:
            key = b"".join(
                _mc_encode_value(row[i], schema[i]) for i in pk
            )
            val = pickle.dumps(row, protocol=4)
            encoded.append((key, val))
        encoded.sort(key=lambda kv: kv[0])
        key = f"{job_name}/mv_epoch_{epoch}.sst"
        data, _ = build_sst_bytes(
            [k for k, _ in encoded], [v for _, v in encoded])
        self.store.put(key, data)
        return self._abs(key)


def _mc_encode_value(v, field) -> bytes:
    from risingwave_tpu.common.types import DataType
    from risingwave_tpu.storage import codec as C

    t = field.data_type
    if t.is_string:
        # terminated string encoding keeps prefix ordering correct
        return str(v).encode() + b"\x00"
    if t == DataType.DECIMAL:
        # to_host returns logical floats; re-scale to the exact integer
        # representation so fractional pks don't collide
        scaled = int(round(float(v) * 10**field.decimal_scale))
        return C.mc_encode_i64(np.asarray([scaled])).tobytes()
    if t in (DataType.FLOAT32, DataType.FLOAT64):
        return C.mc_encode_f64(np.asarray([float(v)])).tobytes()
    return C.mc_encode_i64(np.asarray([int(v)])).tobytes()
