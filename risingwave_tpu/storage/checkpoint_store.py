"""Epoch-versioned checkpoint persistence + manifest.

Reference counterpart: the Hummock commit path — shared-buffer upload on
checkpoint (uploader/mod.rs:1478), ``commit_epoch`` version bump
(src/meta/src/hummock/manager/commit_epoch.rs:73), and meta-backed
recovery (SURVEY.md §3.5).

Round-1 shape: each job's checkpoint = the device state pytree fetched
to host, stored as an ``.npz`` of leaves + a json tree spec, plus the
source offsets.  A json manifest (atomic rename) tracks the latest
committed epoch per job; old epochs are garbage-collected.  MV contents
can additionally be exported as SSTs for engine-free serving
(``export_mv_sst``).
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any

import jax
import numpy as np


class CheckpointStore:
    def __init__(self, root: str, keep_epochs: int = 2):
        self.root = root
        self.keep_epochs = keep_epochs
        os.makedirs(root, exist_ok=True)
        self._manifest_path = os.path.join(root, "MANIFEST.json")

    # -- manifest -------------------------------------------------------
    def _load_manifest(self) -> dict:
        if not os.path.exists(self._manifest_path):
            return {"jobs": {}}
        with open(self._manifest_path) as f:
            return json.load(f)

    def _store_manifest(self, m: dict) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(m, f, indent=1)
        os.replace(tmp, self._manifest_path)

    # -- checkpoint save/load -------------------------------------------
    def save(self, job_name: str, epoch: int, states: Any,
             source_state: dict) -> None:
        """Persist one committed epoch (the 'SST upload' + commit)."""
        job_dir = os.path.join(self.root, job_name)
        os.makedirs(job_dir, exist_ok=True)
        host_states = jax.device_get(states)
        leaves, treedef = jax.tree.flatten(host_states)
        path = os.path.join(job_dir, f"epoch_{epoch}")
        np.savez(path + ".npz.tmp.npz",
                 **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
        os.replace(path + ".npz.tmp.npz", path + ".npz")
        with open(path + ".meta.tmp", "wb") as f:
            pickle.dump({
                "treedef": treedef, "source_state": source_state,
                "epoch": epoch,
            }, f)
        os.replace(path + ".meta.tmp", path + ".meta")

        m = self._load_manifest()
        job = m["jobs"].setdefault(job_name, {"epochs": []})
        job["epochs"].append(epoch)
        job["committed"] = epoch
        # GC beyond keep_epochs (ref: hummock version GC)
        while len(job["epochs"]) > self.keep_epochs:
            old = job["epochs"].pop(0)
            for suffix in (".npz", ".meta"):
                p = os.path.join(job_dir, f"epoch_{old}{suffix}")
                if os.path.exists(p):
                    os.remove(p)
        self._store_manifest(m)

    def committed_epoch(self, job_name: str) -> int | None:
        m = self._load_manifest()
        job = m["jobs"].get(job_name)
        return None if job is None else job.get("committed")

    def epochs(self, job_name: str) -> list[int]:
        """Retained (time-travel-readable) epochs, oldest first."""
        m = self._load_manifest()
        job = m["jobs"].get(job_name)
        return list(job.get("epochs", [])) if job else []

    def load(self, job_name: str, epoch: int | None = None):
        """Load (epoch, states_host, source_state); latest if epoch None."""
        if epoch is None:
            epoch = self.committed_epoch(job_name)
            if epoch is None:
                return None
        path = os.path.join(self.root, job_name, f"epoch_{epoch}")
        with open(path + ".meta", "rb") as f:
            meta = pickle.load(f)
        with np.load(path + ".npz") as z:
            leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
        states = jax.tree.unflatten(meta["treedef"], leaves)
        return epoch, states, meta["source_state"]

    # -- MV export to SSTs ----------------------------------------------
    def export_mv_sst(self, job_name: str, epoch: int, mv_executor,
                      mv_state) -> str:
        """Write an MV's rows as an SST keyed by memcomparable pk.

        The serving path (or another process) can then read the MV at
        this epoch without the job's device state — the reference's
        batch-scan-from-Hummock pattern (SURVEY.md §3.4).
        """
        from risingwave_tpu.storage.sst import write_sst

        rows = mv_executor.to_host(mv_state)
        schema = mv_executor.in_schema
        pk = getattr(mv_executor, "pk_indices", tuple(range(len(schema))))
        encoded: list[tuple[bytes, bytes]] = []
        for row in rows:
            key = b"".join(
                _mc_encode_value(row[i], schema[i]) for i in pk
            )
            val = pickle.dumps(row, protocol=4)
            encoded.append((key, val))
        encoded.sort(key=lambda kv: kv[0])
        job_dir = os.path.join(self.root, job_name)
        os.makedirs(job_dir, exist_ok=True)
        path = os.path.join(job_dir, f"mv_epoch_{epoch}.sst")
        write_sst(path, [k for k, _ in encoded], [v for _, v in encoded])
        return path


def _mc_encode_value(v, field) -> bytes:
    from risingwave_tpu.common.types import DataType
    from risingwave_tpu.storage import codec as C

    t = field.data_type
    if t.is_string:
        # terminated string encoding keeps prefix ordering correct
        return str(v).encode() + b"\x00"
    if t == DataType.DECIMAL:
        # to_host returns logical floats; re-scale to the exact integer
        # representation so fractional pks don't collide
        scaled = int(round(float(v) * 10**field.decimal_scale))
        return C.mc_encode_i64(np.asarray([scaled])).tobytes()
    if t in (DataType.FLOAT32, DataType.FLOAT64):
        return C.mc_encode_f64(np.asarray([float(v)])).tobytes()
    return C.mc_encode_i64(np.asarray([int(v)])).tobytes()
