"""Epoch-versioned checkpoint persistence + manifest (incremental).

Reference counterpart: the Hummock commit path — shared-buffer upload on
checkpoint (uploader/mod.rs:1478), ``commit_epoch`` version bump
(src/meta/src/hummock/manager/commit_epoch.rs:73), and meta-backed
recovery (SURVEY.md §3.5).  The reference uploads per-epoch DELTAS (the
epoch's dirty key-value batches become SSTs); a full snapshot never
crosses the wire.

TPU-first incremental design
----------------------------
Executor state here is a pytree of dense device arrays, not a KV map —
so the natural delta is *dirty blocks of those arrays*:

1. A jitted digest program hashes every state leaf in fixed-size blocks
   ON DEVICE (storage/digest.py — the SAME scheme the in-memory shadow
   snapshot uses, so on the async path the digest vector is computed
   once per snapshot and handed in; the store never re-reads state).
2. Blocks whose digest changed since the last checkpoint are fetched as
   flat slices (adjacent dirty blocks coalesce into runs) and written
   as a delta file — device→host traffic and disk bytes scale with the
   epoch's actual write set, not the state size.
3. Every ``full_interval`` checkpoints (or when >50% of blocks are
   dirty) a full snapshot re-bases the chain, bounding restore length
   and letting GC reclaim old chains.

Persistence is split into two phases so a background uploader can
pipeline it (stream/checkpoint.py):

- ``prepare()`` — the device→host fetch: stages the epoch's payload as
  host arrays and decides full-vs-delta.  After it returns, the caller
  may mutate/donate the device buffers.
- ``commit()`` — npz/meta encode, object-store writes, manifest bump,
  GC, digest-cache advance.

``save()`` remains the synchronous composition of both.  A manifest
lock serializes commits across jobs (one engine hosts several jobs,
each with its own uploader thread, over ONE manifest file).

Restore = nearest full ≤ target epoch + deltas replayed forward —
exactly the reference's version + version-delta reconstruction.  MV
contents can additionally be exported as SSTs for engine-free serving
(``export_mv_sst``).
"""

from __future__ import annotations

import io
import json
import os
import pickle
import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.storage.digest import (
    DEFAULT_BLOCK_ELEMS,
    digest_leaves,
    leaf_block_count,
    normalize_u64,
)
from risingwave_tpu.storage.integrity import (
    CheckpointCorruption,
    crc32c,
    quarantine,
    record_integrity_error,
)

# back-compat aliases (pre-round-7 internal names)
_normalize_u64 = normalize_u64


def _leaf_block_count(shape, dtype, block: int) -> int:
    return leaf_block_count(shape, block)


class CheckpointStore:
    """All durable I/O goes through an ``ObjectStore``
    (storage/hummock/object_store.py) — the same seam the SST layer
    uses, so chaos tests can swap an in-memory or fault-injecting
    backend under the whole durability path."""

    _MANIFEST = "MANIFEST.json"

    def __init__(self, root: str, keep_epochs: int = 2,
                 full_interval: int = 16,
                 block_elems: int = DEFAULT_BLOCK_ELEMS,
                 object_store=None, metrics=None):
        from risingwave_tpu.storage.hummock.object_store import (
            LocalFsObjectStore,
        )
        self.root = root
        self.keep_epochs = keep_epochs
        #: integrity counters (integrity_errors_total, repairs)
        self.metrics = metrics
        #: checkpoints between forced fulls (chain-length bound)
        self.full_interval = full_interval
        self.block_elems = block_elems
        self.store = object_store if object_store is not None \
            else LocalFsObjectStore(root)
        #: per-job digest program + last digests (in-memory fast path;
        #: a restarted process re-bases with a full snapshot)
        self._digest_fns: dict[str, Any] = {}
        self._last_digests: dict[str, tuple[int, np.ndarray]] = {}
        self._since_full: dict[str, int] = {}
        #: serializes manifest read-modify-write + digest-cache updates
        #: across uploader threads (several jobs share one manifest)
        self._lock = threading.RLock()

    def _abs(self, key: str) -> str:
        """Filesystem path for a key when the backend is local (the
        legacy return-a-path surfaces, e.g. ``export_mv_sst``)."""
        root = getattr(self.store, "root", None)
        return os.path.join(root, key) if root is not None else key

    def _manifest_txn(self):
        """Cross-PROCESS manifest transaction: ``self._lock`` excludes
        this store's uploader threads; an OS-level flock on the shared
        directory excludes OTHER worker processes (and other store
        instances in one process).  Exchange-lite's parallel barrier
        dispatch lets several workers' uploaders commit different
        lineages concurrently over ONE shared manifest — without this
        the read-modify-write cycles interleave and lose each other's
        epoch records (observed as broken delta chains).  In-memory
        stores (single-process by construction) skip the file lock."""
        import contextlib

        root = getattr(self.store, "root", None)

        @contextlib.contextmanager
        def txn():
            with self._lock:
                if root is None:
                    yield
                    return
                import fcntl

                os.makedirs(root, exist_ok=True)
                with open(os.path.join(root, "MANIFEST.lock"),
                          "a+b") as f:
                    fcntl.flock(f, fcntl.LOCK_EX)
                    try:
                        yield
                    finally:
                        fcntl.flock(f, fcntl.LOCK_UN)

        return txn()

    # -- manifest -------------------------------------------------------
    def _load_manifest(self) -> dict:
        if not self.store.exists(self._MANIFEST):
            return {"jobs": {}}
        return json.loads(self.store.get(self._MANIFEST))

    def _store_manifest(self, m: dict) -> None:
        self.store.put(self._MANIFEST, json.dumps(m, indent=1).encode())

    # -- digests --------------------------------------------------------
    def _digest_fn(self, job_name: str, leaves):
        """Cached jitted digest program, keyed by the state SHAPE: a
        dropped-and-recreated job with a different plan (different leaf
        list) must rebuild — and its first save re-bases with a full
        (stale digests are discarded with the program)."""
        sig = tuple((str(np.asarray(x).dtype) if not hasattr(x, "dtype")
                     else str(x.dtype), np.shape(x)) for x in leaves)
        with self._lock:
            cached = self._digest_fns.get(job_name)
            if cached is not None and cached[2] == sig:
                return cached[0], cached[1]
            if cached is not None:
                self._last_digests.pop(job_name, None)
                self._since_full.pop(job_name, None)
            block = self.block_elems
            nblocks = [
                leaf_block_count(np.shape(x), block) for x in leaves
            ]

            def digest(leaves):
                return digest_leaves(
                    [jnp.asarray(x) for x in leaves], nblocks, block
                )

            self._digest_fns[job_name] = (jax.jit(digest), nblocks, sig)
            return self._digest_fns[job_name][0], nblocks

    # -- checkpoint save: prepare (fetch) / commit (write) --------------
    def prepare(self, job_name: str, epoch: int, leaves, shapes,
                treedef, source_state: dict, digests=None,
                lanes=None) -> dict:
        """Stage one epoch's payload on the host.

        ``leaves`` may be device arrays of any shape (they are read as
        flat element streams); ``digests`` (uint64 vector from the
        shadow snapshot's update program) skips the digest pass.
        ``lanes`` (per-leaf ``(rows, row_elems)`` or None, from a
        per-shard shadow) describes the digest's block grid: lane
        leaves restart their blocks at every row, so the dirty-run
        extraction below walks rows and never emits a run crossing a
        shard boundary.  After this returns, the caller may freely
        mutate or donate the device buffers — everything needed by
        ``commit`` is host-resident."""
        from risingwave_tpu.storage.digest import lane_block_count

        block = self.block_elems
        if lanes is None:
            lanes = [None] * len(shapes)
        nblocks = [
            lane_block_count(s, ln[0], block) if ln
            else leaf_block_count(s, block)
            for s, ln in zip(shapes, lanes)
        ]
        if digests is None:
            # the store-side digest pass is flat-only; a lane grid is
            # meaningful only for shadow-computed digest vectors
            lanes = [None] * len(shapes)
            digest_jit, nblocks = self._digest_fn(job_name, leaves)
            digests = np.asarray(digest_jit(leaves))
        else:
            digests = np.asarray(digests).astype(np.uint64, copy=False)

        with self._lock:
            prev = self._last_digests.get(job_name)
            since_full = self._since_full.get(job_name, 0)
            # a re-save of an epoch already in the manifest
            # (post-rescale re-base, re-seal after a crashed commit)
            # must be FULL: a delta would overwrite a chain entry with
            # a wrong-base delta
            resave = epoch in self._load_manifest()["jobs"].get(
                job_name, {}).get("epochs", [])

        dirty = None
        if prev is not None and prev[1].shape == digests.shape:
            dirty = digests != prev[1]
        kind = "delta"
        if (dirty is None or since_full >= self.full_interval - 1
                or int(dirty.sum()) * 2 > digests.shape[0] or resave):
            kind = "full"

        payload: dict[str, np.ndarray] = {}
        if kind == "full":
            host = jax.device_get(
                [jnp.asarray(x).reshape(-1) for x in leaves]
            )
            for i, (h, s) in enumerate(zip(host, shapes)):
                payload[f"leaf_{i}"] = np.asarray(h).reshape(s)
        else:
            # fetch only dirty runs, flat per leaf; lane leaves walk
            # per shard row so no run crosses a shard boundary
            off = 0
            for i, (x, nb, shape, ln) in enumerate(
                    zip(leaves, nblocks, shapes, lanes)):
                leaf_dirty = dirty[off:off + nb]
                off += nb
                if not leaf_dirty.any():
                    continue
                flat = jnp.asarray(x).reshape(-1)
                n = flat.shape[0]
                rows, m = ln if ln else (1, n)
                nb_row = nb // rows
                for r in range(rows):
                    row_dirty = leaf_dirty[r * nb_row:(r + 1) * nb_row]
                    if ln and not row_dirty.any():
                        continue
                    base_el = r * m
                    # coalesce adjacent dirty blocks into runs
                    b = 0
                    while b < nb_row:
                        if not row_dirty[b]:
                            b += 1
                            continue
                        e = b
                        while e + 1 < nb_row and row_dirty[e + 1]:
                            e += 1
                        s_el = base_el + b * block
                        e_el = base_el + min((e + 1) * block, m)
                        payload[f"r_{i}_{s_el}"] = np.asarray(
                            flat[s_el:e_el]
                        )
                        b = e + 1
        return {
            "job": job_name, "epoch": epoch, "kind": kind,
            "payload": payload, "treedef": treedef,
            "source_state": source_state, "digests": digests,
        }

    def commit(self, prep: dict) -> None:
        """Write a prepared epoch: objects, manifest bump, GC, digest
        cache — the durable commit point the uploader acks."""
        job_name, epoch, kind = prep["job"], prep["epoch"], prep["kind"]
        key = f"{job_name}/epoch_{epoch}"
        buf = io.BytesIO()
        np.savez(buf, **prep["payload"])
        npz_bytes = buf.getvalue()
        meta_bytes = pickle.dumps({
            "treedef": prep["treedef"],
            "source_state": prep["source_state"],
            "epoch": epoch, "kind": kind,
        })
        with self._manifest_txn():
            self.store.put(key + ".npz", npz_bytes)
            self.store.put(key + ".meta", meta_bytes)
            m = self._load_manifest()
            job = m["jobs"].setdefault(job_name, {"epochs": []})
            # crc32c trailer per epoch object, recorded in the
            # manifest (computed over the bytes BEFORE the put, so a
            # put corrupted in flight — or on disk later — mismatches
            # on read and the typed CheckpointCorruption fires)
            job.setdefault("crc", {})[str(epoch)] = {
                "npz": crc32c(npz_bytes), "meta": crc32c(meta_bytes),
            }
            # idempotent per epoch: a re-save of an already-committed
            # epoch (e.g. ALTER PARALLELISM re-basing state at the
            # current epoch) REPLACES the entry — appending would leave
            # duplicate epochs in GC/load bookkeeping (advisor r4)
            if epoch not in job["epochs"]:
                job["epochs"].append(epoch)
            job.setdefault("kind", {})[str(epoch)] = kind
            job["committed"] = epoch
            # GC beyond keep_epochs — but never break a delta chain:
            # keep everything back to the BASE FULL of the oldest epoch
            # that must stay readable (ref: hummock version GC keeps
            # deltas reachable from a checkpointed version)
            kinds = job["kind"]
            epochs_l = job["epochs"]
            if len(epochs_l) > self.keep_epochs:
                idx = len(epochs_l) - self.keep_epochs
                while idx > 0 and \
                        kinds.get(str(epochs_l[idx]), "full") != "full":
                    idx -= 1
                for old in epochs_l[:idx]:
                    kinds.pop(str(old), None)
                    job.get("crc", {}).pop(str(old), None)
                    for suffix in (".npz", ".meta"):
                        self.store.delete(
                            f"{job_name}/epoch_{old}{suffix}"
                        )
                job["epochs"] = epochs_l[idx:]
            self._store_manifest(m)
            # only after the manifest commit: a save that dies earlier
            # must not leave the digest cache pointing at an orphan file
            self._last_digests[job_name] = (epoch, prep["digests"])
            self._since_full[job_name] = 0 if kind == "full" \
                else self._since_full.get(job_name, 0) + 1

    def save(self, job_name: str, epoch: int, states: Any,
             source_state: dict, digests=None, lanes=None) -> None:
        """Persist one committed epoch synchronously (prepare+commit —
        the 'SST upload' + commit in one call).

        ``states`` may be a DEVICE pytree — only dirty blocks are
        fetched for delta checkpoints."""
        leaves, treedef = jax.tree.flatten(states)
        shapes = [np.shape(x) for x in leaves]
        self.commit(self.prepare(
            job_name, epoch, leaves, shapes, treedef, source_state,
            digests=digests, lanes=lanes,
        ))

    def invalidate(self, job_name: str) -> None:
        """Drop the in-memory digest cache for a job (called on any
        recovery rewind): the next save re-bases with a full snapshot
        instead of a delta computed against post-rewind live state.
        Also vacuums orphan epoch files a crashed upload left behind
        (object written, manifest never bumped)."""
        with self._lock:
            self._last_digests.pop(job_name, None)
            self._since_full.pop(job_name, None)
        self.vacuum_orphans(job_name)

    def vacuum_orphans(self, job_name: str) -> int:
        """Delete ``epoch_N.{npz,meta}`` objects whose epoch the
        manifest does not reference — the residue of a crash between
        the object write and the manifest commit.  Called on recovery
        rewinds, when no upload can be in flight for the job."""
        removed = 0
        with self._lock:
            m = self._load_manifest()
            known = {str(e) for e in m["jobs"].get(
                job_name, {}).get("epochs", [])}
            for key in self.store.list(job_name + "/"):
                name = key.rsplit("/", 1)[-1]
                if not name.startswith("epoch_"):
                    continue  # mv_epoch_*.sst exports etc.
                stem = name[len("epoch_"):]
                for suffix in (".npz", ".meta"):
                    if stem.endswith(suffix):
                        stem = stem[:-len(suffix)]
                        break
                else:
                    continue
                if stem.isdigit() and stem not in known:
                    self.store.delete(key)
                    removed += 1
        return removed

    def committed_epoch(self, job_name: str) -> int | None:
        m = self._load_manifest()
        job = m["jobs"].get(job_name)
        return None if job is None else job.get("committed")

    def epochs(self, job_name: str) -> list[int]:
        """Retained (time-travel-readable) epochs, oldest first."""
        m = self._load_manifest()
        job = m["jobs"].get(job_name)
        return list(job.get("epochs", [])) if job else []

    def checkpoint_bytes(self, job_name: str, epoch: int) -> int:
        """Stored payload size of one epoch (soak-test observability)."""
        key = f"{job_name}/epoch_{epoch}.npz"
        return self.store.size(key) if self.store.exists(key) else 0

    def checkpoint_kind(self, job_name: str, epoch: int) -> str | None:
        m = self._load_manifest()
        job = m["jobs"].get(job_name)
        if job is None:
            return None
        return job.get("kind", {}).get(str(epoch), "full")

    def load(self, job_name: str, epoch: int | None = None):
        """Load (epoch, states_host, source_state); latest if epoch None.

        Reconstructs delta checkpoints from the nearest full plus the
        delta chain (the reference's version + version-deltas).  Every
        object fetched is verified against the crc the manifest
        recorded at commit.  A latest-epoch load (``epoch=None`` — the
        recovery path) SELF-HEALS: a corrupt object quarantines its
        lineage tail (``quarantine_epoch``) and the load rewinds to
        the last epoch whose whole chain verifies — the round-credit
        rewind upstream then replays the gap.  An explicit-epoch load
        (time travel, scale-handover slices) must be exact, so
        corruption there raises ``CheckpointCorruption``.

        Holds the manifest lock so a concurrent uploader commit's GC
        cannot delete a chain file between the manifest read and the
        fetch."""
        with self._lock:
            if epoch is not None:
                return self._load_locked(job_name, epoch)
            while True:
                target = self.committed_epoch(job_name)
                if target is None:
                    return None
                try:
                    return self._load_locked(job_name, target)
                except CheckpointCorruption as e:
                    record_integrity_error(self.metrics, e)
                    dropped = self.quarantine_epoch(
                        job_name, getattr(e, "epoch", target),
                        reason=str(e),
                    )
                    if not dropped:
                        raise  # nothing left to rewind past
                    if self.metrics is not None:
                        self.metrics.inc("integrity_repairs_total",
                                         kind="checkpoint_rewind")

    def _get_verified(self, job: dict, job_name: str, epoch: int,
                      suffix: str) -> bytes:
        key = f"{job_name}/epoch_{epoch}.{suffix}"
        data = self.store.get(key)
        rec = job.get("crc", {}).get(str(epoch))
        if rec is not None and crc32c(data) != int(rec[suffix]):
            err = CheckpointCorruption(
                f"{key}: checkpoint object checksum mismatch", key=key
            )
            err.epoch = epoch
            raise err
        return data

    def _load_locked(self, job_name: str, epoch: int | None):
        if epoch is None:
            epoch = self.committed_epoch(job_name)
            if epoch is None:
                return None
        m = self._load_manifest()
        job = m["jobs"].get(job_name, {})
        kinds = job.get("kind", {})
        retained = [e for e in job.get("epochs", []) if e <= epoch]
        if not retained or retained[-1] != epoch:
            retained = retained + [epoch]  # legacy manifests
        # walk back to the base full
        chain: list[int] = []
        for e in reversed(retained):
            chain.append(e)
            if kinds.get(str(e), "full") == "full":
                break
        chain.reverse()
        base = chain[0]
        meta = pickle.loads(
            self._get_verified(job, job_name, base, "meta")
        )
        with np.load(io.BytesIO(
                self._get_verified(job, job_name, base, "npz"))) as z:
            leaves = [np.array(z[f"leaf_{i}"])
                      for i in range(len(z.files))]
        for e in chain[1:]:
            meta = pickle.loads(
                self._get_verified(job, job_name, e, "meta")
            )
            with np.load(io.BytesIO(
                    self._get_verified(job, job_name, e, "npz"))) as z:
                for key in z.files:
                    _, li, s_el = key.split("_")
                    li, s_el = int(li), int(s_el)
                    data = z[key]
                    flat = leaves[li].reshape(-1)
                    flat[s_el:s_el + data.shape[0]] = data
        states = jax.tree.unflatten(meta["treedef"], leaves)
        return epoch, states, meta["source_state"]

    # -- integrity: quarantine + lineage repair --------------------------
    def quarantine_epoch(self, job_name: str, epoch: int,
                         reason: str = "checksum mismatch") -> list[int]:
        """Quarantine one corrupt epoch and drop it — plus every later
        DELTA chained through it (a full re-bases the chain, so epochs
        from the next full onward stay) — from the manifest.  Dropped
        objects become vacuumable orphans; a durable quarantine note
        records each.  Returns the dropped epochs."""
        with self._manifest_txn():
            m = self._load_manifest()
            job = m["jobs"].get(job_name)
            if job is None or epoch not in job.get("epochs", []):
                return []
            epochs = job["epochs"]
            kinds = job.setdefault("kind", {})
            i = epochs.index(epoch)
            j = i + 1
            while j < len(epochs) \
                    and kinds.get(str(epochs[j]), "full") != "full":
                j += 1
            dropped = epochs[i:j]
            for e in dropped:
                quarantine(self.store, f"{job_name}/epoch_{e}.npz",
                           reason=reason, by="checkpoint_store",
                           metrics=self.metrics)
                kinds.pop(str(e), None)
                job.get("crc", {}).pop(str(e), None)
            job["epochs"] = epochs[:i] + epochs[j:]
            job["committed"] = max(job["epochs"]) if job["epochs"] \
                else 0
            self._store_manifest(m)
            # stale digest cache could delta against a dropped base
            self._last_digests.pop(job_name, None)
            self._since_full.pop(job_name, None)
        return dropped

    def verify_job(self, job_name: str) -> dict:
        """Scrub one job's retained lineage: every epoch object's
        bytes against the manifest-recorded crc (no decode).  Returns
        ``{"verified": n, "corrupt": [(epoch, key)]}``."""
        from risingwave_tpu.storage.integrity import (
            verify_checkpoint_store,
        )

        with self._lock:
            rep = verify_checkpoint_store(self.store, self._MANIFEST,
                                          jobs=[job_name])
        return {"verified": rep["verified"],
                "corrupt": [(e, k) for _, e, k in rep["corrupt"]]}

    def repair_lineage(self, job_name: str) -> dict:
        """Verify + self-heal one lineage in place: corrupt epochs are
        quarantined and the chain truncates to verified state (the
        corrupt-checkpoint repair the scrubber triggers through the
        owning worker).  The next save after a repair re-bases with a
        full snapshot (digest cache dropped by ``quarantine_epoch``)."""
        rep = self.verify_job(job_name)
        dropped: list[int] = []
        for e, key in rep["corrupt"]:
            record_integrity_error(
                self.metrics,
                CheckpointCorruption(f"{key}: scrub mismatch", key=key),
            )
            dropped += self.quarantine_epoch(
                job_name, e, reason="scrub checksum mismatch"
            )
        if dropped and self.metrics is not None:
            self.metrics.inc("integrity_repairs_total",
                             kind="checkpoint_rewind")
        return {"verified": rep["verified"],
                "corrupt": [k for _, k in rep["corrupt"]],
                "dropped_epochs": sorted(set(dropped))}

    # -- MV export to SSTs ----------------------------------------------
    def export_mv_sst(self, job_name: str, epoch: int, mv_executor,
                      mv_state) -> str:
        """Write an MV's rows as an SST keyed by memcomparable pk.

        The serving path (or another process) can then read the MV at
        this epoch without the job's device state — the reference's
        batch-scan-from-Hummock pattern (SURVEY.md §3.4).
        """
        from risingwave_tpu.storage.sst import build_sst_bytes

        rows = mv_executor.to_host(mv_state)
        schema = mv_executor.in_schema
        pk = getattr(mv_executor, "pk_indices", tuple(range(len(schema))))
        encoded: list[tuple[bytes, bytes]] = []
        for row in rows:
            key = b"".join(
                _mc_encode_value(row[i], schema[i]) for i in pk
            )
            val = pickle.dumps(row, protocol=4)
            encoded.append((key, val))
        encoded.sort(key=lambda kv: kv[0])
        key = f"{job_name}/mv_epoch_{epoch}.sst"
        data, _ = build_sst_bytes(
            [k for k, _ in encoded], [v for _, v in encoded])
        self.store.put(key, data)
        return self._abs(key)


def _mc_encode_value(v, field) -> bytes:
    from risingwave_tpu.common.types import DataType
    from risingwave_tpu.storage import codec as C

    t = field.data_type
    if field.nullable:
        # NULLABLE pk components (outer-join MV keys) carry a
        # presence prefix: \x00 + enc for present values, \x01 for
        # NULL — present values keep their relative byte order, NULLs
        # sort LAST (the pg default the serving ORDER BY pushdown
        # mirrors).  Non-nullable fields stay prefix-free, so every
        # pre-existing key encoding is unchanged.
        if v is None:
            return b"\x01"
        from dataclasses import replace as _replace

        return b"\x00" + _mc_encode_value(
            v, _replace(field, nullable=False)
        )
    if t.is_string:
        # terminated string encoding keeps prefix ordering correct
        return str(v).encode() + b"\x00"
    if t == DataType.DECIMAL:
        # to_host returns logical floats; re-scale to the exact integer
        # representation so fractional pks don't collide
        scaled = int(round(float(v) * 10**field.decimal_scale))
        return C.mc_encode_i64(np.asarray([scaled])).tobytes()
    if t in (DataType.FLOAT32, DataType.FLOAT64):
        return C.mc_encode_f64(np.asarray([float(v)])).tobytes()
    return C.mc_encode_i64(np.asarray([int(v)])).tobytes()
