"""HummockStorage: merge-free ingest, pinned reads, compaction, vacuum.

Reference counterpart: ``HummockStorage`` + ``SstableStore``
(src/storage/src/hummock/store/hummock_storage.rs:673,
sstable_store.rs:208) with the meta-side manager's task scheduling and
orphan GC (src/meta/src/hummock/manager/).

The write path is the whole point: ``write_batch`` seals a sorted
batch, uploads ONE new SST object and commits a version delta adding
it to L0 — **no merge I/O ever happens on the ingest path**.  Merging
is the background ``CompactorService``'s job (compactor.py), which
picks tasks from level budgets here, executes them off-thread, and
commits results as version deltas.  Serving reads pin a version so
the SST set under them stays stable (and vacuum-safe) while the
compactor rewrites levels underneath.  When L0 outruns the compactor,
``stalled()`` trips and the barrier loop's write-stall hook blocks in
``wait_below_stall`` — Hummock's write-limit backpressure.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from risingwave_tpu.storage.hummock.version import (
    HummockVersion,
    SstInfo,
    VersionManager,
)
from risingwave_tpu.storage.pushdown import (
    PolicySet,
    partition_elidable,
)
from risingwave_tpu.storage.sst import (
    TOMBSTONE,
    BlockCache,
    SstReader,
    build_sst_bytes,
    merge_scan,
    output_is_bottommost,
)

SST_PREFIX = "sst/"


@dataclass
class CompactionTask:
    """One unit of background work: merge ``inputs`` into a single run
    at ``out_level``.  ``drop_tombstones`` is decided at pick time
    under the version lock; it stays valid for the task's lifetime
    because data only flows downward and every compaction that could
    populate a deeper level would need one of this task's (locked)
    levels as its input."""

    task_id: int
    in_level: int
    out_level: int
    inputs: list[SstInfo]
    drop_tombstones: bool
    epoch: int
    #: pushdown plane: the version's expiry policies, captured at pick
    #: time.  Applied ONLY when ``drop_tombstones`` (bottommost-output
    #: legality — the same rule, for the same resurrection reason).
    policies: "PolicySet | None" = None
    #: filled by execution
    outputs: list[SstInfo] = field(default_factory=list)
    in_bytes: int = 0
    #: pushdown-filter accounting (filled by execution)
    rows_elided: int = 0
    blocks_skipped: int = 0
    ssts_elided: int = 0


class PinnedVersion:
    """A serving handle over one pinned version (context manager)."""

    def __init__(self, storage: "HummockStorage", pin_id: int,
                 version: HummockVersion):
        self._storage = storage
        self._pin_id = pin_id
        self.version = version
        self._released = False

    # newest-first reader order: L0 front-to-back, then level 1, 2, ...
    def _readers(self):
        return [self._storage._reader(s.key)
                for lv in self.version.levels for s in lv]

    def get(self, key: bytes) -> bytes | None:
        m = self._storage.metrics
        for lv in self.version.levels:
            for s in lv:
                r = self._storage._reader(s.key)
                if not r.may_contain(key):
                    if m is not None:
                        m.inc("storage_bloom_filter_total",
                              result="skip")
                    continue
                v = r.get(key)
                if m is not None:
                    m.inc("storage_bloom_filter_total",
                          result="hit" if v is not None else "miss")
                if v is not None:
                    return None if v == TOMBSTONE else v
        return None

    def scan(self, lo: bytes = b"", hi: bytes | None = None):
        yield from merge_scan(self._readers(), lo, hi)

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._storage.versions.unpin(self._pin_id)
            self._storage._update_gauges()

    def __enter__(self) -> "PinnedVersion":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __del__(self):  # best-effort: never leak a pin
        try:
            self.release()
        except Exception:
            pass


class HummockStorage:
    """The storage-service facade over one object store."""

    def __init__(self, store, cache: "BlockCache | None" = None,
                 metrics=None, l0_trigger: int = 4,
                 base_bytes: int = 4 << 20, ratio: int = 8,
                 stall_l0: int = 12, bloom_bits_per_key: int = 10,
                 version_base_interval: int = 64):
        self.store = store
        self.cache = cache if cache is not None else BlockCache(512)
        self.metrics = metrics
        self.l0_trigger = l0_trigger
        self.base_bytes = base_bytes
        self.ratio = ratio
        #: L0 run count at/over which ingest must stall (write limit)
        self.stall_l0 = stall_l0
        self.bloom_bits_per_key = bloom_bits_per_key
        self.versions = VersionManager(
            store, base_interval=version_base_interval)
        self._lock = threading.RLock()
        #: commits signal this: stalled writers + the compactor wait
        self._commit_cv = threading.Condition(self._lock)
        self._readers: dict[str, SstReader] = {}
        #: uploaded-but-uncommitted object keys vacuum must not touch
        self._protected: set[str] = set()
        #: levels owned by in-flight compaction tasks
        self._busy_levels: set[int] = set()
        self._next_task = 1
        #: write-path purity counter: merges performed on ingest (0)
        self.write_path_merges = 0
        #: pushdown-plane compaction-filter counters (cumulative)
        self.pushdown_rows_elided = 0
        self.pushdown_blocks_skipped = 0
        self.pushdown_ssts_elided = 0
        #: corruption sink ``(kind, key, context)`` — the meta points
        #: this at its quarantine+repair pipeline; None = detection
        #: only (typed error + quarantine note)
        self.on_corruption = None
        # next SST id: past the largest object present (orphans from a
        # crashed run included, so a reused id can never alias one)
        ids = [int(k[len(SST_PREFIX):].split(".")[0])
               for k in store.list(SST_PREFIX)]
        self._next_sst = (max(ids) + 1) if ids else 1
        self._update_gauges()

    # -- plumbing -------------------------------------------------------
    def _reader(self, key: str) -> SstReader:
        with self._lock:
            r = self._readers.get(key)
            if r is None:
                r = SstReader(store=self.store, key=key,
                              cache=self.cache)
                self._readers[key] = r
            return r

    def _alloc_sst_key(self) -> str:
        with self._lock:
            key = f"{SST_PREFIX}{self._next_sst:012d}.sst"
            self._next_sst += 1
            self._protected.add(key)
            return key

    def _upload_sst(self, pairs: list[tuple[bytes, bytes]]) -> SstInfo:
        """Build + upload one SST; the key stays vacuum-protected
        until its delta commits (or the caller aborts)."""
        key = self._alloc_sst_key()
        try:
            data, meta = build_sst_bytes(
                [k for k, _ in pairs], [v for _, v in pairs],
                bloom_bits_per_key=self.bloom_bits_per_key,
            )
            self.store.put(key, data)
        except BaseException:
            # failed upload: whatever (if anything) landed is garbage
            # this process will never commit — expose it to vacuum
            with self._lock:
                self._protected.discard(key)
            raise
        if self.metrics is not None:
            self.metrics.inc("storage_sst_uploads_total")
            self.metrics.inc("storage_sst_upload_bytes_total",
                             len(data))
        return SstInfo(key=key, first_key=meta.first_key,
                       last_key=meta.last_key,
                       n_records=meta.n_records, size=meta.size)

    def _update_gauges(self) -> None:
        if self.metrics is None:
            return
        v = self.versions.current
        self.metrics.set_gauge("storage_l0_runs", v.l0_depth())
        self.metrics.set_gauge("storage_version_id", v.vid)
        self.metrics.set_gauge("storage_pinned_versions",
                               self.versions.pinned_count())
        self.metrics.set_gauge("storage_sst_files", v.file_count())

    # -- write path (NO merge I/O) --------------------------------------
    def write_batch(self, pairs: list[tuple[bytes, bytes]],
                    epoch: int = 0) -> SstInfo | None:
        """Seal one batch as a new L0 run: upload + version delta.
        Later duplicates win within the batch; deletes pass TOMBSTONE
        values (``delete_batch``)."""
        if not pairs:
            return None
        dedup = dict(pairs)  # last write wins within the batch
        sst = self._upload_sst(sorted(dedup.items()))
        with self._commit_cv:
            self.versions.commit(epoch, adds={0: [sst]}, removes={})
            self._protected.discard(sst.key)
            self._update_gauges()
            self._commit_cv.notify_all()
        return sst

    def delete_batch(self, keys: list[bytes], epoch: int = 0) -> None:
        self.write_batch([(k, TOMBSTONE) for k in keys], epoch)

    # -- externally-uploaded SSTs (cluster MV exports) -------------------
    def alloc_external_sst_key(self) -> str:
        """Allocate (and vacuum-protect) an SST key for an EXTERNAL
        uploader — a cluster compute worker exporting MV rows over the
        shared store.  The single allocator keeps keys collision-free
        across processes; the key stays protected until its delta
        commits (``commit_external``) or the allocation is abandoned
        (``release_external_sst_key``)."""
        return self._alloc_sst_key()

    def release_external_sst_key(self, key: str) -> None:
        """Abandon an allocated-but-never-committed external key (its
        uploader died or its round was re-sealed elsewhere); whatever
        landed under it becomes a vacuumable orphan."""
        with self._lock:
            self._protected.discard(key)

    def commit_external(self, epoch: int,
                        ssts: list[SstInfo],
                        policies: "dict | None" = None) -> None:
        """Commit externally-uploaded SSTs plus the cluster-epoch stamp
        as ONE version delta.  ``ssts`` list order is newest-first
        within the new L0 prefix (the delta prepends in order).  With
        an empty list this is exactly the old cluster-epoch commit: an
        empty delta advancing ``max_committed_epoch``.  ``policies``
        (table → expiry-policy doc) folds pushdown-plane horizon
        updates into the SAME delta, so the policy is never ahead of
        or behind the data it governs."""
        with self._commit_cv:
            adds = {0: list(ssts)} if ssts else {}
            self.versions.commit(epoch, adds=adds, removes={},
                                 set_policies=policies)
            for s in ssts:
                self._protected.discard(s.key)
            self._update_gauges()
            self._commit_cv.notify_all()

    # -- pushdown plane: per-table expiry policies -----------------------
    def set_policy(self, table: str, doc: "dict | None") -> None:
        """Commit one table's expiry-policy doc (None removes it) as a
        version delta — the policy rides the manifest, so compactor
        restarts and offline ``ctl storage compact`` replay it."""
        with self._commit_cv:
            self.versions.commit(
                self.versions.max_committed_epoch,
                adds={}, removes={}, set_policies={table: doc},
            )
            self._update_gauges()
            self._commit_cv.notify_all()

    def policy_set(self) -> PolicySet:
        """The CURRENT version's compaction filter."""
        return PolicySet.from_docs(self.versions.current.policy_docs())

    # -- reads ----------------------------------------------------------
    def pin(self) -> PinnedVersion:
        pin_id, version = self.versions.pin()
        self._update_gauges()
        return PinnedVersion(self, pin_id, version)

    def get(self, key: bytes) -> bytes | None:
        with self.pin() as pv:
            return pv.get(key)

    def scan(self, lo: bytes = b"", hi: bytes | None = None):
        pv = self.pin()
        try:
            yield from pv.scan(lo, hi)
        finally:
            pv.release()

    # -- write stall / backpressure -------------------------------------
    def l0_depth(self) -> int:
        return self.versions.current.l0_depth()

    def stalled(self) -> bool:
        """The Hummock write-limit condition: L0 deeper than the stall
        threshold means compaction is behind; ingest must wait."""
        return self.l0_depth() >= self.stall_l0

    def wait_below_stall(self, timeout: float = 5.0) -> float:
        """Block until L0 drops below the stall threshold (or timeout);
        returns seconds stalled.  The barrier loop's stall hook."""
        if not self.stalled():
            return 0.0
        t0 = time.monotonic()
        deadline = t0 + timeout
        with self._commit_cv:
            while self.stalled():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._commit_cv.wait(remaining)
        waited = time.monotonic() - t0
        if self.metrics is not None and waited > 0:
            self.metrics.inc("storage_write_stall_seconds_total",
                             waited)
        return waited

    # -- compaction (executed by CompactorService) ----------------------
    def pending_compaction_level(self) -> int | None:
        """The deterministic policy over the CURRENT version, ignoring
        levels already owned by in-flight tasks."""
        v = self.versions.current
        levels = v.levels
        if v.l0_depth() >= self.l0_trigger \
                and not self._busy_levels & {0, 1}:
            return 0
        for i in range(1, len(levels)):
            budget = self.base_bytes * self.ratio ** (i - 1)
            if levels[i] and v.level_bytes(i) > budget \
                    and not self._busy_levels & {i, i + 1}:
                return i
        return None

    def pick_compaction(self) -> CompactionTask | None:
        """Claim one task (locks its level pair until commit/abort)."""
        with self._lock:
            i = self.pending_compaction_level()
            if i is None:
                return None
            v = self.versions.current
            levels = v.levels
            inputs = list(levels[i])
            if i + 1 < len(levels):
                inputs += list(levels[i + 1])
            # tombstone drop is legal ONLY into the bottommost
            # non-empty level (see sst.output_is_bottommost); decided
            # under the lock and stable for the task lifetime
            drop = output_is_bottommost(levels, i + 1)
            # the expiry filter obeys the SAME legality rule: dropping
            # an expired row/tombstone above deeper data would
            # resurrect whatever older value that level still holds
            policies = PolicySet.from_docs(v.policy_docs()) \
                if drop and v.policies else None
            task = CompactionTask(
                task_id=self._next_task, in_level=i, out_level=i + 1,
                inputs=inputs, drop_tombstones=drop,
                epoch=v.max_committed_epoch, policies=policies,
            )
            self._next_task += 1
            self._busy_levels |= {i, i + 1}
            return task

    def execute_compaction(self, task: CompactionTask) -> None:
        """The merge itself — runs OFF the write path (compactor
        thread), reading input SSTs and uploading the merged run.

        With a policy set attached (bottommost output only), this IS
        the compaction filter: inputs whose whole key range is below
        their table's horizon are elided outright — no block is read;
        the manifest's recorded ``n_records`` accounts their rows and
        the index-only reader their blocks — and the surviving merge
        drops every expired key (live rows and whole tombstone runs
        alike) as it streams past."""
        inputs = task.inputs
        if task.policies:
            dead, inputs = partition_elidable(task.inputs,
                                              task.policies)
            for s in dead:
                task.ssts_elided += 1
                task.rows_elided += s.n_records
                # index-only open: counts blocks without block I/O
                task.blocks_skipped += len(
                    self._reader(s.key).index["blocks"])
        readers = [self._reader(s.key) for s in inputs]
        pairs: list[tuple[bytes, bytes]] = []
        for k, v in merge_scan(readers,
                               keep_tombstones=not task.drop_tombstones):
            if task.policies is not None and task.policies.expired(k):
                task.rows_elided += 1
                continue
            pairs.append((k, v))
            task.in_bytes += len(k) + len(v)
        if pairs:
            task.outputs = [self._upload_sst(pairs)]

    def commit_compaction(self, task: CompactionTask) -> None:
        """Commit the task as one version delta; input SSTs leave the
        version (vacuum reclaims them once unpinned)."""
        with self._commit_cv:
            in_keys = [s.key for s in task.inputs]
            self.versions.commit(
                task.epoch,
                adds={task.out_level: task.outputs},
                removes={task.in_level: in_keys,
                         task.out_level: in_keys},
            )
            for s in task.outputs:
                self._protected.discard(s.key)
            self._busy_levels -= {task.in_level, task.out_level}
            if self.metrics is not None:
                self.metrics.inc("storage_compaction_tasks_total",
                                 level=str(task.in_level))
                self.metrics.inc("storage_compaction_bytes_total",
                                 task.in_bytes)
                if task.rows_elided:
                    self.metrics.inc("pushdown_rows_elided_total",
                                     task.rows_elided,
                                     where="compactor")
                if task.blocks_skipped:
                    self.metrics.inc("pushdown_blocks_skipped_total",
                                     task.blocks_skipped)
            #: cumulative filter counters (the offline/ctl surface —
            #: a bare HummockStorage has no metrics registry)
            self.pushdown_rows_elided += task.rows_elided
            self.pushdown_blocks_skipped += task.blocks_skipped
            self.pushdown_ssts_elided += task.ssts_elided
            self._update_gauges()
            self._commit_cv.notify_all()

    def abort_compaction(self, task: CompactionTask) -> None:
        """Release the task's level locks; any uploaded output stays
        as an orphan for vacuum (the crash path does the same without
        this courtesy call)."""
        with self._commit_cv:
            for s in task.outputs:
                self._protected.discard(s.key)
            self._busy_levels -= {task.in_level, task.out_level}
            self._commit_cv.notify_all()

    def compact_once(self) -> bool:
        """Pick + execute + commit one task synchronously (the ctl
        'trigger compaction' surface and the service's inner step).

        Compaction reads every input block, so it is a DETECTION POINT
        for cold corruption: an ``IntegrityError`` aborts the task,
        quarantines the corrupt input and hands it to
        ``on_corruption`` (the meta wires repair) instead of wedging
        the compactor on a poisoned level."""
        from risingwave_tpu.storage.integrity import (
            IntegrityError,
            record_integrity_error,
        )

        task = self.pick_compaction()
        if task is None:
            return False
        try:
            self.execute_compaction(task)
        except IntegrityError as e:
            self.abort_compaction(task)
            record_integrity_error(self.metrics, e)
            key = e.key or (task.inputs[0].key if task.inputs else "")
            self.quarantine_sst(key, reason=str(e), by="compactor")
            if self.on_corruption is not None:
                self.on_corruption("sst", key, {"error": str(e)})
            return False
        except BaseException:
            self.abort_compaction(task)
            raise
        self.commit_compaction(task)
        return True

    # -- integrity: quarantine + corrupt-object removal ------------------
    def quarantine_sst(self, key: str, reason: str,
                       by: str = "storage") -> bool:
        """Durable quarantine note for one corrupt SST (idempotent);
        returns True on first detection."""
        from risingwave_tpu.storage.integrity import quarantine

        return quarantine(self.store, key, reason, by=by,
                          metrics=self.metrics)

    def replace_sst(self, bad_key: str, ssts: "list[SstInfo]") -> bool:
        """ONE version delta: drop a corrupt SST from its level and
        prepend fresh repair exports at L0 — atomic, so no read ever
        sees the rows missing between removal and re-export."""
        with self._commit_cv:
            v = self.versions.current
            lv_hit = next((lv for lv, level in enumerate(v.levels)
                           if any(s.key == bad_key for s in level)),
                          None)
            if lv_hit is None and not ssts:
                return False
            adds = {0: list(ssts)} if ssts else {}
            removes = {lv_hit: [bad_key]} if lv_hit is not None else {}
            self.versions.commit(v.max_committed_epoch,
                                 adds=adds, removes=removes)
            for s in ssts:
                self._protected.discard(s.key)
            r = self._readers.pop(bad_key, None)
            if r is not None:
                r.close()
            self._update_gauges()
            self._commit_cv.notify_all()
            return lv_hit is not None

    def remove_sst(self, key: str) -> bool:
        """Commit one delta removing a (corrupt, quarantined) SST from
        whichever level holds it — the first half of repair; the
        second half is the owner re-exporting the rows it carried.
        Returns whether the key was in the current version."""
        with self._commit_cv:
            v = self.versions.current
            for lv, level in enumerate(v.levels):
                if any(s.key == key for s in level):
                    self.versions.commit(v.max_committed_epoch,
                                         adds={}, removes={lv: [key]})
                    r = self._readers.pop(key, None)
                    if r is not None:
                        r.close()
                    self._update_gauges()
                    self._commit_cv.notify_all()
                    return True
        return False

    # -- vacuum / GC ----------------------------------------------------
    def vacuum(self, extra_refs: "set[str] | frozenset[str]" = frozenset(),
               ) -> int:
        """Delete SST objects unreferenced by the current version, any
        pinned version, in-flight uploads, or ``extra_refs`` (retained
        checkpoint exports).  Returns the number of objects deleted
        (the meta vacuum's orphan-object GC)."""
        with self._lock:
            keep = self.versions.referenced_keys()
            keep |= self._protected
            keep |= set(extra_refs)
            deleted = 0
            for key in self.store.list(SST_PREFIX):
                if key in keep:
                    continue
                r = self._readers.pop(key, None)
                if r is not None:
                    r.close()
                self.store.delete(key)
                deleted += 1
            if self.metrics is not None and deleted:
                self.metrics.inc("storage_gc_objects_total", deleted)
            return deleted

    # -- introspection ---------------------------------------------------
    def stats(self) -> dict:
        """The ctl 'storage version' surface."""
        v = self.versions.current
        return {
            "version_id": v.vid,
            "max_committed_epoch": v.max_committed_epoch,
            "l0_runs": v.l0_depth(),
            "levels": [
                {"level": i, "files": len(lv),
                 "bytes": sum(s.size for s in lv)}
                for i, lv in enumerate(v.levels)
            ],
            "pinned_versions": self.versions.pinned_count(),
            "stalled": self.stalled(),
            "stall_l0": self.stall_l0,
            "objects": len(self.store.list(SST_PREFIX)),
            "pushdown": {
                "policies": v.policy_docs(),
                "rows_elided": self.pushdown_rows_elided,
                "blocks_skipped": self.pushdown_blocks_skipped,
                "ssts_elided": self.pushdown_ssts_elided,
            },
        }

    def close(self) -> None:
        with self._lock:
            for r in self._readers.values():
                r.close()
            self._readers.clear()
