"""Background compactor service: merges off the write path.

Reference counterpart: the compactor node role
(src/storage/src/hummock/compactor/compactor_runner.rs:70,
src/storage/compactor) — RisingWave's fourth binary, which this repo
lacked: the seed ``LsmTree`` merged inline on the ingest path.  Here a
daemon thread polls ``HummockStorage.pick_compaction`` (level budgets
→ tasks), executes the k-way merge, and commits version deltas; the
ingest path's only coupling is the L0-depth write stall.  Decoupling
compaction from ingest is the latency-tail discipline of Hazelcast
Jet's 99.99th-percentile argument and Taurus' near-data storage
service split (PAPERS.md).
"""

from __future__ import annotations

import threading
import time

from risingwave_tpu.common.trace import GLOBAL_TRACE


class CompactorService:
    """Thread-based compactor over one ``HummockStorage``.

    ``start()``/``stop()`` bound the thread's life; ``run_once()`` is
    the synchronous single-task step (shared with ctl and tests).  An
    optional ``vacuum_interval_tasks`` runs the orphan GC pass every N
    committed tasks — the embedded vacuum mode; deployments can also
    call ``storage.vacuum()`` on their own cadence (ctl ``storage
    gc``).
    """

    def __init__(self, storage, poll_interval_s: float = 0.01,
                 metrics=None, vacuum_interval_tasks: int = 0):
        self.storage = storage
        self.poll_interval_s = poll_interval_s
        self.metrics = metrics if metrics is not None \
            else storage.metrics
        self.vacuum_interval_tasks = vacuum_interval_tasks
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.tasks_run = 0
        self.errors = 0
        #: last exception seen by the loop (surfaced to ctl/tests)
        self.last_error: BaseException | None = None

    # -- lifecycle ------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "CompactorService":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hummock-compactor", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    # -- work -----------------------------------------------------------
    def run_once(self) -> bool:
        """Pick + execute + commit one compaction task; False when the
        policy is at quiescence."""
        t0 = time.perf_counter()
        with GLOBAL_TRACE.sampled_span("compact_cycle") as tsp:
            did = self.storage.compact_once()
            tsp.set(did=bool(did))
        if did:
            self.tasks_run += 1
            if self.metrics is not None:
                self.metrics.observe("storage_compact_seconds",
                                     time.perf_counter() - t0)
            if self.vacuum_interval_tasks \
                    and self.tasks_run % self.vacuum_interval_tasks == 0:
                self.storage.vacuum()
        return did

    def drain(self, max_tasks: int = 1_000_000) -> int:
        """Run tasks to quiescence on the CALLER's thread (tests,
        shutdown flush)."""
        n = 0
        while n < max_tasks and self.run_once():
            n += 1
        return n

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if not self.run_once():
                    # idle: nothing due — sleep one poll interval
                    # (woken early only by the next due poll; ingest
                    # commits are frequent enough at stall depths)
                    self._stop.wait(self.poll_interval_s)
            except BaseException as e:  # keep the service alive
                self.errors += 1
                self.last_error = e
                if self.metrics is not None:
                    self.metrics.inc("storage_compactor_errors_total")
                self._stop.wait(self.poll_interval_s)
