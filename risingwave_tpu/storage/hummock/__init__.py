"""Hummock-lite storage service (the shared-LSM-on-object-store analog).

Reference counterpart: ``src/storage/src/hummock`` + the meta-side
Hummock manager (SURVEY.md §2.5/§3.5) — RisingWave's fourth node role.
Four pieces, mirroring the reference's split:

- ``object_store``  — the S3 seam: ``LocalFsObjectStore`` /
  ``InMemObjectStore`` with deterministic fault injection (the madsim
  sim-object-store analog, src/object_store/src/object/sim/)
- ``version``       — epoch-stamped ``HummockVersion`` + append-only
  version deltas with pin/unpin for in-flight serving reads
  (commit_epoch.rs:73, time_travel_version_cache.rs:65)
- ``store``         — ``HummockStorage``: merge-free write path
  (seal batch → upload SST → commit delta), pinned snapshot reads,
  compaction task picking, vacuum GC of unreferenced objects
- ``compactor``     — ``CompactorService``: the background thread that
  takes compaction off the ingest path (compactor_runner.rs:70) and
  whose L0-depth write stall backpressures the barrier loop
- ``scrubber``      — ``ScrubberService``: paced off-barrier checksum
  verification of every pinned-version SST and retained checkpoint
  lineage, feeding the quarantine + self-healing repair pipeline
  (storage/integrity.py)
"""

from risingwave_tpu.storage.hummock.compactor import CompactorService
from risingwave_tpu.storage.hummock.scrubber import ScrubberService
from risingwave_tpu.storage.hummock.object_store import (
    InMemObjectStore,
    LocalFsObjectStore,
    ObjectError,
    ObjectStore,
    StoreFaults,
)
from risingwave_tpu.storage.hummock.store import (
    CompactionTask,
    HummockStorage,
    PinnedVersion,
)
from risingwave_tpu.storage.hummock.version import (
    HummockVersion,
    SstInfo,
    VersionDelta,
    VersionManager,
)

__all__ = [
    "CompactionTask",
    "CompactorService",
    "HummockStorage",
    "HummockVersion",
    "InMemObjectStore",
    "LocalFsObjectStore",
    "ObjectError",
    "ObjectStore",
    "PinnedVersion",
    "ScrubberService",
    "SstInfo",
    "StoreFaults",
    "VersionDelta",
    "VersionManager",
]
