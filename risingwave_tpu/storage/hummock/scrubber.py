"""Background scrubber: paced, off-barrier checksum verification.

Reference counterpart: background data scrubbing as practiced by every
production object/LSM store (and RisingWave's stance that a bad block
is an operational event, not a crash): a low-priority walker re-reads
durable bytes end-to-end so *cold* corruption — bits that rotted in
objects nobody reads on the hot path — is found and repaired long
before a recovery or a serving read trips over it.

``ScrubberService`` is meta-owned, a sibling of the
``CompactorService``: a daemon thread that, every ``interval_s``,
walks

- every SST reachable from the current or any pinned version (footer,
  index crc, every data block's crc32c trailer — the whole object),
- every checkpoint lineage the checkpoint manifest retains (object
  bytes vs the manifest-recorded crc32c, jax-free),

paced by ``pace_s`` sleeps between objects so it never competes with
the barrier path.  Progress is durable: ``scrub/CURSOR.json`` records
the last verified object, so ``scrub_cursor_age_s`` exposes how stale
the scrub coverage is.  Detections raise nothing here — each corrupt
object is handed to ``on_corruption(kind, key, context)`` (the meta
wires quarantine + repair) and counted on the scrape surface:

- ``scrub_objects_verified_total`` / ``scrub_blocks_verified_total``
- ``scrub_corruptions_total{kind=...}``
- ``scrub_cycles_total``, ``scrub_cursor_age_s``
"""

from __future__ import annotations

import json
import threading
import time

from risingwave_tpu.common.trace import GLOBAL_TRACE
from risingwave_tpu.storage.integrity import (
    IntegrityError,
    verify_checkpoint_store,
    verify_sst_object,
)

CURSOR_KEY = "scrub/CURSOR.json"


class ScrubberService:
    def __init__(self, storage, ckpt_object_store=None, metrics=None,
                 interval_s: float = 30.0, pace_s: float = 0.005,
                 on_corruption=None):
        self.storage = storage
        #: plain ObjectStore over the checkpoint root (jax-free: the
        #: scrub verifies bytes vs manifest crcs, never decodes state)
        self.ckpt_store = ckpt_object_store
        self.metrics = metrics if metrics is not None \
            else storage.metrics
        self.interval_s = interval_s
        self.pace_s = pace_s
        self.on_corruption = on_corruption
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.cycles = 0
        self.objects_verified = 0
        self.blocks_verified = 0
        self.corruptions = 0
        self.last_error: BaseException | None = None
        self._cursor_at = time.monotonic()

    # -- lifecycle ------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "ScrubberService":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hummock-scrubber", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except BaseException as e:  # keep the service alive
                self.last_error = e
                if self.metrics is not None:
                    self.metrics.inc("scrub_errors_total")

    # -- one full verification pass -------------------------------------
    def _emit(self, kind: str, key: str, err: IntegrityError,
              **context) -> None:
        self.corruptions += 1
        if self.metrics is not None:
            self.metrics.inc("scrub_corruptions_total", kind=kind)
        if self.on_corruption is not None:
            try:
                self.on_corruption(kind, key, {"error": str(err),
                                               **context})
            except Exception as e:  # noqa: BLE001 — repair must not
                self.last_error = e  # kill the scrub walk

    def _advance_cursor(self, key: str) -> None:
        self._cursor_at = time.monotonic()
        try:
            self.storage.store.put(CURSOR_KEY, json.dumps({
                "key": key, "cycle": self.cycles,
                "objects_verified": self.objects_verified,
                "at": time.time(),
            }).encode())
        except Exception:  # noqa: BLE001 — cursor is observability
            pass
        self._export_gauges()

    def _export_gauges(self) -> None:
        if self.metrics is None:
            return
        self.metrics.set_gauge("scrub_objects_verified_total",
                               self.objects_verified)
        self.metrics.set_gauge("scrub_blocks_verified_total",
                               self.blocks_verified)
        self.metrics.set_gauge("scrub_cycles_total", self.cycles)
        self.metrics.set_gauge(
            "scrub_cursor_age_s", time.monotonic() - self._cursor_at)

    def run_once(self) -> dict:
        """One full scrub cycle (also the ``ctl cluster scrub``
        surface).  Returns the cycle report."""
        cycle_span = GLOBAL_TRACE.sampled_span("scrub_cycle")
        cycle_span.__enter__()
        report = {"ssts_verified": 0, "blocks_verified": 0,
                  "checkpoints_verified": 0, "corrupt": []}
        try:
            # SSTs reachable from the current + every pinned version:
            # the exact set a serving read or a recovery could touch
            versions = self.storage.versions
            keys = sorted(versions.referenced_keys())
            for key in keys:
                if self._stop.is_set():
                    break
                try:
                    n = verify_sst_object(self.storage.store, key)
                    self.objects_verified += 1
                    self.blocks_verified += n
                    report["ssts_verified"] += 1
                    report["blocks_verified"] += n
                except IntegrityError as e:
                    report["corrupt"].append(("sst", key))
                    self._emit("sst", key, e)
                except Exception:  # noqa: BLE001 — vacuumed under us
                    pass
                self._advance_cursor(key)
                if self.pace_s:
                    self._stop.wait(self.pace_s)
            if self.ckpt_store is not None:
                ck = verify_checkpoint_store(self.ckpt_store)
                self.objects_verified += ck["verified"]
                report["checkpoints_verified"] = ck["verified"]
                for job, epoch, key in ck["corrupt"]:
                    report["corrupt"].append(("checkpoint", key))
                    self._emit(
                        "checkpoint", key,
                        IntegrityError(
                            f"{key}: checkpoint scrub mismatch",
                            key=key),
                        job=job, epoch=epoch,
                    )
                self._advance_cursor("checkpoints")
            self.cycles += 1
            cycle_span.set(ssts=report["ssts_verified"],
                           corrupt=len(report["corrupt"]))
        finally:
            cycle_span.__exit__(None, None, None)
        self._export_gauges()
        return report
