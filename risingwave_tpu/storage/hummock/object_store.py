"""Object-store seam under the storage service (the S3 boundary).

Reference counterpart: ``src/object_store`` — one trait
(``ObjectStore``: upload/read/delete/list) with S3/GCS/filesystem/
in-memory implementations, plus the deterministic *simulated* store
madsim uses to kill uploads mid-flight
(``src/object_store/src/object/sim/``).  Everything above this seam
(SSTs, version manifest, checkpoints) speaks keys and bytes only, so
chaos tests swap the backend without touching the LSM.

Fault injection is **deterministic** (counter-addressed, no RNG): a
``StoreFaults`` rule fires on the Nth matching operation, either
*before* the object is stored (upload lost with the process) or
*after* (the object is durable but the caller dies before committing
its manifest — the orphan-SST case vacuum must reap).
"""

from __future__ import annotations

import io
import os
import threading
import time
from dataclasses import dataclass, field


class ObjectError(IOError):
    """An object-store operation failed (injected or real)."""


@dataclass
class _FaultRule:
    op: str               # "put" | "get" | "delete"
    substr: str           # only keys containing this match
    after: int            # skip this many matching ops first
    mode: str             # "before" (op lost) | "after" (op durable)
    times: int            # how many firings before the rule retires
    hits: int = 0
    seen: int = 0


@dataclass
class StoreFaults:
    """Injectable latency + error schedule shared by both stores."""

    put_latency_s: float = 0.0
    get_latency_s: float = 0.0
    rules: list[_FaultRule] = field(default_factory=list)
    #: totals for test assertions
    injected_errors: int = 0
    injected_corruptions: int = 0
    #: deterministic-corruption seed (splitmix64 bit choice)
    seed: int = 0

    def fail(self, op: str, substr: str = "", after: int = 0,
             mode: str = "before", times: int = 1) -> None:
        """Arm one deterministic failure: the ``after``-th matching op
        (0-based) raises ``ObjectError``; with ``mode='after'`` the
        store mutation still lands first (crash-after-upload); with
        ``mode='bit_flip'``/``'truncate'`` the op succeeds but its
        PAYLOAD is deterministically damaged (the corruption probe the
        integrity layer must catch)."""
        from risingwave_tpu.common.faults import CORRUPT_MODES

        assert op in ("put", "get", "delete") \
            and mode in ("before", "after") + CORRUPT_MODES
        assert not (mode in CORRUPT_MODES and op == "delete")
        self.rules.append(_FaultRule(op, substr, after, mode, times))

    # -- hooks called by the stores -------------------------------------
    def _match(self, op: str, key: str) -> "_FaultRule | None":
        for r in self.rules:
            if r.op != op or r.substr not in key or r.hits >= r.times:
                continue
            r.seen += 1
            if r.seen > r.after:
                r.hits += 1
                return r
        return None

    def before(self, op: str, key: str) -> "_FaultRule | None":
        lat = self.put_latency_s if op == "put" else self.get_latency_s
        if lat:
            time.sleep(lat)
        r = self._match(op, key)
        if r is not None and r.mode == "before":
            self.injected_errors += 1
            raise ObjectError(f"injected {op} fault (lost): {key}")
        return r

    def after(self, rule: "_FaultRule | None", op: str,
              key: str) -> None:
        if rule is not None and rule.mode == "after":
            self.injected_errors += 1
            raise ObjectError(f"injected {op} fault (durable): {key}")

    def corrupt(self, rule: "_FaultRule | None", key: str,
                data: bytes) -> bytes:
        from risingwave_tpu.common.faults import (
            CORRUPT_MODES,
            corrupt_payload,
        )

        if rule is None or rule.mode not in CORRUPT_MODES:
            return data
        self.injected_corruptions += 1
        return corrupt_payload(data, rule.mode, self.seed, rule.hits)


class ObjectStore:
    """Key → immutable bytes.  ``put`` is atomic (no torn reads)."""

    faults: StoreFaults | None = None

    # -- interface ------------------------------------------------------
    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def open(self, key: str):
        """Seekable binary reader (SSTs read footer-first)."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    def size(self, key: str) -> int:
        raise NotImplementedError

    # -- shared fault plumbing ------------------------------------------
    # Two layers consult here: the store's OWN StoreFaults (armed by
    # unit tests against one store instance) and the process-global
    # FaultFabric (common/faults.py — armed by chaos schedules, also
    # via the RWT_FAULTS env in spawned workers).  Either may raise.
    def _pre(self, op: str, key: str):
        local = self.faults.before(op, key) if self.faults else None
        from risingwave_tpu.common.faults import get_fabric

        fabric = get_fabric()
        global_rule = None
        if fabric is not None:
            global_rule = fabric.store_before(op, key)
        return local, global_rule

    def _post(self, rule, op: str, key: str) -> None:
        local, global_rule = rule if isinstance(rule, tuple) \
            else (rule, None)
        if self.faults:
            self.faults.after(local, op, key)
        if global_rule is not None:
            from risingwave_tpu.common.faults import get_fabric

            fabric = get_fabric()
            if fabric is not None:
                fabric.store_after(global_rule, op, key)

    def _xform(self, rule, key: str, data: bytes) -> bytes:
        """Apply matched corrupt-mode rules (local + global fabric) to
        one payload — put corruption lands DURABLY damaged bytes, get
        corruption models a bad read of an intact object."""
        local, global_rule = rule if isinstance(rule, tuple) \
            else (rule, None)
        if self.faults:
            data = self.faults.corrupt(local, key, data)
        if global_rule is not None:
            from risingwave_tpu.common.faults import get_fabric

            fabric = get_fabric()
            if fabric is not None:
                data = fabric.store_corrupt(global_rule, key, data)
        return data


class InMemObjectStore(ObjectStore):
    """Dict-backed store for tests/chaos (the sim object store)."""

    def __init__(self, faults: StoreFaults | None = None):
        self._d: dict[str, bytes] = {}
        self._lock = threading.Lock()
        self.faults = faults

    def put(self, key: str, data: bytes) -> None:
        rule = self._pre("put", key)
        with self._lock:
            self._d[key] = bytes(self._xform(rule, key, data))
        self._post(rule, "put", key)

    def get(self, key: str) -> bytes:
        rule = self._pre("get", key)
        with self._lock:
            if key not in self._d:
                raise ObjectError(f"no such object: {key}")
            data = self._d[key]
        self._post(rule, "get", key)
        return self._xform(rule, key, data)

    def open(self, key: str):
        return io.BytesIO(self.get(key))

    def delete(self, key: str) -> None:
        rule = self._pre("delete", key)
        with self._lock:
            self._d.pop(key, None)
        self._post(rule, "delete", key)

    def exists(self, key: str) -> bool:
        with self._lock:
            return key in self._d

    def list(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._d if k.startswith(prefix))

    def size(self, key: str) -> int:
        with self._lock:
            if key not in self._d:
                raise ObjectError(f"no such object: {key}")
            return len(self._d[key])


class LocalFsObjectStore(ObjectStore):
    """Filesystem-backed store; atomic put via tmp + rename."""

    def __init__(self, root: str, faults: StoreFaults | None = None):
        self.root = root
        self.faults = faults
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        assert ".." not in key.split("/"), key
        return os.path.join(self.root, key)

    def put(self, key: str, data: bytes) -> None:
        rule = self._pre("put", key)
        data = self._xform(rule, key, data)
        path = self._path(key)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        self._post(rule, "put", key)

    def get(self, key: str) -> bytes:
        rule = self._pre("get", key)
        try:
            with open(self._path(key), "rb") as f:
                data = f.read()
        except FileNotFoundError as e:
            raise ObjectError(f"no such object: {key}") from e
        self._post(rule, "get", key)
        return self._xform(rule, key, data)

    def open(self, key: str):
        rule = self._pre("get", key)
        try:
            f = open(self._path(key), "rb")
        except FileNotFoundError as e:
            raise ObjectError(f"no such object: {key}") from e
        self._post(rule, "get", key)
        local, global_rule = rule
        if (local is not None and local.mode in ("bit_flip", "truncate")) \
                or (global_rule is not None
                    and global_rule.mode in ("bit_flip", "truncate")):
            # a corrupted READ of a seekable object: materialize the
            # damaged bytes once (footer-first SST reads then see them)
            data = f.read()
            f.close()
            return io.BytesIO(self._xform(rule, key, data))
        return f

    def delete(self, key: str) -> None:
        rule = self._pre("delete", key)
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass
        self._post(rule, "delete", key)

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def list(self, prefix: str = "") -> list[str]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            rel = "" if rel == "." else rel + "/"
            for name in files:
                if name.endswith(".tmp"):
                    continue  # torn put, never visible
                key = rel + name
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def size(self, key: str) -> int:
        try:
            return os.path.getsize(self._path(key))
        except FileNotFoundError as e:
            raise ObjectError(f"no such object: {key}") from e
