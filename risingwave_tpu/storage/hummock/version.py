"""Versioned manifest: epoch-stamped versions + append-only deltas.

Reference counterpart: the meta-side Hummock manager's version
bookkeeping — ``commit_epoch`` bumps a ``HummockVersion`` by applying a
``HummockVersionDelta`` (src/meta/src/hummock/manager/commit_epoch.rs:
73), compute nodes pin versions for in-flight reads, and time travel
replays archived deltas (time_travel_version_cache.rs:65).

Shape here: every mutation of the SST set (ingest upload, compaction
commit) appends ONE delta object ``version/delta_<vid>.json`` to the
object store and applies it in memory.  Reopen = latest base snapshot
+ later deltas replayed in vid order — a crash between an SST upload
and its delta commit leaves an *orphan object* that no version
references (vacuum reaps it), never a corrupt version.  Pins hold a
full immutable ``HummockVersion`` so serving reads keep a consistent
SST set while the compactor rewrites levels underneath them.

Integrity: the log is a **hash chain**.  Every delta/base object is
wrapped as ``{"prev": <predecessor link>, "crc": crc32c(prev || body),
"delta"/"version": body}`` — each entry commits the hash of its
predecessor, a base snapshot re-anchors the chain, and replay
(``VersionManager._replay`` on meta recovery, and the serving tier's
``ManifestFollower``) verifies every link with
``verify_chain_doc``.  A flipped bit anywhere in the log raises the
typed ``ManifestCorruption`` (storage/integrity.py) naming the exact
object — an operational event for the scrubber/ctl surface, never a
silently wrong SST set.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field

from risingwave_tpu.storage import codec
from risingwave_tpu.storage.integrity import ManifestCorruption

_DELTA_FMT = "version/delta_{:012d}.json"
_BASE_FMT = "version/base_{:012d}.json"
_DELTA_PREFIX = "version/delta_"
_BASE_PREFIX = "version/base_"


@dataclass(frozen=True)
class SstInfo:
    """Immutable SST descriptor carried by versions and deltas."""

    key: str            # object-store key
    first_key: bytes
    last_key: bytes
    n_records: int
    size: int

    def to_json(self) -> dict:
        return {
            "key": self.key,
            "first_key": self.first_key.hex(),
            "last_key": self.last_key.hex(),
            "n_records": self.n_records,
            "size": self.size,
        }

    @staticmethod
    def from_json(d: dict) -> "SstInfo":
        return SstInfo(
            key=d["key"],
            first_key=bytes.fromhex(d["first_key"]),
            last_key=bytes.fromhex(d["last_key"]),
            n_records=d["n_records"],
            size=d["size"],
        )


@dataclass(frozen=True)
class HummockVersion:
    """One immutable version of the LSM shape.

    ``levels[0]`` is newest-first overlapping runs; deeper levels hold
    at most one sorted run each (mirroring ``LsmTree``).
    """

    vid: int
    max_committed_epoch: int
    levels: tuple[tuple[SstInfo, ...], ...]
    #: pushdown plane: per-table expiry policy docs (table → doc, see
    #: storage/pushdown.ExpiryPolicy).  Riding the manifest makes the
    #: compaction filter a pure function of the version: the owning
    #: service, a restarted compactor, and the offline ``ctl storage
    #: compact`` path all evaluate the same horizons.
    policies: "tuple[tuple[str, str], ...]" = ()

    def policy_docs(self) -> dict:
        """Decode the policy map (table → doc dict)."""
        return {t: json.loads(d) for t, d in self.policies}

    def all_keys(self) -> set[str]:
        return {s.key for lv in self.levels for s in lv}

    def l0_depth(self) -> int:
        return len(self.levels[0]) if self.levels else 0

    def level_bytes(self, i: int) -> int:
        return sum(s.size for s in self.levels[i])

    def file_count(self) -> int:
        return sum(len(lv) for lv in self.levels)

    def to_json(self) -> dict:
        out = {
            "vid": self.vid,
            "max_committed_epoch": self.max_committed_epoch,
            "levels": [[s.to_json() for s in lv] for lv in self.levels],
        }
        if self.policies:
            # omitted when empty: legacy logs replay byte-identically
            out["policies"] = {t: json.loads(d)
                               for t, d in self.policies}
        return out

    @staticmethod
    def from_json(d: dict) -> "HummockVersion":
        return HummockVersion(
            vid=d["vid"],
            max_committed_epoch=d["max_committed_epoch"],
            levels=tuple(
                tuple(SstInfo.from_json(s) for s in lv)
                for lv in d["levels"]
            ),
            policies=tuple(sorted(
                (t, json.dumps(doc, sort_keys=True))
                for t, doc in d.get("policies", {}).items()
            )),
        )

    @staticmethod
    def empty() -> "HummockVersion":
        return HummockVersion(vid=0, max_committed_epoch=0,
                              levels=((),))


@dataclass
class VersionDelta:
    """One append-only version-log entry: SST add/remove per level.

    ``adds[level]`` lists new SSTs; for L0 they PREPEND (newest first),
    deeper levels hold the single new run.  ``removes[level]`` lists
    object keys leaving that level (compaction inputs).
    """

    vid: int
    epoch: int
    adds: dict[int, list[SstInfo]] = field(default_factory=dict)
    removes: dict[int, list[str]] = field(default_factory=dict)
    #: pushdown plane: policy-doc updates (table → doc, or None to
    #: remove) folded into ``HummockVersion.policies`` on apply
    set_policies: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        out = {
            "vid": self.vid,
            "epoch": self.epoch,
            "adds": {str(lv): [s.to_json() for s in ss]
                     for lv, ss in self.adds.items()},
            "removes": {str(lv): ks for lv, ks in self.removes.items()},
        }
        if self.set_policies:
            out["set_policies"] = self.set_policies
        return out

    @staticmethod
    def from_json(d: dict) -> "VersionDelta":
        return VersionDelta(
            vid=d["vid"],
            epoch=d["epoch"],
            adds={int(lv): [SstInfo.from_json(s) for s in ss]
                  for lv, ss in d["adds"].items()},
            removes={int(lv): ks for lv, ks in d["removes"].items()},
            set_policies=d.get("set_policies", {}),
        )


def apply_delta(v: HummockVersion, d: VersionDelta) -> HummockVersion:
    """Pure application of one delta (replay = fold over the log)."""
    n_levels = max(
        [len(v.levels)] + [lv + 1 for lv in d.adds]
        + [lv + 1 for lv in d.removes]
    )
    levels = [list(v.levels[i]) if i < len(v.levels) else []
              for i in range(n_levels)]
    for lv, keys in d.removes.items():
        gone = set(keys)
        levels[lv] = [s for s in levels[lv] if s.key not in gone]
    for lv, ssts in d.adds.items():
        if lv == 0:
            # newest-first: this delta's runs go to the front in order
            levels[0] = list(ssts) + levels[0]
        else:
            levels[lv] = levels[lv] + list(ssts)
    policies = v.policies
    if d.set_policies:
        from risingwave_tpu.storage.pushdown import merge_policy_docs

        merged = merge_policy_docs(v.policy_docs(), d.set_policies)
        policies = tuple(sorted(
            (t, json.dumps(doc, sort_keys=True))
            for t, doc in merged.items()
        ))
    return HummockVersion(
        vid=d.vid,
        max_committed_epoch=max(v.max_committed_epoch, d.epoch),
        levels=tuple(tuple(lv) for lv in levels),
        policies=policies,
    )


def wrap_chain_doc(kind: str, body: dict, prev: int) -> tuple[bytes, int]:
    """Serialize one log entry (``kind`` = "delta" | "version") with
    its chain fields; returns (object bytes, this entry's link value).
    The link is ``crc32c(prev || canonical body)`` — committing the
    predecessor's link makes the log a hash chain."""
    body_bytes = json.dumps(body, sort_keys=True).encode()
    crc = codec.crc32c(("%08x" % (prev & 0xFFFFFFFF)).encode()
                       + body_bytes)
    doc = {"prev": int(prev), "crc": crc, kind: body}
    return json.dumps(doc).encode(), crc


def verify_chain_doc(raw: bytes, kind: str, key: str,
                     prev: "int | None") -> tuple[dict, int]:
    """Decode + verify one log entry: self-crc always, predecessor
    link when ``prev`` is known (None = re-anchoring, e.g. a follower
    landing on a base snapshot).  Returns (body, link).  Legacy bare
    objects (pre-integrity logs) pass through with the raw bytes' crc
    as their link so mixed logs keep chaining."""
    try:
        doc = json.loads(raw)
    except (ValueError, UnicodeDecodeError) as e:
        raise ManifestCorruption(
            f"{key}: undecodable manifest entry ({e!r})", key=key
        ) from e
    if kind not in doc:
        if "vid" not in doc:
            raise ManifestCorruption(
                f"{key}: not a manifest {kind} entry", key=key
            )
        return doc, codec.crc32c(bytes(raw))  # legacy bare entry
    body = doc[kind]
    body_bytes = json.dumps(body, sort_keys=True).encode()
    crc = codec.crc32c(
        ("%08x" % (int(doc.get("prev", 0)) & 0xFFFFFFFF)).encode()
        + body_bytes
    )
    if crc != int(doc.get("crc", -1)):
        raise ManifestCorruption(
            f"{key}: manifest entry checksum mismatch", key=key
        )
    if prev is not None and int(doc.get("prev", 0)) != int(prev):
        raise ManifestCorruption(
            f"{key}: chain break (expected predecessor "
            f"{int(prev):#010x}, recorded "
            f"{int(doc.get('prev', 0)):#010x})",
            key=key,
        )
    return body, crc


class VersionManager:
    """Owns the version log on the object store + the pin table.

    Thread-safe: ingest and the compactor commit deltas concurrently;
    serving reads pin/unpin.  Every ``base_interval`` deltas a full
    base snapshot re-anchors the log and older entries are pruned
    (deltas ≤ the base vid are ignored on replay, so a crash between
    the base write and the prune leaves a replayable log).
    """

    def __init__(self, store, base_interval: int = 64):
        self.store = store
        self.base_interval = base_interval
        self._lock = threading.RLock()
        #: pin_id -> version; pinned versions keep their SSTs reachable
        self._pins: dict[int, HummockVersion] = {}
        self._next_pin = 1
        self._deltas_since_base = 0
        #: hash-chain link of the newest log entry (0 = empty log)
        self._chain = 0
        self.current = self._replay()

    # -- log ------------------------------------------------------------
    def _replay(self) -> HummockVersion:
        """Rebuild from the log, VERIFYING the hash chain link by link
        (the meta-recovery verification leg: a corrupt base or delta
        raises ``ManifestCorruption`` naming the object instead of
        silently applying a damaged SST set)."""
        base_keys = self.store.list(_BASE_PREFIX)
        v = HummockVersion.empty()
        self._chain = 0
        if base_keys:
            key = base_keys[-1]
            body, self._chain = verify_chain_doc(
                self.store.get(key), "version", key, None
            )
            v = HummockVersion.from_json(body)
        n = 0
        for key in self.store.list(_DELTA_PREFIX):
            vid = int(key[len(_DELTA_PREFIX):-len(".json")])
            if vid <= v.vid:
                continue  # pre-base entry not yet pruned
            body, self._chain = verify_chain_doc(
                self.store.get(key), "delta", key, self._chain
            )
            v = apply_delta(v, VersionDelta.from_json(body))
            n += 1
        self._deltas_since_base = n
        return v

    def commit(self, epoch: int, adds: dict[int, list[SstInfo]],
               removes: dict[int, list[str]],
               set_policies: "dict | None" = None) -> HummockVersion:
        """Append one delta (atomic object put) and apply it."""
        with self._lock:
            delta = VersionDelta(
                vid=self.current.vid + 1, epoch=epoch,
                adds=adds, removes=removes,
                set_policies=set_policies or {},
            )
            # the delta object IS the commit point: a crash before this
            # put leaves only orphan SSTs, never a half-applied version
            raw, link = wrap_chain_doc("delta", delta.to_json(),
                                       self._chain)
            self.store.put(_DELTA_FMT.format(delta.vid), raw)
            self._chain = link
            self.current = apply_delta(self.current, delta)
            self._deltas_since_base += 1
            if self._deltas_since_base >= self.base_interval:
                self._write_base()
            return self.current

    def commit_cluster_epoch(self, epoch: int) -> HummockVersion:
        """Record a cluster-wide consistency point: an EMPTY delta
        whose only effect is advancing ``max_committed_epoch``.

        This is the cluster control plane's global commit (ref meta's
        ``commit_epoch`` bumping the version even for SST-less
        epochs): every streaming job has sealed the round, so the
        manifest — the single durable authority readers trust —
        advances exactly once per global checkpoint.  Crash-safe for
        the same reason ingest commits are: the delta object IS the
        commit; a meta killed before the put never half-commits."""
        return self.commit(epoch, adds={}, removes={})

    @property
    def max_committed_epoch(self) -> int:
        return self.current.max_committed_epoch

    def _write_base(self) -> None:
        v = self.current
        raw, link = wrap_chain_doc("version", v.to_json(), self._chain)
        self.store.put(_BASE_FMT.format(v.vid), raw)
        # the chain re-anchors on the base: the next delta commits the
        # base's link, so a follower landing on the base keeps chaining
        self._chain = link
        self._deltas_since_base = 0
        # prune superseded log entries (safe: replay ignores them)
        for key in self.store.list(_DELTA_PREFIX):
            vid = int(key[len(_DELTA_PREFIX):-len(".json")])
            if vid <= v.vid:
                self.store.delete(key)
        for key in self.store.list(_BASE_PREFIX)[:-1]:
            self.store.delete(key)

    # -- pins -----------------------------------------------------------
    def pin(self) -> tuple[int, HummockVersion]:
        """Pin the current version for a serving read; the pinned SST
        set stays vacuum-safe until unpinned (ref pinned snapshots)."""
        with self._lock:
            pin_id = self._next_pin
            self._next_pin += 1
            self._pins[pin_id] = self.current
            return pin_id, self.current

    def unpin(self, pin_id: int) -> None:
        with self._lock:
            self._pins.pop(pin_id, None)

    def pinned_count(self) -> int:
        with self._lock:
            return len(self._pins)

    def referenced_keys(self) -> set[str]:
        """Object keys reachable from the current or any pinned
        version — the vacuum keep-set."""
        with self._lock:
            keys = self.current.all_keys()
            for v in self._pins.values():
                keys |= v.all_keys()
            return keys
