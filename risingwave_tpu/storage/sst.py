"""Block-based sorted-string-table files + k-way merge reads.

Reference counterpart: ``src/storage/src/hummock/sstable/`` (block
format, builder, multi-SST iterators — SURVEY.md §2.5).  Simplified
round-1 format, one file per SST:

    [block 0][block 1]...[block k-1][index json][footer]
    footer = index_offset (8B LE) + index_len (8B LE) + magic (8B)

Each block holds varint-framed (key, value) records in key order with a
crc32c trailer; the index stores each block's first key + offset/len.
Point gets binary-search the index then scan one block; range scans
merge blocks.  ``merge_iter`` merges multiple SSTs newest-first with
tombstone handling — the LSM read path (compaction lands next round).
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass

import numpy as np

from risingwave_tpu.storage import codec

MAGIC = b"RWTPUSST"
TOMBSTONE = b"\xff\xfe__tombstone__"
DEFAULT_BLOCK_BYTES = 64 * 1024


@dataclass
class SstMeta:
    path: str
    first_key: bytes
    last_key: bytes
    n_records: int


def write_sst(path: str, keys: list[bytes], values: list[bytes],
              block_bytes: int = DEFAULT_BLOCK_BYTES) -> SstMeta:
    """Write sorted (key, value) pairs; keys must be pre-sorted unique."""
    assert len(keys) == len(values)
    index = []
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        i = 0
        offset = 0
        while i < len(keys):
            # greedy block packing
            j = i
            sz = 0
            while j < len(keys) and (sz < block_bytes or j == i):
                sz += len(keys[j]) + len(values[j]) + 10
                j += 1
            blk_keys = keys[i:j]
            blk_vals = values[i:j]
            ko = np.cumsum([0] + [len(k) for k in blk_keys]).astype(np.int64)
            vo = np.cumsum([0] + [len(v) for v in blk_vals]).astype(np.int64)
            kpool = np.frombuffer(b"".join(blk_keys), np.uint8)
            vpool = np.frombuffer(b"".join(blk_vals), np.uint8)
            block = codec.block_encode(kpool, ko, vpool, vo)
            crc = struct.pack("<I", codec.crc32c(block))
            f.write(block)
            f.write(crc)
            index.append({
                "first_key": blk_keys[0].hex(),
                "offset": offset,
                "len": len(block),
            })
            offset += len(block) + 4
            i = j
        index_bytes = json.dumps({
            "blocks": index, "n": len(keys),
        }).encode()
        f.write(index_bytes)
        f.write(struct.pack("<QQ", offset, len(index_bytes)))
        f.write(MAGIC)
    os.replace(tmp, path)
    return SstMeta(
        path=path,
        first_key=keys[0] if keys else b"",
        last_key=keys[-1] if keys else b"",
        n_records=len(keys),
    )


class SstReader:
    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        self._f.seek(-24, os.SEEK_END)
        tail = self._f.read(24)
        index_offset, index_len = struct.unpack("<QQ", tail[:16])
        if tail[16:] != MAGIC:
            raise ValueError(f"{path}: bad magic")
        self._f.seek(index_offset)
        self.index = json.loads(self._f.read(index_len))
        self._block_first_keys = [
            bytes.fromhex(b["first_key"]) for b in self.index["blocks"]
        ]

    def close(self) -> None:
        self._f.close()

    def __del__(self):  # best-effort
        try:
            self._f.close()
        except Exception:
            pass

    @property
    def n_records(self) -> int:
        return self.index["n"]

    def _read_block(self, bi: int):
        meta = self.index["blocks"][bi]
        self._f.seek(meta["offset"])
        data = self._f.read(meta["len"] + 4)
        block, crc = data[:-4], struct.unpack("<I", data[-4:])[0]
        if codec.crc32c(block) != crc:
            raise ValueError(f"{self.path}: block {bi} checksum mismatch")
        keys, ko, vals, vo = codec.block_decode(block)
        out = []
        kb = keys.tobytes()
        vb = vals.tobytes()
        for i in range(len(ko) - 1):
            out.append((kb[ko[i]:ko[i + 1]], vb[vo[i]:vo[i + 1]]))
        return out

    def get(self, key: bytes) -> bytes | None:
        import bisect
        bi = bisect.bisect_right(self._block_first_keys, key) - 1
        if bi < 0:
            return None
        for k, v in self._read_block(bi):
            if k == key:
                return v
        return None

    def scan(self, lo: bytes = b"", hi: bytes | None = None):
        """Yield (key, value) with lo <= key < hi."""
        import bisect
        start = max(bisect.bisect_right(self._block_first_keys, lo) - 1, 0)
        for bi in range(start, len(self.index["blocks"])):
            for k, v in self._read_block(bi):
                if k < lo:
                    continue
                if hi is not None and k >= hi:
                    return
                yield k, v


def merge_scan(readers: list[SstReader], lo: bytes = b"",
               hi: bytes | None = None):
    """K-way merge over SSTs, newest FIRST in ``readers``; per key the
    newest value wins; tombstones suppress (ref MergeIterator,
    src/storage/src/hummock/iterator/merge_inner.rs:62)."""
    import heapq

    iters = []
    for gen, r in enumerate(readers):
        it = r.scan(lo, hi)
        first = next(it, None)
        if first is not None:
            iters.append((first[0], gen, first[1], it))
    heapq.heapify(iters)
    last_key = None
    while iters:
        k, gen, v, it = heapq.heappop(iters)
        nxt = next(it, None)
        if nxt is not None:
            heapq.heappush(iters, (nxt[0], gen, nxt[1], it))
        if k == last_key:
            continue  # older generation shadowed
        last_key = k
        if v == TOMBSTONE:
            continue
        yield k, v
