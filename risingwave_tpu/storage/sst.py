"""Block-based sorted-string-table files + k-way merge reads.

Reference counterpart: ``src/storage/src/hummock/sstable/`` (block
format, builder, multi-SST iterators — SURVEY.md §2.5).  Simplified
round-1 format, one file per SST:

    [block 0][block 1]...[block k-1][index json][footer]
    footer = index_offset (8B LE) + index_len (8B LE) + magic (8B)

Each block holds varint-framed (key, value) records in key order with a
crc32c trailer; the index stores each block's first key + offset/len.
Point gets binary-search the index then scan one block; range scans
merge blocks.  ``merge_scan`` merges multiple SSTs newest-first with
tombstone handling — the LSM read path.

``LsmTree`` adds the LSM lifecycle on top: L0 accumulates newest-first
overlapping runs; levels 1..n hold one sorted run each; compaction
merges a level into the next when it exceeds its budget, dropping
tombstones at the bottommost level (ref compactor_runner.rs:70).
``BlockCache`` is the foyer-block-cache analog for the serving read
path (sstable_store.rs:208).
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass

import numpy as np

from risingwave_tpu.storage import codec

MAGIC = b"RWTPUSST"
TOMBSTONE = b"\xff\xfe__tombstone__"
DEFAULT_BLOCK_BYTES = 64 * 1024


@dataclass
class SstMeta:
    path: str
    first_key: bytes
    last_key: bytes
    n_records: int


def write_sst(path: str, keys: list[bytes], values: list[bytes],
              block_bytes: int = DEFAULT_BLOCK_BYTES) -> SstMeta:
    """Write sorted (key, value) pairs; keys must be pre-sorted unique."""
    assert len(keys) == len(values)
    index = []
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        i = 0
        offset = 0
        while i < len(keys):
            # greedy block packing
            j = i
            sz = 0
            while j < len(keys) and (sz < block_bytes or j == i):
                sz += len(keys[j]) + len(values[j]) + 10
                j += 1
            blk_keys = keys[i:j]
            blk_vals = values[i:j]
            ko = np.cumsum([0] + [len(k) for k in blk_keys]).astype(np.int64)
            vo = np.cumsum([0] + [len(v) for v in blk_vals]).astype(np.int64)
            kpool = np.frombuffer(b"".join(blk_keys), np.uint8)
            vpool = np.frombuffer(b"".join(blk_vals), np.uint8)
            block = codec.block_encode(kpool, ko, vpool, vo)
            crc = struct.pack("<I", codec.crc32c(block))
            f.write(block)
            f.write(crc)
            index.append({
                "first_key": blk_keys[0].hex(),
                "offset": offset,
                "len": len(block),
            })
            offset += len(block) + 4
            i = j
        index_bytes = json.dumps({
            "blocks": index, "n": len(keys),
        }).encode()
        f.write(index_bytes)
        f.write(struct.pack("<QQ", offset, len(index_bytes)))
        f.write(MAGIC)
    os.replace(tmp, path)
    return SstMeta(
        path=path,
        first_key=keys[0] if keys else b"",
        last_key=keys[-1] if keys else b"",
        n_records=len(keys),
    )


class BlockCache:
    """LRU over decoded blocks, shared across readers (ref the foyer
    hybrid block cache fronting SstableStore, sstable_store.rs:208 —
    here memory-only; the 'disk tier' is the SST itself)."""

    def __init__(self, capacity_blocks: int = 256):
        from collections import OrderedDict
        self._d: "OrderedDict[tuple, list]" = OrderedDict()
        self.capacity = capacity_blocks
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple):
        v = self._d.get(key)
        if v is not None:
            self._d.move_to_end(key)
            self.hits += 1
        else:
            self.misses += 1
        return v

    def put(self, key: tuple, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)


class SstReader:
    def __init__(self, path: str, cache: "BlockCache | None" = None):
        self.path = path
        self.cache = cache
        self._f = open(path, "rb")
        self._f.seek(-24, os.SEEK_END)
        tail = self._f.read(24)
        index_offset, index_len = struct.unpack("<QQ", tail[:16])
        if tail[16:] != MAGIC:
            raise ValueError(f"{path}: bad magic")
        self._f.seek(index_offset)
        self.index = json.loads(self._f.read(index_len))
        self._block_first_keys = [
            bytes.fromhex(b["first_key"]) for b in self.index["blocks"]
        ]

    def close(self) -> None:
        self._f.close()

    def __del__(self):  # best-effort
        try:
            self._f.close()
        except Exception:
            pass

    @property
    def n_records(self) -> int:
        return self.index["n"]

    def _read_block(self, bi: int):
        if self.cache is not None:
            hit = self.cache.get((self.path, bi))
            if hit is not None:
                return hit
        meta = self.index["blocks"][bi]
        self._f.seek(meta["offset"])
        data = self._f.read(meta["len"] + 4)
        block, crc = data[:-4], struct.unpack("<I", data[-4:])[0]
        if codec.crc32c(block) != crc:
            raise ValueError(f"{self.path}: block {bi} checksum mismatch")
        keys, ko, vals, vo = codec.block_decode(block)
        out = []
        kb = keys.tobytes()
        vb = vals.tobytes()
        for i in range(len(ko) - 1):
            out.append((kb[ko[i]:ko[i + 1]], vb[vo[i]:vo[i + 1]]))
        if self.cache is not None:
            self.cache.put((self.path, bi), out)
        return out

    def get(self, key: bytes) -> bytes | None:
        import bisect
        bi = bisect.bisect_right(self._block_first_keys, key) - 1
        if bi < 0:
            return None
        for k, v in self._read_block(bi):
            if k == key:
                return v
        return None

    def scan(self, lo: bytes = b"", hi: bytes | None = None):
        """Yield (key, value) with lo <= key < hi."""
        import bisect
        start = max(bisect.bisect_right(self._block_first_keys, lo) - 1, 0)
        for bi in range(start, len(self.index["blocks"])):
            for k, v in self._read_block(bi):
                if k < lo:
                    continue
                if hi is not None and k >= hi:
                    return
                yield k, v


class LsmTree:
    """Leveled LSM over SST files with a JSON manifest.

    Structure (ref Hummock levels + compactor, compactor_runner.rs:70):
    - level 0: newest-first list of overlapping runs (one per sealed
      write batch);
    - level i>=1: at most ONE sorted run each.

    Compaction policy: when L0 reaches ``l0_trigger`` runs, L0 + L1
    merge into a new L1 run; when a level's run exceeds its byte
    budget (``base_bytes * ratio**(i-1)``), it merges into the next
    level.  Tombstones drop only when the output is the bottommost
    populated level (deeper data could otherwise resurrect).  All
    decisions are deterministic functions of the manifest — the
    compaction determinism test replays byte-for-byte.
    """

    def __init__(self, root: str, cache: "BlockCache | None" = None,
                 l0_trigger: int = 4, base_bytes: int = 4 << 20,
                 ratio: int = 8):
        self.root = root
        self.cache = cache
        self.l0_trigger = l0_trigger
        self.base_bytes = base_bytes
        self.ratio = ratio
        os.makedirs(root, exist_ok=True)
        self._manifest_path = os.path.join(root, "LSM_MANIFEST.json")
        if os.path.exists(self._manifest_path):
            with open(self._manifest_path) as f:
                self.m = json.load(f)
        else:
            self.m = {"seq": 0, "levels": [[]]}
        self._readers: dict[str, SstReader] = {}

    # -- manifest -------------------------------------------------------
    def _store(self) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.m, f, indent=1)
        os.replace(tmp, self._manifest_path)

    def _reader(self, path: str) -> SstReader:
        r = self._readers.get(path)
        if r is None:
            r = SstReader(os.path.join(self.root, path), self.cache)
            self._readers[path] = r
        return r

    def _new_path(self) -> str:
        self.m["seq"] += 1
        return f"sst_{self.m['seq']:08d}.sst"

    # -- writes ---------------------------------------------------------
    def write_batch(self, pairs: list[tuple[bytes, bytes]]) -> None:
        """Seal one sorted batch as a new L0 run (the shared-buffer →
        SST upload); deletes pass TOMBSTONE values."""
        if not pairs:
            return
        pairs = sorted(pairs)
        path = self._new_path()
        write_sst(os.path.join(self.root, path),
                  [k for k, _ in pairs], [v for _, v in pairs])
        self.m["levels"][0].insert(0, path)
        self._store()
        self.maybe_compact()

    def delete_batch(self, keys: list[bytes]) -> None:
        self.write_batch([(k, TOMBSTONE) for k in keys])

    # -- compaction -----------------------------------------------------
    def _level_bytes(self, i: int) -> int:
        return sum(
            os.path.getsize(os.path.join(self.root, p))
            for p in self.m["levels"][i]
        )

    def maybe_compact(self) -> int:
        """Run the deterministic policy to quiescence; returns the
        number of compactions performed."""
        n = 0
        while True:
            levels = self.m["levels"]
            if len(levels[0]) >= self.l0_trigger:
                self._compact_into(0)
                n += 1
                continue
            done = True
            for i in range(1, len(levels)):
                budget = self.base_bytes * self.ratio ** (i - 1)
                if levels[i] and self._level_bytes(i) > budget:
                    self._compact_into(i)
                    n += 1
                    done = False
                    break
            if done:
                return n

    def _compact_into(self, i: int) -> None:
        """Merge level i (+ the existing run of level i+1) into a new
        level-i+1 run."""
        levels = self.m["levels"]
        while len(levels) <= i + 1:
            levels.append([])
        inputs = list(levels[i]) + list(levels[i + 1])
        bottommost = all(not levels[j] for j in range(i + 2, len(levels)))
        readers = [self._reader(p) for p in inputs]
        keys: list[bytes] = []
        vals: list[bytes] = []
        for k, v in merge_scan(readers, keep_tombstones=not bottommost):
            keys.append(k)
            vals.append(v)
        if keys:
            out_path = self._new_path()
            write_sst(os.path.join(self.root, out_path), keys, vals)
            levels[i + 1] = [out_path]
        else:
            # everything tombstoned away: no output run, no orphan file
            levels[i + 1] = []
        levels[i] = []
        self._store()
        for p in inputs:
            r = self._readers.pop(p, None)
            if r is not None:
                r.close()
            try:
                os.remove(os.path.join(self.root, p))
            except OSError:
                pass

    # -- reads ----------------------------------------------------------
    def _all_readers(self) -> list[SstReader]:
        out = []
        for level in self.m["levels"]:
            for p in level:
                out.append(self._reader(p))
        return out

    def get(self, key: bytes) -> bytes | None:
        for r in self._all_readers():
            v = r.get(key)
            if v is not None:
                return None if v == TOMBSTONE else v
        return None

    def scan(self, lo: bytes = b"", hi: bytes | None = None):
        yield from merge_scan(self._all_readers(), lo, hi)

    def file_count(self) -> int:
        return sum(len(lv) for lv in self.m["levels"])

    def close(self) -> None:
        for r in self._readers.values():
            r.close()
        self._readers.clear()


def merge_scan(readers: list[SstReader], lo: bytes = b"",
               hi: bytes | None = None, keep_tombstones: bool = False):
    """K-way merge over SSTs, newest FIRST in ``readers``; per key the
    newest value wins; tombstones suppress (ref MergeIterator,
    src/storage/src/hummock/iterator/merge_inner.rs:62)."""
    import heapq

    iters = []
    for gen, r in enumerate(readers):
        it = r.scan(lo, hi)
        first = next(it, None)
        if first is not None:
            iters.append((first[0], gen, first[1], it))
    heapq.heapify(iters)
    last_key = None
    while iters:
        k, gen, v, it = heapq.heappop(iters)
        nxt = next(it, None)
        if nxt is not None:
            heapq.heappush(iters, (nxt[0], gen, nxt[1], it))
        if k == last_key:
            continue  # older generation shadowed
        last_key = k
        if v == TOMBSTONE and not keep_tombstones:
            continue
        yield k, v
