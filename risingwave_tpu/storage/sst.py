"""Block-based sorted-string-table files + k-way merge reads.

Reference counterpart: ``src/storage/src/hummock/sstable/`` (block
format, builder, bloom filters, multi-SST iterators — SURVEY.md §2.5).
Simplified format, one object per SST:

    [block 0][block 1]...[block k-1][index json][footer]
    footer = index_offset (8B LE) + index_len (8B LE)
           + index_crc32c (4B LE) + magic (8B)

Each block holds varint-framed (key, value) records in key order with a
crc32c trailer; the index stores each block's first key + offset/len,
the SST's key range, and a per-SST bloom filter over full keys; the
footer crc covers the whole index/bloom region, so EVERY byte of an
SST is checksummed (ref block.rs crc32c + the sstable meta checksum).
Corruption raises the typed ``IntegrityError`` taxonomy
(storage/integrity.py) — ``BlockCorruption`` for a data block,
``FooterCorruption`` for the footer/index — which the owners turn into
quarantine + repair instead of a crash.  Point
gets consult the bloom then binary-search the index and scan one block;
range scans merge blocks.  ``merge_scan`` merges multiple SSTs
newest-first with tombstone handling — the LSM read path — skipping
readers whose key range misses the scan window.

All I/O goes through the ``ObjectStore`` seam
(``storage/hummock/object_store.py``); the legacy path-based API keeps
working via a local-filesystem store.

``LsmTree`` adds the LSM lifecycle on top: L0 accumulates newest-first
overlapping runs; levels 1..n hold one sorted run each; compaction
merges a level into the next when it exceeds its budget, dropping
tombstones ONLY when the output is the bottommost non-empty level
(ref compactor_runner.rs:70).  With ``auto_compact=False`` the write
path performs no merge I/O and a background driver (the hummock
``CompactorService``) calls ``compact_one`` instead.  ``BlockCache``
is the foyer-block-cache analog for the serving read path
(sstable_store.rs:208).
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass

import numpy as np

from risingwave_tpu.storage import codec
from risingwave_tpu.storage.integrity import (
    BlockCorruption,
    FooterCorruption,
)

#: legacy footer magic (24-byte footer, no index crc) — still readable
MAGIC = b"RWTPUSST"
#: current footer magic: 28-byte footer whose crc covers the index
MAGIC2 = b"RWTPUST2"
TOMBSTONE = b"\xff\xfe__tombstone__"
DEFAULT_BLOCK_BYTES = 64 * 1024
DEFAULT_BLOOM_BITS_PER_KEY = 10


@dataclass
class SstMeta:
    path: str
    first_key: bytes
    last_key: bytes
    n_records: int
    size: int = 0


# -- bloom filter -------------------------------------------------------
def _bloom_hashes(key: bytes) -> tuple[int, int]:
    """Double hashing (h1 + i*h2) — two crc32c passes, h2 forced odd."""
    h1 = codec.crc32c(key)
    h2 = codec.crc32c(b"\x9e" + key) | 1
    return h1, h2


def bloom_build(keys: list[bytes], bits_per_key: int) -> dict:
    """Build the per-SST filter; returned dict embeds in the index."""
    m = max(64, len(keys) * bits_per_key)
    m = (m + 7) & ~7  # whole bytes
    k = max(1, min(8, round(0.69 * bits_per_key)))
    bits = bytearray(m // 8)
    for key in keys:
        h1, h2 = _bloom_hashes(key)
        for i in range(k):
            b = (h1 + i * h2) % m
            bits[b >> 3] |= 1 << (b & 7)
    return {"m": m, "k": k, "bits": bytes(bits).hex()}


def bloom_may_contain(bloom: dict, key: bytes,
                      bits: bytes | None = None) -> bool:
    """Probe a filter dict; pass pre-decoded ``bits`` on hot paths."""
    if bits is None:
        bits = bytes.fromhex(bloom["bits"])
    m, k = bloom["m"], bloom["k"]
    h1, h2 = _bloom_hashes(key)
    for i in range(k):
        b = (h1 + i * h2) % m
        if not bits[b >> 3] & (1 << (b & 7)):
            return False
    return True


# -- builder ------------------------------------------------------------
def build_sst_bytes(
    keys: list[bytes], values: list[bytes],
    block_bytes: int = DEFAULT_BLOCK_BYTES,
    bloom_bits_per_key: int = DEFAULT_BLOOM_BITS_PER_KEY,
) -> tuple[bytes, SstMeta]:
    """Serialize sorted (key, value) pairs to one SST object in memory;
    keys must be pre-sorted unique."""
    assert len(keys) == len(values)
    index = []
    out = bytearray()
    i = 0
    offset = 0
    while i < len(keys):
        # greedy block packing
        j = i
        sz = 0
        while j < len(keys) and (sz < block_bytes or j == i):
            sz += len(keys[j]) + len(values[j]) + 10
            j += 1
        blk_keys = keys[i:j]
        blk_vals = values[i:j]
        ko = np.cumsum([0] + [len(k) for k in blk_keys]).astype(np.int64)
        vo = np.cumsum([0] + [len(v) for v in blk_vals]).astype(np.int64)
        kpool = np.frombuffer(b"".join(blk_keys), np.uint8)
        vpool = np.frombuffer(b"".join(blk_vals), np.uint8)
        block = codec.block_encode(kpool, ko, vpool, vo)
        out += block
        out += struct.pack("<I", codec.crc32c(block))
        index.append({
            "first_key": blk_keys[0].hex(),
            "offset": offset,
            "len": len(block),
        })
        offset += len(block) + 4
        i = j
    index_bytes = json.dumps({
        "blocks": index, "n": len(keys),
        "first_key": keys[0].hex() if keys else "",
        "last_key": keys[-1].hex() if keys else "",
        "bloom": bloom_build(keys, bloom_bits_per_key)
        if bloom_bits_per_key else None,
    }).encode()
    out += index_bytes
    out += struct.pack("<QQI", offset, len(index_bytes),
                       codec.crc32c(index_bytes))
    out += MAGIC2
    meta = SstMeta(
        path="",
        first_key=keys[0] if keys else b"",
        last_key=keys[-1] if keys else b"",
        n_records=len(keys),
        size=len(out),
    )
    return bytes(out), meta


def write_sst(path: str, keys: list[bytes], values: list[bytes],
              block_bytes: int = DEFAULT_BLOCK_BYTES,
              bloom_bits_per_key: int = DEFAULT_BLOOM_BITS_PER_KEY,
              ) -> SstMeta:
    """Write sorted (key, value) pairs to a local file (atomic)."""
    data, meta = build_sst_bytes(keys, values, block_bytes,
                                 bloom_bits_per_key)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    meta.path = path
    return meta


class BlockCache:
    """LRU over decoded blocks, shared across readers (ref the foyer
    hybrid block cache fronting SstableStore, sstable_store.rs:208 —
    here memory-only; the 'disk tier' is the SST itself)."""

    def __init__(self, capacity_blocks: int = 256):
        from collections import OrderedDict
        self._d: "OrderedDict[tuple, list]" = OrderedDict()
        self.capacity = capacity_blocks
        self.hits = 0
        self.misses = 0
        #: decoded bytes inserted on misses — the serving tier's
        #: cache-fill I/O gauge (approximate: key+value payload)
        self.miss_bytes = 0

    def get(self, key: tuple):
        v = self._d.get(key)
        if v is not None:
            self._d.move_to_end(key)
            self.hits += 1
        else:
            self.misses += 1
        return v

    def put(self, key: tuple, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        self.miss_bytes += sum(len(k) + len(v) for k, v in value)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)

    def hit_ratio(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class SstReader:
    """Reader over one SST — a local path or an object-store key."""

    def __init__(self, path: str | None = None,
                 cache: "BlockCache | None" = None, *,
                 store=None, key: str | None = None):
        if store is not None:
            assert key is not None
            self.path = key
            self._f = store.open(key)
        else:
            assert path is not None
            self.path = path
            self._f = open(path, "rb")
        self.cache = cache
        try:
            self._f.seek(0, os.SEEK_END)
            size = self._f.tell()
            if size < 24:
                raise FooterCorruption(
                    f"{self.path}: truncated ({size} bytes, no footer)",
                    key=self.path,
                )
            tail_len = min(28, size)
            self._f.seek(-tail_len, os.SEEK_END)
            tail = self._f.read(tail_len)
            if tail[-8:] == MAGIC2:
                index_offset, index_len, index_crc = struct.unpack(
                    "<QQI", tail[-28:-8]
                )
            elif tail[-8:] == MAGIC:
                index_offset, index_len = struct.unpack(
                    "<QQ", tail[-24:-8]
                )
                index_crc = None  # pre-integrity SST
            else:
                raise FooterCorruption(
                    f"{self.path}: bad magic", key=self.path
                )
            self._f.seek(index_offset)
            index_bytes = self._f.read(index_len)
            if index_crc is not None \
                    and codec.crc32c(index_bytes) != index_crc:
                raise FooterCorruption(
                    f"{self.path}: index checksum mismatch",
                    key=self.path,
                )
            self.index = json.loads(index_bytes)
        except FooterCorruption:
            raise
        except (ValueError, KeyError, struct.error, OSError) as e:
            # any garbage between the footer and a decoded index is
            # the same operational event: a corrupt footer/index
            raise FooterCorruption(
                f"{self.path}: unreadable footer/index ({e!r})",
                key=self.path,
            ) from e
        self._block_first_keys = [
            bytes.fromhex(b["first_key"]) for b in self.index["blocks"]
        ]
        #: key range + bloom (absent in pre-bloom SSTs)
        self.first_key = bytes.fromhex(self.index.get("first_key", ""))
        self.last_key = bytes.fromhex(self.index.get("last_key", ""))
        self._bloom = self.index.get("bloom")
        self._bloom_bits = bytes.fromhex(self._bloom["bits"]) \
            if self._bloom else b""
        self.bloom_checks = 0
        self.bloom_negatives = 0

    def close(self) -> None:
        self._f.close()

    def __del__(self):  # best-effort
        try:
            self._f.close()
        except Exception:
            pass

    @property
    def n_records(self) -> int:
        return self.index["n"]

    def may_contain(self, key: bytes) -> bool:
        """Cheap SST-level prune: key range, then the bloom filter."""
        if self.index["n"] == 0:
            return False
        if self.last_key and not (self.first_key <= key <= self.last_key):
            return False
        if self._bloom is None:
            return True
        self.bloom_checks += 1
        if bloom_may_contain(self._bloom, key, self._bloom_bits):
            return True
        self.bloom_negatives += 1
        return False

    def overlaps(self, lo: bytes, hi: bytes | None) -> bool:
        """Does [first_key, last_key] intersect the scan window?"""
        if self.index["n"] == 0:
            return False
        if not self.last_key:
            return True  # legacy SST without a recorded range
        if self.last_key < lo:
            return False
        if hi is not None and self.first_key >= hi:
            return False
        return True

    def _read_block(self, bi: int):
        if self.cache is not None:
            hit = self.cache.get((self.path, bi))
            if hit is not None:
                return hit
        meta = self.index["blocks"][bi]
        self._f.seek(meta["offset"])
        data = self._f.read(meta["len"] + 4)
        if len(data) < meta["len"] + 4:
            raise BlockCorruption(
                f"{self.path}: block {bi} truncated", key=self.path
            )
        block, crc = data[:-4], struct.unpack("<I", data[-4:])[0]
        if codec.crc32c(block) != crc:
            raise BlockCorruption(
                f"{self.path}: block {bi} checksum mismatch",
                key=self.path,
            )
        keys, ko, vals, vo = codec.block_decode(block)
        out = []
        kb = keys.tobytes()
        vb = vals.tobytes()
        for i in range(len(ko) - 1):
            out.append((kb[ko[i]:ko[i + 1]], vb[vo[i]:vo[i + 1]]))
        if self.cache is not None:
            self.cache.put((self.path, bi), out)
        return out

    def get(self, key: bytes) -> bytes | None:
        import bisect
        if not self.may_contain(key):
            return None
        bi = bisect.bisect_right(self._block_first_keys, key) - 1
        if bi < 0:
            return None
        for k, v in self._read_block(bi):
            if k == key:
                return v
        return None

    def scan(self, lo: bytes = b"", hi: bytes | None = None,
             stats=None):
        """Yield (key, value) with lo <= key < hi.

        ``stats`` (optional, duck-typed with a ``blocks_skipped``
        attribute — ``pushdown.PushdownStats``) counts blocks the
        range pruning never decoded: everything bisected past at the
        front plus everything abandoned after the ``hi`` cut."""
        import bisect
        n_blocks = len(self.index["blocks"])
        if not self.overlaps(lo, hi):
            if stats is not None:
                stats.blocks_skipped += n_blocks
            return
        start = max(bisect.bisect_right(self._block_first_keys, lo) - 1, 0)
        if stats is not None:
            stats.blocks_skipped += start
        for bi in range(start, n_blocks):
            for k, v in self._read_block(bi):
                if k < lo:
                    continue
                if hi is not None and k >= hi:
                    if stats is not None:
                        stats.blocks_skipped += n_blocks - bi - 1
                    return
                yield k, v


def output_is_bottommost(levels, out_level: int) -> bool:
    """True iff a compaction writing into ``out_level`` produces the
    bottommost NON-EMPTY level — i.e. no level strictly deeper holds
    any run.  Only then may tombstones drop: any deeper run could hold
    an older value of a deleted key, and dropping the tombstone above
    it would resurrect that value on the next merge read.  The inline
    cascade preserves this invariant implicitly; a task-based external
    compactor (hummock ``CompactorService``) MUST consult it per task
    (ref compactor_runner.rs:70 bottom-level check)."""
    return all(not levels[j] for j in range(out_level + 1, len(levels)))


class LsmTree:
    """Leveled LSM over SST objects with a JSON manifest.

    Structure (ref Hummock levels + compactor, compactor_runner.rs:70):
    - level 0: newest-first list of overlapping runs (one per sealed
      write batch);
    - level i>=1: at most ONE sorted run each.

    Compaction policy: when L0 reaches ``l0_trigger`` runs, L0 + L1
    merge into a new L1 run; when a level's run exceeds its byte
    budget (``base_bytes * ratio**(i-1)``), it merges into the next
    level.  Tombstones drop only when the output is the bottommost
    non-empty level (``output_is_bottommost`` — deeper data could
    otherwise resurrect).  All decisions are deterministic functions
    of the manifest — the compaction determinism test replays
    byte-for-byte.

    With ``auto_compact=False`` the write path never merges: a
    background driver calls ``compact_one`` (the hummock compactor
    split).  All I/O goes through ``self.store`` (default: local
    filesystem rooted at ``root``).
    """

    _MANIFEST = "LSM_MANIFEST.json"

    def __init__(self, root: str, cache: "BlockCache | None" = None,
                 l0_trigger: int = 4, base_bytes: int = 4 << 20,
                 ratio: int = 8, *, store=None, auto_compact: bool = True,
                 metrics=None,
                 bloom_bits_per_key: int = DEFAULT_BLOOM_BITS_PER_KEY):
        from risingwave_tpu.storage.hummock.object_store import (
            LocalFsObjectStore,
        )
        self.root = root
        self.cache = cache
        self.l0_trigger = l0_trigger
        self.base_bytes = base_bytes
        self.ratio = ratio
        self.auto_compact = auto_compact
        self.metrics = metrics
        self.bloom_bits_per_key = bloom_bits_per_key
        self.store = store if store is not None \
            else LocalFsObjectStore(root)
        #: merge I/O performed by THIS object (the write-path purity
        #: assertion surface: with auto_compact=False it stays 0)
        self.compactions_run = 0
        if self.store.exists(self._MANIFEST):
            self.m = json.loads(self.store.get(self._MANIFEST))
        else:
            self.m = {"seq": 0, "levels": [[]]}
        self._readers: dict[str, SstReader] = {}

    # -- manifest -------------------------------------------------------
    def _store_manifest(self) -> None:
        self.store.put(self._MANIFEST, json.dumps(self.m, indent=1)
                       .encode())

    def _reader(self, path: str) -> SstReader:
        r = self._readers.get(path)
        if r is None:
            r = SstReader(store=self.store, key=path, cache=self.cache)
            self._readers[path] = r
        return r

    def _new_path(self) -> str:
        self.m["seq"] += 1
        return f"sst_{self.m['seq']:08d}.sst"

    # -- writes ---------------------------------------------------------
    def write_batch(self, pairs: list[tuple[bytes, bytes]]) -> None:
        """Seal one sorted batch as a new L0 run (the shared-buffer →
        SST upload); deletes pass TOMBSTONE values.  Performs no merge
        I/O itself unless ``auto_compact``."""
        if not pairs:
            return
        pairs = sorted(pairs)
        path = self._new_path()
        data, _ = build_sst_bytes(
            [k for k, _ in pairs], [v for _, v in pairs],
            bloom_bits_per_key=self.bloom_bits_per_key,
        )
        self.store.put(path, data)
        self.m["levels"][0].insert(0, path)
        self._store_manifest()
        if self.auto_compact:
            self.maybe_compact()

    def delete_batch(self, keys: list[bytes]) -> None:
        self.write_batch([(k, TOMBSTONE) for k in keys])

    # -- compaction -----------------------------------------------------
    def _level_bytes(self, i: int) -> int:
        return sum(self.store.size(p) for p in self.m["levels"][i])

    def l0_depth(self) -> int:
        return len(self.m["levels"][0])

    def pending_compaction(self) -> int | None:
        """The deterministic policy: the input level of the next due
        compaction, or None at quiescence."""
        levels = self.m["levels"]
        if len(levels[0]) >= self.l0_trigger:
            return 0
        for i in range(1, len(levels)):
            budget = self.base_bytes * self.ratio ** (i - 1)
            if levels[i] and self._level_bytes(i) > budget:
                return i
        return None

    def compact_one(self) -> bool:
        """Run at most ONE compaction task (the external-driver step);
        returns whether anything was compacted."""
        i = self.pending_compaction()
        if i is None:
            return False
        self._compact_into(i)
        return True

    def maybe_compact(self) -> int:
        """Run the deterministic policy to quiescence; returns the
        number of compactions performed."""
        n = 0
        while self.compact_one():
            n += 1
        return n

    def _compact_into(self, i: int) -> None:
        """Merge level i (+ the existing run of level i+1) into a new
        level-i+1 run."""
        levels = self.m["levels"]
        while len(levels) <= i + 1:
            levels.append([])
        inputs = list(levels[i]) + list(levels[i + 1])
        # tombstones drop ONLY into the bottommost non-empty level;
        # deeper runs may hold older values a dropped tombstone would
        # resurrect (the task-based compactor hits this case routinely:
        # L0→L1 while L2 holds data)
        bottommost = output_is_bottommost(levels, i + 1)
        readers = [self._reader(p) for p in inputs]
        keys: list[bytes] = []
        vals: list[bytes] = []
        in_bytes = 0
        for k, v in merge_scan(readers, keep_tombstones=not bottommost):
            keys.append(k)
            vals.append(v)
            in_bytes += len(k) + len(v)
        if keys:
            out_path = self._new_path()
            data, _ = build_sst_bytes(
                keys, vals, bloom_bits_per_key=self.bloom_bits_per_key)
            self.store.put(out_path, data)
            levels[i + 1] = [out_path]
        else:
            # everything tombstoned away: no output run, no orphan file
            levels[i + 1] = []
        levels[i] = []
        self._store_manifest()
        self.compactions_run += 1
        if self.metrics is not None:
            self.metrics.inc("storage_compaction_tasks_total",
                             level=str(i))
            self.metrics.inc("storage_compaction_bytes_total", in_bytes)
        for p in inputs:
            r = self._readers.pop(p, None)
            if r is not None:
                r.close()
            self.store.delete(p)

    # -- reads ----------------------------------------------------------
    def _all_readers(self) -> list[SstReader]:
        out = []
        for level in self.m["levels"]:
            for p in level:
                out.append(self._reader(p))
        return out

    def get(self, key: bytes) -> bytes | None:
        for r in self._all_readers():
            # bloom + key-range prune before any block I/O
            if not r.may_contain(key):
                if self.metrics is not None:
                    self.metrics.inc("storage_bloom_filter_total",
                                     result="skip")
                continue
            v = r.get(key)
            if self.metrics is not None:
                self.metrics.inc(
                    "storage_bloom_filter_total",
                    result="hit" if v is not None else "miss",
                )
            if v is not None:
                return None if v == TOMBSTONE else v
        return None

    def scan(self, lo: bytes = b"", hi: bytes | None = None):
        yield from merge_scan(self._all_readers(), lo, hi)

    def file_count(self) -> int:
        return sum(len(lv) for lv in self.m["levels"])

    def close(self) -> None:
        for r in self._readers.values():
            r.close()
        self._readers.clear()


def merge_scan(readers: list[SstReader], lo: bytes = b"",
               hi: bytes | None = None, keep_tombstones: bool = False):
    """K-way merge over SSTs, newest FIRST in ``readers``; per key the
    newest value wins; tombstones suppress (ref MergeIterator,
    src/storage/src/hummock/iterator/merge_inner.rs:62).  Readers whose
    key range misses [lo, hi) never open a block."""
    import heapq

    iters = []
    for gen, r in enumerate(readers):
        if not r.overlaps(lo, hi):
            continue
        it = r.scan(lo, hi)
        first = next(it, None)
        if first is not None:
            iters.append((first[0], gen, first[1], it))
    heapq.heapify(iters)
    last_key = None
    while iters:
        k, gen, v, it = heapq.heappop(iters)
        nxt = next(it, None)
        if nxt is not None:
            heapq.heappush(iters, (nxt[0], gen, nxt[1], it))
        if k == last_key:
            continue  # older generation shadowed
        last_key = k
        if v == TOMBSTONE and not keep_tombstones:
            continue
        yield k, v
