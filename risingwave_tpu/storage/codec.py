"""ctypes bindings for the native storage codec (+ numpy fallback).

The C++ library (native/rwtpu_codec.cpp) implements the hot host-side
loops: memcomparable scalar encoding, varint block encode/decode,
crc32c.  Built on first use with g++ and cached beside the source; a
pure-numpy fallback keeps the storage layer functional without a
toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
_SRC = os.path.join(_REPO_ROOT, "native", "rwtpu_codec.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "librwtpu_codec.so")

_lock = threading.Lock()
_lib = None
_native_failed = False


def _load():
    global _lib, _native_failed
    if _lib is not None or _native_failed:
        return _lib
    with _lock:
        if _lib is not None or _native_failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO],
                    check=True, capture_output=True,
                )
            lib = ctypes.CDLL(_SO)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            i64p = ctypes.POINTER(ctypes.c_int64)
            f64p = ctypes.POINTER(ctypes.c_double)
            lib.mc_encode_i64.argtypes = [i64p, ctypes.c_int64, u8p]
            lib.mc_decode_i64.argtypes = [u8p, ctypes.c_int64, i64p]
            lib.mc_encode_f64.argtypes = [f64p, ctypes.c_int64, u8p]
            lib.mc_decode_f64.argtypes = [u8p, ctypes.c_int64, f64p]
            lib.block_encode.argtypes = [u8p, i64p, u8p, i64p,
                                         ctypes.c_int64, u8p, ctypes.c_int64]
            lib.block_encode.restype = ctypes.c_int64
            lib.block_scan.argtypes = [u8p, ctypes.c_int64, i64p, i64p, i64p]
            lib.block_scan.restype = ctypes.c_int64
            lib.block_decode.argtypes = [u8p, ctypes.c_int64, u8p, i64p,
                                         u8p, i64p]
            lib.block_decode.restype = ctypes.c_int64
            lib.rw_crc32c.argtypes = [u8p, ctypes.c_int64]
            lib.rw_crc32c.restype = ctypes.c_uint32
            _lib = lib
        except Exception:
            _native_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


# ---------------------------------------------------------------------------
# memcomparable encoding


def mc_encode_i64(vals: np.ndarray) -> np.ndarray:
    vals = np.ascontiguousarray(vals, np.int64)
    lib = _load()
    out = np.empty(len(vals) * 8, np.uint8)
    if lib is not None:
        lib.mc_encode_i64(_i64(vals), len(vals), _u8(out))
        return out.reshape(len(vals), 8)
    u = (vals.view(np.uint64) ^ np.uint64(1 << 63)).byteswap()
    return u.view(np.uint8).reshape(len(vals), 8)


def mc_decode_i64(data: np.ndarray) -> np.ndarray:
    data = np.ascontiguousarray(data, np.uint8).reshape(-1, 8)
    lib = _load()
    out = np.empty(len(data), np.int64)
    if lib is not None:
        lib.mc_decode_i64(_u8(data), len(data), _i64(out))
        return out
    u = data.reshape(-1).view(np.uint64).byteswap()
    return (u ^ np.uint64(1 << 63)).view(np.int64)


def mc_encode_f64(vals: np.ndarray) -> np.ndarray:
    vals = np.ascontiguousarray(vals, np.float64)
    lib = _load()
    out = np.empty(len(vals) * 8, np.uint8)
    if lib is not None:
        lib.mc_encode_f64(_f64(vals), len(vals), _u8(out))
        return out.reshape(len(vals), 8)
    u = vals.view(np.uint64)
    u = np.where(u >> np.uint64(63), ~u, u | np.uint64(1 << 63))
    return u.byteswap().view(np.uint8).reshape(len(vals), 8)


def mc_decode_f64(data: np.ndarray) -> np.ndarray:
    data = np.ascontiguousarray(data, np.uint8).reshape(-1, 8)
    lib = _load()
    out = np.empty(len(data), np.float64)
    if lib is not None:
        lib.mc_decode_f64(_u8(data), len(data), _f64(out))
        return out
    u = data.reshape(-1).view(np.uint64).byteswap()
    u = np.where(u >> np.uint64(63), u & np.uint64(0x7FFFFFFFFFFFFFFF), ~u)
    return u.view(np.float64)


# ---------------------------------------------------------------------------
# block codec


def block_encode(keys: np.ndarray, key_offsets: np.ndarray,
                 vals: np.ndarray, val_offsets: np.ndarray) -> bytes:
    """Encode n records given flat byte pools + (n+1) offset arrays."""
    n = len(key_offsets) - 1
    keys = np.ascontiguousarray(keys, np.uint8)
    vals = np.ascontiguousarray(vals, np.uint8)
    key_offsets = np.ascontiguousarray(key_offsets, np.int64)
    val_offsets = np.ascontiguousarray(val_offsets, np.int64)
    lib = _load()
    if lib is not None:
        cap = int(keys.size + vals.size + 20 * n + 64)
        out = np.empty(cap, np.uint8)
        w = lib.block_encode(_u8(keys), _i64(key_offsets), _u8(vals),
                             _i64(val_offsets), n, _u8(out), cap)
        if w < 0:
            raise RuntimeError("block_encode overflow")
        return out[:w].tobytes()
    # fallback
    import io
    buf = io.BytesIO()
    for i in range(n):
        k = keys[key_offsets[i]:key_offsets[i + 1]].tobytes()
        v = vals[val_offsets[i]:val_offsets[i + 1]].tobytes()
        buf.write(_varint(len(k)))
        buf.write(k)
        buf.write(_varint(len(v)))
        buf.write(v)
    return buf.getvalue()


def block_decode(data: bytes):
    """Decode a block → (keys, key_offsets, vals, val_offsets)."""
    arr = np.frombuffer(data, np.uint8)
    lib = _load()
    if lib is not None:
        n = np.zeros(1, np.int64)
        kb = np.zeros(1, np.int64)
        vb = np.zeros(1, np.int64)
        rc = lib.block_scan(_u8(arr), len(arr), _i64(n), _i64(kb), _i64(vb))
        if rc < 0:
            raise ValueError("corrupt block")
        keys = np.empty(int(kb[0]), np.uint8)
        vals = np.empty(int(vb[0]), np.uint8)
        ko = np.empty(int(n[0]) + 1, np.int64)
        vo = np.empty(int(n[0]) + 1, np.int64)
        got = lib.block_decode(_u8(arr), len(arr), _u8(keys), _i64(ko),
                               _u8(vals), _i64(vo))
        if got != n[0]:
            raise ValueError("corrupt block")
        return keys, ko, vals, vo
    # fallback
    keys_l, vals_l = [], []
    i = 0
    while i < len(data):
        klen, i = _read_varint(data, i)
        keys_l.append(data[i:i + klen]); i += klen
        vlen, i = _read_varint(data, i)
        vals_l.append(data[i:i + vlen]); i += vlen
    ko = np.cumsum([0] + [len(k) for k in keys_l]).astype(np.int64)
    vo = np.cumsum([0] + [len(v) for v in vals_l]).astype(np.int64)
    keys = np.frombuffer(b"".join(keys_l), np.uint8)
    vals = np.frombuffer(b"".join(vals_l), np.uint8)
    return keys, ko, vals, vo


def crc32c(data: bytes) -> int:
    lib = _load()
    arr = np.frombuffer(data, np.uint8)
    if lib is not None:
        return int(lib.rw_crc32c(_u8(np.ascontiguousarray(arr)), len(arr)))
    # fallback: python crc32c (slow but correct)
    poly = 0x82F63B78
    c = 0xFFFFFFFF
    for b in data:
        c ^= b
        for _ in range(8):
            c = (poly ^ (c >> 1)) if c & 1 else (c >> 1)
    return c ^ 0xFFFFFFFF


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _read_varint(data: bytes, i: int):
    x = 0
    shift = 0
    while True:
        b = data[i]
        i += 1
        x |= (b & 0x7F) << shift
        if not (b & 0x80):
            return x, i
        shift += 7
