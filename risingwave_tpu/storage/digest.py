"""Shared block-digest scheme for incremental state snapshots.

One digest definition, two consumers:

- the device-side shadow snapshot (stream/shadow.py) diffs live state
  against the shadow copy and scatters only the dirty block runs;
- the durable checkpoint store (storage/checkpoint_store.py) diffs an
  epoch against the last persisted digests and uploads only the dirty
  runs as a delta file.

Because both sides hash the SAME flat element stream with the SAME
block size, the digest vector computed once per snapshot (on the
barrier path, as part of the shadow-update program) can be handed to
the durable store verbatim — the store never re-reads the full state.

The digest of one block is a position-mixed splitmix sum: every element
is xored with its golden-ratio-scaled flat index before mixing, so
swapped or shifted values cannot cancel, and the per-block sum keeps
the reduction associative (XLA fuses the elementwise mix straight into
the block reduction — no materialized temp).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.hash import _MIX_K1 as _GOLD, _mix64

#: default block size in ELEMENTS (not bytes) — matches the checkpoint
#: store's historical default so shadow digests and store digests agree
DEFAULT_BLOCK_ELEMS = 1 << 9


def normalize_u64(x):
    """Change-faithful view of any leaf as flat uint64 (1:1 elements).

    float64 avoids 64-bit float bitcasts (unimplemented by the TPU x64
    rewrite — see common/hash._key_words): frexp decomposes exactly
    into a 53-bit integer mantissa + exponent, with inf/nan pinned to
    sentinels so value flips never alias zero."""
    if x.dtype == jnp.bool_:
        v = x.astype(jnp.uint64)
    elif x.dtype == jnp.float64:
        m, e = jnp.frexp(x)
        m2 = (m * (2.0 ** 53)).astype(jnp.int64)
        m2 = jnp.where(jnp.isnan(x), jnp.int64(-(2 ** 62)), m2)
        m2 = jnp.where(jnp.isposinf(x), jnp.int64(2 ** 62), m2)
        m2 = jnp.where(jnp.isneginf(x), jnp.int64(-(2 ** 62) + 1), m2)
        v = m2.astype(jnp.uint64) ^ (e.astype(jnp.uint64)
                                     << np.uint64(53))
    elif x.dtype == jnp.float32:
        v = jax.lax.bitcast_convert_type(x, jnp.uint32).astype(jnp.uint64)
    elif x.dtype.itemsize == 8:
        v = jax.lax.bitcast_convert_type(x, jnp.uint64)
    else:
        u = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[x.dtype.itemsize]
        v = jax.lax.bitcast_convert_type(x, u).astype(jnp.uint64)
    return v.reshape(-1)


def leaf_block_count(shape, block: int) -> int:
    n = int(np.prod(shape)) if shape else 1
    return max(1, -(-n // block))


def _pack_words(x, nb: int, block: int) -> jnp.ndarray | None:
    """Narrow dtypes packed 8-bytes-per-u64 word, ``[nb * block/k]``.

    The splitmix mix is a scalar 64-bit multiply chain on this CPU ISA
    (no AVX2 vpmullq) — mixing per BYTE makes string columns ~8x more
    expensive per stored byte than int64 columns.  Packing k narrow
    lanes into one word before mixing restores byte-rate parity.
    Returns None for dtypes that already occupy a full word (the
    caller mixes elements directly)."""
    if x.dtype == jnp.bool_:
        u, bits = x.astype(jnp.uint8), 8
    elif x.dtype == jnp.float32:
        u, bits = jax.lax.bitcast_convert_type(x, jnp.uint32), 32
    elif x.dtype.itemsize == 8:
        return None
    else:
        t = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[x.dtype.itemsize]
        u, bits = jax.lax.bitcast_convert_type(x, t), 8 * x.dtype.itemsize
    k = 64 // bits
    flat = u.reshape(-1)
    pad = nb * block - flat.shape[0]
    if pad:  # trace-time: aligned leaves never materialize a pad copy
        flat = jnp.pad(flat, (0, pad))
    lanes = flat.reshape(-1, k).astype(jnp.uint64)
    shifts = (np.arange(k, dtype=np.uint64) * np.uint64(bits))
    return jnp.sum(lanes << shifts[None, :], axis=1, dtype=jnp.uint64)


def leaf_digest(x, nb: int, block: int) -> jnp.ndarray:
    """Per-block digests of one leaf, ``uint64 [nb]`` (traceable).

    ``block`` counts ELEMENTS; narrow dtypes are packed into u64 words
    first (block must keep whole words per block — any power of two
    ≥ 8 does)."""
    x = jnp.asarray(x)
    words = _pack_words(x, nb, block)
    if words is None:
        words = normalize_u64(x)
        pad = nb * block - words.shape[0]
        if pad:
            words = jnp.pad(words, (0, pad))
    wpb = words.shape[0] // nb
    idx = jnp.arange(words.shape[0], dtype=jnp.uint64)
    h = _mix64(words ^ (idx * _GOLD) ^ _GOLD)
    return jnp.sum(h.reshape(nb, wpb), axis=1)


def digest_leaves(leaves, nblocks, block: int) -> jnp.ndarray:
    """Concatenated per-block digests of a leaf list (traceable)."""
    return jnp.concatenate([
        leaf_digest(x, nb, block) for x, nb in zip(leaves, nblocks)
    ])


def lane_block_count(shape, rows: int, block: int) -> int:
    """Block count of a leaf digested as ``rows`` independent lanes
    (``rows * ceil(row_elems / block)``)."""
    n = int(np.prod(shape)) if shape else 1
    m = n // rows
    return rows * max(1, -(-m // block))


def leaf_digest_lanes(x, rows: int, block: int) -> jnp.ndarray:
    """Per-block digests of one leaf in ``rows`` lanes, ``uint64
    [lane_block_count]`` (traceable).

    A mesh-stacked leaf (``[n_shards, ...]``) digested flat would let
    blocks straddle shard rows: two shards writing different halves of
    one straddling block keep it eternally dirty, and the delta
    extraction cannot attribute it to either shard.  Lanes restart the
    block grid at every row — no digest block spans a lane boundary,
    so the dirty mask (and the dirty-run upload) is exact per shard.
    Position mixing is row-local, which is fine: a digest is only ever
    compared against the SAME block's previous digest."""
    x = jnp.asarray(x).reshape(rows, -1)
    nb_row = max(1, -(-x.shape[1] // block))
    return jax.vmap(
        lambda r: leaf_digest(r, nb_row, block)
    )(x).reshape(-1)
