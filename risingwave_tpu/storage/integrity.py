"""End-to-end integrity layer: typed corruption errors + quarantine.

Reference counterpart: Hummock's checksum discipline — every SST block
carries a crc32c (src/storage/src/hummock/sstable/block.rs) and a
checksum mismatch is an *operational event* (a storage error routed to
recovery), never a silent wrong read and never a bare process crash.
This module is the repo-wide vocabulary for that discipline:

- ``IntegrityError`` taxonomy — one typed error per corruption site
  (SST data block, SST footer/index, checkpoint epoch object, manifest
  base+delta chain), each carrying the object key so the control plane
  can quarantine and repair the exact object;
- durable **quarantine notes** — ``quarantine/<key>.json`` documents in
  the same object store, written when corruption is detected, so an
  operator (and ``ctl storage scrub``) can see every corruption event
  across process restarts;
- jax-free verifiers for whole objects (an SST end-to-end, a
  checkpoint store's manifest-recorded crcs) shared by the online
  ScrubberService (storage/hummock/scrubber.py), the offline
  ``ctl storage scrub <dir>``, and the serving tier (which must stay
  jax-free).

Everything here is detection vocabulary; *repair* lives with the
owners: the meta re-exports corrupt MV SSTs from live job state and
rewinds corrupt checkpoint lineages to the last verified epoch
(cluster/meta_service.py), a serving replica answers
``ServeUnavailable`` so the read routes around the bad replica.
"""

from __future__ import annotations

import json

from risingwave_tpu.storage import codec

QUARANTINE_PREFIX = "quarantine/"


class IntegrityError(Exception):
    """Base of the corruption taxonomy.  ``key`` names the corrupt
    object (object-store key or path); ``kind`` labels metric series
    (``integrity_errors_total{kind=...}``)."""

    kind = "integrity"

    def __init__(self, message: str, *, key: str = ""):
        super().__init__(message)
        self.key = key


class BlockCorruption(IntegrityError):
    """An SST data block failed its crc32c trailer."""

    kind = "sst_block"


class FooterCorruption(IntegrityError):
    """An SST footer/index region is unreadable: bad magic, short
    object, index crc mismatch, or undecodable index."""

    kind = "sst_footer"


class CheckpointCorruption(IntegrityError):
    """A checkpoint epoch object's bytes mismatch the crc recorded in
    the checkpoint manifest."""

    kind = "checkpoint"


class ManifestCorruption(IntegrityError):
    """The version-manifest base+delta chain broke: a delta's
    predecessor hash or self-crc does not verify."""

    kind = "manifest"


def crc32c(data: bytes) -> int:
    return codec.crc32c(data)


# ---------------------------------------------------------------------------
# durable quarantine notes


def quarantine_key(object_key: str) -> str:
    return QUARANTINE_PREFIX + object_key.replace("/", "__") + ".json"


def quarantine(store, object_key: str, reason: str, by: str = "",
               metrics=None) -> bool:
    """Write one durable quarantine note for ``object_key`` (idempotent
    — re-detections of the same object keep the first note).  Returns
    True when this call wrote the note (first detection)."""
    import time

    qk = quarantine_key(object_key)
    fresh = not store.exists(qk)
    if fresh:
        store.put(qk, json.dumps({
            "key": object_key,
            "reason": reason,
            "by": by,
            "at": time.time(),
        }).encode())
    if metrics is not None:
        metrics.set_gauge("quarantined_objects",
                          len(store.list(QUARANTINE_PREFIX)))
    return fresh


def quarantine_list(store) -> list[dict]:
    """Every durable quarantine note in the store (oldest key order)."""
    out = []
    for key in store.list(QUARANTINE_PREFIX):
        try:
            out.append(json.loads(store.get(key)))
        except Exception:  # noqa: BLE001 — a torn note is still a note
            out.append({"key": key, "reason": "unreadable note"})
    return out


def record_integrity_error(metrics, err: IntegrityError) -> None:
    if metrics is not None:
        metrics.inc("integrity_errors_total", kind=err.kind)


# ---------------------------------------------------------------------------
# jax-free object verifiers (scrubber / offline ctl / serving tier)


def verify_sst_object(store, key: str) -> int:
    """Read one SST end-to-end — footer, index crc, every data block's
    crc trailer.  Returns the number of blocks verified; raises the
    typed ``IntegrityError`` on the first mismatch."""
    from risingwave_tpu.storage.sst import SstReader

    r = SstReader(store=store, key=key)
    try:
        n = 0
        for bi in range(len(r.index["blocks"])):
            r._read_block(bi)
            n += 1
        return n
    finally:
        r.close()


def verify_checkpoint_store(store, manifest_key: str = "MANIFEST.json",
                            jobs: "list[str] | None" = None) -> dict:
    """Verify every retained checkpoint epoch object against the crcs
    the checkpoint manifest records (jax-free: bytes + crc only, no
    npz decode).  Returns ``{"verified": n, "corrupt": [(job, epoch,
    key), ...], "skipped": n_without_crc}``."""
    report = {"verified": 0, "corrupt": [], "skipped": 0}
    if not store.exists(manifest_key):
        return report
    m = json.loads(store.get(manifest_key))
    for job_name, job in m.get("jobs", {}).items():
        if jobs is not None and job_name not in jobs:
            continue
        crcs = job.get("crc", {})
        for epoch in job.get("epochs", []):
            rec = crcs.get(str(epoch))
            if rec is None:
                report["skipped"] += 1  # pre-integrity checkpoint
                continue
            for suffix in ("npz", "meta"):
                key = f"{job_name}/epoch_{epoch}.{suffix}"
                try:
                    data = store.get(key)
                except Exception:  # noqa: BLE001 — missing = corrupt chain
                    report["corrupt"].append((job_name, epoch, key))
                    continue
                if crc32c(data) != int(rec[suffix]):
                    report["corrupt"].append((job_name, epoch, key))
                else:
                    report["verified"] += 1
    return report
