"""Minimal sqllogictest (.slt) runner.

Reference counterpart: the sqllogictest-rs harness driving
``e2e_test/`` (SURVEY.md §4) — the corpus format is engine-agnostic,
so the same files can exercise this engine.

Supported directives (the subset the reference's streaming tests use):

    statement ok
    <sql>

    statement error [substring]
    <sql>

    query <type-letters> [rowsort]
    <sql>
    ----
    <expected rows, tab- or space-separated>

    sleep <n>ms|s         (mapped to engine ticks: barriers advance time)
    flush                 (FLUSH statement)

Values compare as text after normalization (ints unpadded, floats
rounded to 3 decimals like sqllogictest's convention).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class SltError(AssertionError):
    path: str
    line: int
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: {self.message}"


def _norm(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "t" if v else "f"
    if isinstance(v, float):
        if v == int(v):
            return str(int(v))
        return f"{v:.3f}"
    s = str(v)
    try:
        f = float(s)
        if "." in s or "e" in s.lower():
            return _norm(f)
    except ValueError:
        pass
    return s


def _render(engine, rows) -> list[tuple]:
    """Type-aware value rendering for comparisons: TIMESTAMP columns
    print as pg text ('2015-07-15 00:00:00.005'), using the serving
    read's bound fields when available."""
    fields = getattr(engine, "_last_fields", None)
    if not fields or not rows:
        return rows
    from risingwave_tpu.common.types import DataType

    ts_cols = [
        i for i, f in enumerate(fields)
        if f.data_type in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ)
    ]
    date_cols = [
        i for i, f in enumerate(fields) if f.data_type == DataType.DATE
    ]
    if not ts_cols and not date_cols:
        return rows
    from datetime import datetime, timedelta

    def fmt_ts(us):
        if us is None:
            return None
        us = int(us)
        dt = datetime(1970, 1, 1) + timedelta(microseconds=us)
        s = dt.replace(microsecond=0).isoformat(sep=" ")
        # fractional seconds render in millisecond groups like the
        # reference ('00:00:20.210', not pg's trimmed '.21'); micro
        # precision extends to 6 digits
        frac = us % 1_000_000
        if frac:
            if frac % 1000 == 0:
                return f"{s}.{frac // 1000:03d}"
            return f"{s}.{frac:06d}"
        return s

    def fmt_date(days):
        if days is None:
            return None
        from datetime import date
        return (date(1970, 1, 1) + timedelta(days=int(days))).isoformat()

    out = []
    for r in rows:
        r = list(r)
        for i in ts_cols:
            r[i] = fmt_ts(r[i])
        for i in date_cols:
            r[i] = fmt_date(r[i])
        out.append(tuple(r))
    return out


def run_slt(engine, path: str, tick_between: int = 1) -> int:
    """Execute an .slt file against an Engine; returns #directives run.

    ``tick_between``: engine barriers advanced after each statement so
    streaming MVs catch up before queries (the reference harness relies
    on wall-clock barrier cadence; ticks are its deterministic analog).
    """
    import os

    with open(path) as f:
        lines = f.read().splitlines()
    i = 0
    n_run = 0
    while i < len(lines):
        line = lines[i].strip()
        if not line or line.startswith("#"):
            i += 1
            continue
        if line.startswith("include "):
            target = line.split(None, 1)[1].strip()
            n_run += run_slt(
                engine,
                os.path.join(os.path.dirname(path), target),
                tick_between=tick_between,
            )
            i += 1
            continue
        if line.startswith("sleep"):
            # barriers are this engine's wall clock: sleep Ns advances N
            # barrier intervals (ms rounds up to one)
            import re as _re

            m = _re.match(r"sleep\s+(\d+)\s*(ms|s)?", line)
            n = int(m.group(1)) if m else 1
            unit = (m.group(2) or "s") if m else "s"
            barriers = max(n if unit == "s" else 1, 1)
            engine.tick(barriers=min(barriers, 60))
            i += 1
            n_run += 1
            continue
        if line == "flush":
            engine.execute("FLUSH")
            i += 1
            n_run += 1
            continue
        if line.startswith("statement"):
            expect_err = "error" in line.split()
            err_sub = line.split("error", 1)[1].strip() if expect_err \
                else None
            sql, i2 = _take_sql(lines, i + 1)
            try:
                engine.execute(sql)
                if expect_err:
                    raise SltError(path, i + 1, "expected an error")
            except SltError:
                raise
            except Exception as e:
                if not expect_err:
                    raise SltError(path, i + 1, f"unexpected error: {e}")
                if err_sub and err_sub not in str(e):
                    raise SltError(
                        path, i + 1,
                        f"error {e!r} does not contain {err_sub!r}",
                    )
            if not expect_err and tick_between and sql.lstrip()[:6].lower() \
                    in ("create", "insert"):
                engine.tick(barriers=tick_between)
            i = i2
            n_run += 1
            continue
        if line.startswith("query"):
            parts = line.split()
            rowsort = "rowsort" in parts
            sql, i2 = _take_sql(lines, i + 1, until="----")
            expected: list[str] = []
            j = i2 + 1  # skip ----
            while j < len(lines) and lines[j].strip():
                expected.append(" ".join(lines[j].split()))
                j += 1
            try:
                rows = engine.execute(sql) or []
            except Exception as e:
                raise SltError(path, i + 1, f"query failed: {e}")
            rows = _render(engine, rows)
            # sqllogictest convention: whitespace inside TEXT values
            # collapses for comparison (the corpus writes rows
            # whitespace-split), so collapse the whole line
            got = [" ".join((" ".join(_norm(v) for v in r)).split())
                   for r in rows]
            # normalize the expected side too: corpus files write floats
            # as e.g. '1.5' while _norm canonicalizes to 3 decimals
            want = [" ".join(_norm(t) for t in row.split())
                    for row in expected]
            if rowsort:
                got, want = sorted(got), sorted(want)
            if got != want:
                raise SltError(
                    path, i + 1,
                    f"mismatch\n  got:  {got}\n  want: {want}",
                )
            i = j
            n_run += 1
            continue
        raise SltError(path, i + 1, f"unknown directive {line!r}")
    return n_run


def _take_sql(lines, i, until=None):
    out = []
    while i < len(lines):
        s = lines[i]
        if until is not None and s.strip() == until:
            break
        if not s.strip():
            break
        out.append(s)
        i += 1
    return "\n".join(out), i
