"""risingwave_tpu — a TPU-native streaming-dataflow SQL framework.

A ground-up reimplementation of the *capabilities* of RisingWave (an
event-streaming SQL database that incrementally maintains materialized
views over retractable changelog streams) designed TPU-first:

- Per-chunk columnar compute (expression eval, hash-agg, hash-join,
  over-window inner loops) runs as jit-compiled XLA programs on a TPU
  mesh, with fixed shapes and visibility masks instead of dynamic
  filtering.
- Data parallelism is vnode (virtual-node) sharding mapped onto a
  ``jax.sharding.Mesh`` axis; hash exchanges are ``all_to_all``
  collectives over ICI inside the jitted step, not RPC.
- Barrier alignment, checkpointing and state persistence stay on the
  host control plane (Chandy-Lamport epoch barriers), mirroring the
  reference's meta/barrier design.

Layer map (mirrors reference layers, see SURVEY.md §1):

- ``common``   — chunks/arrays/types/vnode hashing (ref: src/common)
- ``expr``     — vectorized expression + aggregate engine (ref: src/expr)
- ``state``    — device-resident state tables + stores (ref: src/storage, state_table)
- ``stream``   — streaming executors + fragment runtime (ref: src/stream)
- ``batch``    — snapshot/serving reads (ref: src/batch)
- ``parallel`` — mesh/sharding/collective exchange (ref: dispatch/exchange)
- ``sql``      — parser/binder/planner/fragmenter (ref: src/sqlparser, src/frontend)
- ``connector``— sources (nexmark, datagen) and sinks (ref: src/connector)
- ``meta``     — catalog, barrier scheduler, checkpoint manager (ref: src/meta)
"""

import os as _os
import sys as _sys

# The serving tier (serve/, ``server.py --role serving``) is ENGINE-FREE:
# it reads MV rows straight from shared SSTs and must never pay the jax
# import (nor accidentally trace anything).  Skip the eager jax import
# when the process declares itself jax-free — every compute-facing
# module still imports jax itself, so a misrouted import in a serving
# process shows up as ``"jax" in sys.modules`` (asserted by tests).
_no_jax = bool(_os.environ.get("RWT_NO_JAX")) or (
    "--role" in _sys.argv and "serving" in _sys.argv
)

if not _no_jax:
    import jax as _jax

    # int64/timestamp/decimal columns are first-class in a SQL engine;
    # enable 64-bit types before any tracing happens.  Device kernels
    # prefer int64 / float32 paths (float64 is emulated on TPU and
    # avoided in hot loops).
    _jax.config.update("jax_enable_x64", True)

    # Some environments install a PJRT plugin whose registration hook
    # rewrites ``jax_platforms`` (e.g. to "axon,cpu"), silently
    # overriding the JAX_PLATFORMS env var.  A SQL engine must honor the
    # operator's explicit platform choice (tests/dryruns pin cpu;
    # benches pin the accelerator), so re-assert the env var over any
    # plugin override.
    if _os.environ.get("JAX_PLATFORMS"):
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

__version__ = "0.1.0"

