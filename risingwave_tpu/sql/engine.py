"""The single-process SQL engine: DDL, streaming jobs, serving reads.

Reference counterparts: the frontend ``handler`` dispatch
(src/frontend/src/handler/mod.rs:278), meta's DDL controller + barrier
scheduler (SURVEY.md §2.4), and the batch local-execution mode
(src/frontend/src/scheduler/local.rs:60) — collapsed into one object:

    eng = Engine()
    eng.execute("CREATE SOURCE bid (...) WITH (connector='nexmark', ...)")
    eng.execute("CREATE MATERIALIZED VIEW v AS SELECT ...")
    eng.tick(barriers=5)          # the global barrier loop
    eng.execute("SELECT * FROM v ORDER BY x LIMIT 10")   # serving read
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Sequence

import numpy as np

from risingwave_tpu.common.chunk import Chunk, split_col
from risingwave_tpu.common.config import RwConfig, SessionConfig, SystemParams
from risingwave_tpu.common.metrics import MetricsRegistry
from risingwave_tpu.common.trace import GLOBAL_TRACE
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.connector.nexmark import (
    AUCTION_SCHEMA,
    BID_SCHEMA,
    PERSON_SCHEMA,
    NexmarkConfig,
    NexmarkGenerator,
    NexmarkSplitReader,
)
from risingwave_tpu.meta.catalog import Catalog, CatalogEntry
from risingwave_tpu.sql import ast
from risingwave_tpu.sql.binder import Binder, Scope
from risingwave_tpu.sql.parser import parse
from risingwave_tpu.sql.planner import (
    DagPlan,
    MvTap,
    PlanError,
    Planner,
    PlannerConfig,
    UnaryPlan,
)
from risingwave_tpu.storage.checkpoint_store import _mc_encode_value
from risingwave_tpu.stream.dag import DagJob, FragNode, JoinNode
from risingwave_tpu.stream.runtime import StreamingJob


def _ast_map(node, fn):
    """Bottom-up structural map over the (frozen-dataclass) SQL AST."""
    import dataclasses

    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        changed = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = _ast_map(v, fn)
            if nv is not v:
                changed[f.name] = nv
        if changed:
            node = dataclasses.replace(node, **changed)
        return fn(node)
    if isinstance(node, tuple):
        mapped = tuple(_ast_map(x, fn) for x in node)
        return mapped if any(m is not x for m, x in zip(mapped, node)) \
            else node
    if isinstance(node, list):
        mapped = [_ast_map(x, fn) for x in node]
        return mapped if any(m is not x for m, x in zip(mapped, node)) \
            else node
    return node


def inline_udfs(stmt, udfs: dict, depth: int = 0):
    """Expand SQL-UDF calls by AST substitution (the reference inlines
    SQL UDFs in the frontend binder the same way)."""
    if not udfs:
        return stmt
    if depth > 8:
        raise ValueError("SQL UDF recursion exceeds depth 8")

    def expand(node):
        if not isinstance(node, ast.FuncCall) or node.name not in udfs:
            return node
        params, body = udfs[node.name]
        if len(node.args) != len(params):
            raise ValueError(
                f"{node.name} takes {len(params)} arguments, "
                f"got {len(node.args)}"
            )
        sub = dict(zip(params, node.args))

        def substitute(n):
            if isinstance(n, ast.ColumnRef) and n.table is None \
                    and n.name in sub:
                return sub[n.name]
            return n

        expanded = _ast_map(body, substitute)
        # the body may itself call UDFs
        return inline_udfs(expanded, udfs, depth + 1)

    return _ast_map(stmt, expand)


def _empty_chunk(schema: Schema, cap: int) -> Chunk:
    """All-invalid chunk prototype (shape-only trace input for audits)."""
    import jax.numpy as jnp

    from risingwave_tpu.common.chunk import NCol, StrCol

    cols = []
    for f in schema:
        if f.data_type.is_string:
            col = StrCol(
                jnp.zeros((cap, f.str_width), jnp.uint8),
                jnp.zeros((cap,), jnp.int32),
            )
        else:
            col = jnp.zeros((cap,), f.data_type.physical_dtype)
        if f.nullable:
            col = NCol(col, jnp.zeros((cap,), jnp.bool_))
        cols.append(col)
    return Chunk(
        tuple(cols), jnp.zeros((cap,), jnp.int8),
        jnp.zeros((cap,), jnp.bool_), schema,
    )


def _join_exchange_keys(key_exprs, chunk):
    """Evaluate join keys for vnode routing, nullability-normalized.

    compute_vnodes hashes an NCol as [zeroed-payload, null-flag] but a
    plain column as [payload] — so a key nullable on one join side and
    NOT NULL on the other would route equal non-NULL values to
    different shards.  Join equality discards NULL keys anyway (they
    match nothing), so routing hashes the zeroed payload alone: equal
    non-NULL values collide regardless of declared nullability, and
    NULL-keyed rows land (consistently) with payload-zero rows, where
    they emit as unmatched like anywhere else."""
    from risingwave_tpu.common.hash import normalize_null_col

    keys = []
    for e in key_exprs:
        keys.append(normalize_null_col(e.eval(chunk))[0])
    return keys


class Engine:
    def __init__(self, config: "PlannerConfig | RwConfig | None" = None,
                 data_dir: str | None = None, role: str = "single"):
        self.catalog = Catalog()
        if isinstance(config, RwConfig):
            self.rw_config = config
            st = config.state
            self.config = PlannerConfig(
                chunk_capacity=config.streaming.chunk_size,
                agg_table_size=st.agg_table_size,
                agg_emit_capacity=st.agg_emit_capacity,
                join_table_size=st.join_table_size,
                join_bucket_cap=st.join_bucket_cap,
                join_out_capacity=st.join_out_capacity,
                topn_pool_size=st.topn_pool_size,
                topn_emit_capacity=st.topn_emit_capacity,
                mv_table_size=st.mv_table_size,
                mv_ring_size=st.mv_ring_size,
            )
            data_dir = data_dir or config.storage.data_directory
        else:
            self.rw_config = RwConfig()
            self.config = config or PlannerConfig()
        self.planner = Planner(self.catalog, self.config)
        self.jobs: list[Any] = []
        self.system_params = SystemParams()
        self.session_config = SessionConfig()
        # per-engine registry: restarted engines must not inherit a
        # dead engine's counters for same-named jobs
        self.metrics = MetricsRegistry()
        #: rolling per-job barrier latencies feeding the
        #: ``barrier_spike_ratio`` gauge (p99/median over the window)
        self._barrier_lat: dict[str, deque] = {}
        self.checkpoint_store = None
        #: SQL UDFs: name -> (param names, body expr AST), inlined at
        #: parse time (ref: frontend SQL-UDF inlining)
        self.functions: dict[str, tuple] = {}
        self.meta_store = None
        #: the Hummock-lite storage service (object store + versioned
        #: manifest + background compactor + vacuum); built alongside
        #: the checkpoint store whenever the engine is durable
        self.hummock = None
        self.compactor = None
        #: True while replaying the durable DDL/DML logs (suppresses
        #: re-logging)
        self._replaying = False
        #: "single" owns every durable subsystem; "compute" is a
        #: cluster worker — it shares the cluster's checkpoint store
        #: but the META process owns the DDL log and the version
        #: manifest (a second VersionManager over the same object
        #: store would fork the version chain)
        self.role = role
        #: shared object store for MV export SSTs in compute role (the
        #: META owns the version manifest over the same store; workers
        #: only upload objects and hand descriptors back)
        self.shared_store = None
        #: key allocator for exported SSTs (cluster workers point this
        #: at the meta's ``alloc_sst`` RPC — single-allocator keys
        #: never collide across workers and stay vacuum-protected
        #: until their round commits)
        self.sst_key_allocator = None
        #: last exported (key → pickled row) per MV — the incremental
        #: export diff base; seeded from the shared manifest on adopt
        self._exported: dict[str, dict] = {}
        #: MV names whose serve-schema doc this process already
        #: published; CREATE/DROP INDEX discards the upstream so the
        #: doc republishes with the new index list on the next export
        self._schema_published: set = set()
        #: per-read vnode override for partitioned MV serving (the
        #: cluster worker pins reads to the map at the pinned round)
        self._serve_vnodes = None
        #: SST keys the export diff-base seeding must skip (quarantined
        #: corrupt objects mid-repair — see reexport_job_mvs)
        self._seed_exclude: frozenset = frozenset()
        #: pushdown plane — per-TTL-MV expiry horizons (max observed
        #: leading export-pk value − ttl, MONOTONE per table: the
        #: watermark proxy derived at export time) and the matching
        #: storage-key cutoffs (``expire_below`` bounds) the export
        #: path filters both sides of its diff through
        self._ttl_horizons: dict[str, int] = {}
        self._ttl_cutoffs: dict[str, bytes] = {}
        #: policy docs staged for the NEXT barrier response (cluster
        #: compute role): the meta folds them into the same manifest
        #: delta that commits the round's export SSTs
        self.pending_policies: dict = {}
        if data_dir is not None and role == "compute":
            import os as _os

            from risingwave_tpu.storage import CheckpointStore
            from risingwave_tpu.storage.hummock import (
                LocalFsObjectStore,
            )
            self.checkpoint_store = CheckpointStore(
                data_dir,
                keep_epochs=self.rw_config.storage.checkpoint_keep_epochs,
                metrics=self.metrics,
            )
            self.shared_store = LocalFsObjectStore(
                _os.path.join(data_dir, "hummock")
            )
        elif data_dir is not None:
            import os as _os

            from risingwave_tpu.meta.store import MetaStore
            from risingwave_tpu.storage import CheckpointStore
            from risingwave_tpu.storage.hummock import (
                CompactorService,
                HummockStorage,
                LocalFsObjectStore,
            )
            self.checkpoint_store = CheckpointStore(
                data_dir,
                keep_epochs=self.rw_config.storage.checkpoint_keep_epochs,
                metrics=self.metrics,
            )
            self.meta_store = MetaStore(data_dir)
            self.hummock = HummockStorage(
                LocalFsObjectStore(_os.path.join(data_dir, "hummock")),
                metrics=self.metrics,
            )
            # not started: tests/embedded use drive compaction
            # synchronously; long-running nodes call
            # start_storage_service() (server.py does)
            self.compactor = CompactorService(self.hummock)
            if self.meta_store.has_catalog():
                self._bootstrap()

    def _bootstrap(self) -> None:
        """Cold-start recovery (ref DdlController + recovery,
        ddl_controller.rs:1096, SURVEY.md §3.5): replay the durable DDL
        log to rebuild catalog + jobs, reload each DML table's history,
        then restore every job's state and source cursors from the last
        committed checkpoint."""
        self._replaying = True
        try:
            for sql in self.meta_store.ddl_log():
                self.execute(sql)
            self.recover()
        finally:
            self._replaying = False

    # ------------------------------------------------------------------
    #: DDL statement kinds recorded in the durable catalog log — the
    #: full set whose replay reconstructs catalog + job topology +
    #: plan-relevant parameters (session SETs included: they steer
    #: planning, e.g. streaming_parallelism)
    _LOGGED_DDL = (
        ast.CreateSource, ast.CreateMaterializedView, ast.CreateSink,
        ast.CreateIndex, ast.CreateFunction, ast.DropStatement,
        ast.AlterParallelism, ast.SetStatement,
    )

    def execute(self, sql: str):
        """Run one or more statements; returns the last result."""
        from risingwave_tpu.sql.parser import parse_with_text

        result = None
        for text, stmt in parse_with_text(sql):
            # the statement's raw SQL, recorded as the catalog entry's
            # definition (re-parseable — job export/adoption ships it)
            self._stmt_text = text
            if isinstance(stmt, ast.CreateFunction):
                result = self._create_function(stmt)
            else:
                result = self._execute_one(
                    inline_udfs(stmt, self.functions)
                )
            if isinstance(stmt, self._LOGGED_DDL):
                # DDL (or a planner-relevant SET) invalidates cached
                # serving pipelines
                self._serving_cache = {}
                if self.meta_store is not None and not self._replaying:
                    self.meta_store.append_ddl(text)
        return result

    def _definition_text(self, stmt) -> str:
        """The statement's original SQL (stashed by execute()) — the
        catalog entry's re-parseable definition, shipped verbatim when
        a job is exported/adopted across processes."""
        return getattr(self, "_stmt_text", None) or str(stmt)

    def _create_function(self, stmt: ast.CreateFunction):
        """Register a SQL UDF (ref: frontend SQL UDF inlining)."""
        if stmt.name in self.functions:
            if stmt.if_not_exists:
                return None
            raise ValueError(f"function {stmt.name!r} already exists")
        body = parse(stmt.body_sql)
        if len(body) != 1 or not isinstance(body[0], ast.Select) \
                or body[0].from_ is not None or len(body[0].items) != 1:
            raise ValueError(
                "SQL UDF body must be a single SELECT <expr>"
            )
        self.functions[stmt.name] = (
            tuple(stmt.params), body[0].items[0].expr
        )
        return None

    def query(self, sql: str):
        """Run statements; returns (column_names, rows) for wire clients."""
        self._last_columns = None
        rows = self.execute(sql)
        if rows is None:
            return [], []
        cols = self._last_columns
        if cols is None:
            cols = [f"col{i}" for i in range(len(rows[0]))] if rows else []
        return cols, rows

    def _execute_one(self, stmt):
        # column names are per-statement: a trailing non-SELECT must not
        # inherit an earlier SELECT's RowDescription
        self._last_columns = None
        #: bound Fields of the last SELECT's output (type-aware result
        #: rendering, e.g. timestamps in the slt runner); None when the
        #: serving path doesn't track them
        self._last_fields = None
        if isinstance(stmt, ast.CreateSource):
            return self._create_source(stmt)
        if isinstance(stmt, ast.CreateMaterializedView):
            return self._create_mview(stmt)
        if isinstance(stmt, ast.CreateIndex):
            return self._create_index(stmt)
        if isinstance(stmt, ast.CreateSink):
            return self._create_sink(stmt)
        if isinstance(stmt, ast.DropStatement):
            entry = self.catalog.get(stmt.name) \
                if stmt.name in self.catalog else None
            if entry is not None:
                want = {"source": "source", "table": "source",
                        "materialized view": "mview",
                        "sink": "sink", "index": "mview"}[stmt.kind]
                if entry.kind != want:
                    raise ValueError(
                        f"{stmt.name} is a {entry.kind}, not a {want}"
                    )
                if stmt.kind == "index" and entry.index_on is None:
                    raise ValueError(f"{stmt.name} is not an index")
                if entry.kind == "mview" and entry.index_on is None:
                    deps = [e.name for e in self.catalog.list("mview")
                            if e.index_on is not None
                            and e.index_on[0] == stmt.name]
                    if deps:
                        raise ValueError(
                            f"cannot drop {stmt.name!r}: indexes "
                            f"{deps} depend on it (DROP INDEX first)"
                        )
                if entry.kind == "mview":
                    # the shared serving keyspace forgets the MV too:
                    # tombstones for its exported rows + schema doc
                    # removed, so serving answers "does not exist"
                    # instead of stale rows
                    self._tombstone_dropped_mv(entry)
                if entry.job is not None:
                    job = entry.job
                    shared = isinstance(job, DagJob) and any(
                        e is not entry and e.job is job
                        for e in self.catalog.list()
                    )
                    if shared:
                        # removing only this MV's nodes; raises while
                        # dependent (cascaded) MVs still consume them
                        job.remove_nodes(entry.dag_nodes)
                        # this MV's private readers must stop being
                        # pulled once nothing consumes them
                        job.remove_sources(entry.dag_sources or [])
                        if not self._replaying:
                            job.reseed_checkpoint()
                    else:
                        self.jobs.remove(job)
                if entry.kind == "sink" and entry.mv_executor is not None:
                    entry.mv_executor.sink.close()
                if entry.dml is not None and self.meta_store is not None \
                        and not self._replaying:
                    # the durable history dies with the table; NOT at
                    # replay — there the log already holds only the
                    # final generation's rows
                    self.meta_store.truncate_dml(stmt.name)
                if entry.kind == "mview":
                    # DROP MV / DROP INDEX sweeps the scrape surface:
                    # the entry's own job-labeled series always; the
                    # underlying job's only when the job itself died
                    # (an index on a shared DAG leaves the host MV's
                    # series alone)
                    self._retire_job_series(entry.name)
                    if entry.job is not None \
                            and entry.job not in self.jobs:
                        self._retire_job_series(entry.job.name)
            self.catalog.drop(stmt.name, stmt.if_exists)
            return None
        if isinstance(stmt, ast.ShowStatement):
            kind = {"sources": "source", "tables": "source",
                    "materialized views": "mview",
                    "sinks": "sink"}.get(stmt.kind)
            return [(e.name,) for e in self.catalog.list(kind)]
        if isinstance(stmt, ast.FlushStatement):
            # ref FLUSH semantics (handler/flush.rs): block until all
            # DML issued so far is materialized and checkpointed — here:
            # drain every bounded source's pending rows, then commit one
            # barrier.  Unbounded sources (nexmark/datagen) have no
            # pending() and are excluded (they never drain).
            cpb = max(
                1, int(self.system_params.get("chunks_per_barrier"))
            )
            for _ in range(4096):
                pending = 0
                for job in self.jobs:
                    srcs = list(getattr(job, "sources", {}).values())
                    if not srcs:
                        s = getattr(job, "source", None) \
                            or getattr(job, "reader", None)
                        srcs = [s] if s is not None else []
                    for s in srcs:
                        if hasattr(s, "pending"):
                            pending += s.pending()
                if pending == 0:
                    break
                self.tick(barriers=1, chunks_per_barrier=cpb)
            else:
                raise RuntimeError(
                    "FLUSH did not drain in 4096 barriers "
                    f"({pending} rows still pending)"
                )
            self.tick(barriers=1, chunks_per_barrier=0)
            return None
        if isinstance(stmt, ast.SetStatement):
            if stmt.system:
                self.system_params.set(stmt.name, stmt.value)
            else:
                self.session_config.set(stmt.name, stmt.value)
            return None
        if isinstance(stmt, ast.DescribeStatement):
            entry = self.catalog.get(stmt.name)
            self._last_columns = ["name", "type"]
            return [(f.name, f.data_type.value) for f in entry.schema]
        if isinstance(stmt, ast.ShowParameters):
            return self.session_config.show_all() + [
                (k, str(v), "system")
                for k, v in sorted(self.system_params.to_dict().items())
            ]
        if isinstance(stmt, ast.Explain):
            return self._explain(stmt.statement)
        if isinstance(stmt, ast.AlterParallelism):
            return self._alter_parallelism(stmt)
        if isinstance(stmt, ast.Insert):
            return self._insert(stmt)
        if isinstance(stmt, ast.Delete):
            return self._delete(stmt)
        if isinstance(stmt, ast.Update):
            return self._update(stmt)
        if isinstance(stmt, ast.Select):
            return self._serve(stmt)
        raise ValueError(f"unhandled statement {stmt!r}")

    def _alter_parallelism(self, stmt: ast.AlterParallelism):
        """Online rescale of a running sharded MV at a barrier (ref
        ScaleController reschedule, scale.rs:224)."""
        from risingwave_tpu.stream.sharded import ShardedStreamingJob

        entry = self.catalog.get(stmt.name)
        if entry.kind != "mview" or not isinstance(
            entry.job, ShardedStreamingJob
        ):
            raise ValueError(
                f"{stmt.name} is not a sharded materialized view "
                "(linear jobs re-plan via DROP + CREATE with "
                "streaming_parallelism set)"
            )
        import jax as _jax
        n = stmt.parallelism
        if n < 2 or n > len(_jax.devices()):
            raise ValueError(
                f"parallelism {n} outside [2, {len(_jax.devices())}]"
            )
        entry.job.rescale(n)
        # retained checkpoints hold the OLD state-tree shape; re-seed
        # so recovery restores the new topology (recover() rebuilds the
        # mesh to the checkpoint's shard dim).  During bootstrap replay
        # the states are fresh — the real checkpoint must NOT be
        # overwritten; the trailing recover() will rescale-restore.
        if self.checkpoint_store is not None and not self._replaying:
            self.checkpoint_store.save(
                entry.job.name, entry.job.committed_epoch,
                entry.job.states,
                {"offset": entry.job.reader.offset},
            )
        return None

    def _dml_rows(self, stmt, entry, verb: str) -> list[tuple]:
        """Coerce INSERT/DELETE literal rows to the table schema."""
        schema = entry.schema
        if stmt.columns:
            order = [schema.index_of(c) for c in stmt.columns]
            if len(set(order)) != len(order):
                raise ValueError(f"{verb} lists a column twice")
            for i in set(range(len(schema))) - set(order):
                if not schema[i].nullable:
                    raise ValueError(
                        f"{verb} omits NOT NULL column {schema[i].name}"
                    )
        else:
            order = list(range(len(schema)))
        rows = []
        for r in stmt.rows:
            if len(r) != len(order):
                raise ValueError(f"{verb} arity mismatch")
            vals = [None] * len(schema)
            for pos, e in zip(order, r):
                vals[pos] = _coerce_const(
                    _const_value(e), schema[pos]
                )
            rows.append(tuple(vals))
        return rows

    def _insert(self, stmt: ast.Insert):
        entry = self.catalog.get(stmt.table)
        if entry.dml is None:
            raise ValueError(f"{stmt.table} is not an INSERT-able table")
        rows = self._dml_rows(stmt, entry, "INSERT")
        entry.dml.insert(rows)
        if self.meta_store is not None and not self._replaying:
            self.meta_store.append_dml(stmt.table, rows)
        return None

    def _delete(self, stmt: "ast.Delete"):
        """Exact-full-row retraction on a table created WITH
        (retract = 'true').  The marked rows (marker-tail encoding,
        connector/dml.py) are appended to the same history log, so the
        durable DML journal, exchange slicing, and replay all carry
        the op for free."""
        from risingwave_tpu.connector.dml import mark_deletes

        entry = self.catalog.get(stmt.table)
        if entry.dml is None:
            raise ValueError(f"{stmt.table} is not a DML table")
        if entry.append_only:
            raise ValueError(
                f"{stmt.table} is append-only; CREATE TABLE ... WITH "
                "(retract = 'true') to enable DELETE"
            )
        rows = self._dml_rows(stmt, entry, "DELETE")
        marked = mark_deletes(rows, len(entry.schema))
        entry.dml.insert(marked)
        if self.meta_store is not None and not self._replaying:
            self.meta_store.append_dml(stmt.table, marked)
        return None

    def _update(self, stmt: "ast.Update"):
        """``UPDATE t SET col = lit, ... WHERE <full-pk equality>`` —
        sugar over the exact-full-row retraction pair: resolve the
        live old row by pk from the table's own history log, then emit
        the SAME marked-delete + insert the workload generator would
        have shipped.  The pair lands in the durable DML journal as
        rows (not SQL), so cold-start replay reloads it like any other
        batch."""
        from risingwave_tpu.connector.dml import (
            mark_deletes,
            row_is_delete,
        )

        entry = self.catalog.get(stmt.table)
        if entry.dml is None:
            raise ValueError(f"{stmt.table} is not a DML table")
        if entry.append_only:
            raise ValueError(
                f"{stmt.table} is append-only; CREATE TABLE ... WITH "
                "(retract = 'true') to enable UPDATE"
            )
        if not entry.stream_key:
            raise ValueError(
                f"{stmt.table} has no PRIMARY KEY; UPDATE needs a "
                "full-pk WHERE"
            )
        schema = entry.schema
        width = len(schema)
        pk = set(entry.stream_key)

        def conjuncts(e):
            if isinstance(e, ast.BinaryOp) and e.op == "and":
                return conjuncts(e.left) + conjuncts(e.right)
            return [e]

        eq: dict[int, object] = {}
        for c in conjuncts(stmt.where):
            if not (isinstance(c, ast.BinaryOp) and c.op == "equal"):
                raise ValueError(
                    "UPDATE WHERE must be a conjunction of full-pk "
                    "equalities"
                )
            left, right = c.left, c.right
            if isinstance(left, ast.Literal) \
                    and isinstance(right, ast.ColumnRef):
                left, right = right, left
            if not isinstance(left, ast.ColumnRef):
                raise ValueError(
                    "UPDATE WHERE must compare columns to literals"
                )
            i = schema.index_of(left.name)
            if i is None:
                raise ValueError(
                    f"column {left.name!r} does not exist in "
                    f"{stmt.table!r}"
                )
            eq[i] = _coerce_const(_const_value(right), schema[i])
        if set(eq) != pk:
            raise ValueError(
                "UPDATE WHERE must pin exactly the full primary key"
            )

        sets: dict[int, object] = {}
        for col, expr in stmt.assignments:
            i = schema.index_of(col)
            if i is None:
                raise ValueError(
                    f"column {col!r} does not exist in {stmt.table!r}"
                )
            if i in pk:
                raise ValueError(
                    "UPDATE cannot assign a primary-key column "
                    "(retract + insert instead)"
                )
            if i in sets:
                raise ValueError(f"UPDATE assigns {col!r} twice")
            sets[i] = _coerce_const(_const_value(expr), schema[i])

        # fold the table's history as a multiset to find the live old
        # row under this pk (inserts +1, marked deletes −1) — the same
        # arithmetic every retraction-capable operator applies
        count: dict[tuple, int] = {}
        for row in entry.dml.history_slice(0):
            if row is None:
                continue  # shuffled-follower placeholder
            t = tuple(row)
            base = t[:width]
            if any(base[i] != eq[i] for i in pk):
                continue
            if row_is_delete(t, width):
                count[base] = count.get(base, 0) - 1
            else:
                count[base] = count.get(base, 0) + 1
        live = [b for b, n in count.items() if n > 0]
        if not live:
            raise ValueError(
                f"UPDATE matched no live row in {stmt.table!r}"
            )
        if len(live) > 1:
            raise ValueError(
                f"UPDATE pk matched {len(live)} live rows in "
                f"{stmt.table!r} (history is inconsistent)"
            )
        old = live[0]
        new_row = tuple(sets.get(i, old[i]) for i in range(width))
        rows = mark_deletes([old], width) + [new_row]
        entry.dml.insert(rows)
        if self.meta_store is not None and not self._replaying:
            self.meta_store.append_dml(stmt.table, rows)
        return None

    def _explain(self, stmt) -> list[tuple[str]]:
        """Plan description (ref handler/explain.rs, simplified)."""
        if isinstance(stmt, ast.CreateMaterializedView):
            query = stmt.query
        elif isinstance(stmt, ast.Select):
            query = stmt
        else:
            return [(f"DDL: {type(stmt).__name__}",)]
        plan = self.planner.plan(query)
        lines: list[tuple[str]] = []
        if isinstance(plan, UnaryPlan):
            lines.append(("StreamJob",))
            lines.append((f"  Source: {type(plan.reader).__name__}",))
            for ex in plan.fragment.executors:
                lines.append((f"  {ex!r}",))
        else:
            lines.append(("StreamJob (dataflow graph)",))
            for name, reader in plan.sources.items():
                kind = "MvTap" if isinstance(reader, MvTap) \
                    else type(reader).__name__
                lines.append((f"  source {name}: {kind}",))
            for i, node in enumerate(plan.nodes):
                if isinstance(node, JoinNode):
                    lines.append((
                        f"  node {i} <- {node.left}, {node.right}: "
                        f"HashJoin(keys={len(node.join.left_keys)})",
                    ))
                    continue
                lines.append((f"  node {i} <- {node.input}:",))
                for ex in node.fragment.executors:
                    lines.append((f"    {ex!r}",))
        return lines

    # -- DDL -------------------------------------------------------------
    def _create_source(self, stmt: ast.CreateSource):
        connector = stmt.with_options.get("connector")
        if connector is None and stmt.is_table:
            entry = self._dml_table(stmt)
        elif connector == "nexmark":
            entry = self._nexmark_source(stmt)
        elif connector == "datagen":
            entry = self._datagen_source(stmt)
        elif connector == "filetail":
            entry = self._filetail_source(stmt)
        else:
            raise ValueError(
                f"unsupported connector {connector!r} "
                "(nexmark, datagen, filetail available this round)"
            )
        self.catalog.create(entry, stmt.if_not_exists)
        return None

    def _nexmark_source(self, stmt: ast.CreateSource) -> CatalogEntry:
        opts = stmt.with_options
        table = opts.get("nexmark.table", stmt.name)
        base = {"bid": BID_SCHEMA, "auction": AUCTION_SCHEMA,
                "person": PERSON_SCHEMA}[table]
        # declared columns select/reorder the generator's columns
        if stmt.columns:
            idxs = []
            fields = []
            for c in stmt.columns:
                i = base.index_of(c.name)
                idxs.append(i)
                fields.append(base[i])
            schema = Schema(tuple(fields))
        else:
            idxs = list(range(len(base)))
            schema = base
        rate = int(opts.get("nexmark.event.rate", "100000"))
        inter_us = max(1_000_000 // max(rate, 1), 1)
        gen_config = NexmarkConfig(inter_event_us=inter_us)
        cap = self.config.chunk_capacity

        def factory(split_id: int = 0, num_splits: int = 1):
            reader = NexmarkSplitReader(
                table, NexmarkGenerator(gen_config), chunk_capacity=cap,
                split_id=split_id, num_splits=num_splits,
            )
            if idxs == list(range(len(base))):
                return reader
            return _ProjectingReader(reader, idxs, schema)

        wm = None
        if stmt.watermark is not None:
            wm = (schema.index_of(stmt.watermark.column),
                  stmt.watermark.delay.micros)
        return CatalogEntry(
            stmt.name, "source", schema, reader_factory=factory,
            watermark=wm, append_only=True, definition=self._definition_text(stmt),
        )

    @staticmethod
    def _declared_schema(stmt: ast.CreateSource):
        """(schema, watermark, auto-width cols) from CREATE SOURCE/TABLE.

        ``auto`` lists VARCHAR columns declared without a length: their
        device width starts at the default and is re-derived from
        observed data before each new plan (DML tables only — external
        sources size from their declared schema)."""
        from risingwave_tpu.common.types import parse_sql_type

        fields = []
        auto = []
        for i, c in enumerate(stmt.columns):
            t, width, scale = parse_sql_type(c.type_name)
            kw = {}
            if width is not None:
                kw["str_width"] = width
            elif t.is_string:
                auto.append(i)
            if scale is not None:
                kw["decimal_scale"] = scale
            fields.append(Field(c.name, t, nullable=c.nullable, **kw))
        schema = Schema(tuple(fields))
        wm = None
        if stmt.watermark is not None:
            wm = (schema.index_of(stmt.watermark.column),
                  stmt.watermark.delay.micros)
        return schema, wm, auto

    def _dml_table(self, stmt: ast.CreateSource) -> CatalogEntry:
        """CREATE TABLE without a connector: INSERT-fed (ref src/dml)."""
        from risingwave_tpu.connector.dml import TableDmlManager

        schema, wm, auto = self._declared_schema(stmt)
        dml = TableDmlManager(schema, auto_width_cols=auto)
        if self._replaying and self.meta_store is not None:
            # cold start: reload the table's durable history BEFORE any
            # MV replay plans against it — auto varchar widths and
            # recovered source cursors both index into this history
            hist = self.meta_store.dml_rows(stmt.name)
            if hist:
                dml.insert(hist)
        cap = self.config.chunk_capacity

        def factory(split_id: int = 0, num_splits: int = 1):
            return dml.new_reader(cap)

        pk = [schema.index_of(c) for c in stmt.primary_key] \
            if stmt.primary_key else None
        # WITH (retract = 'true'): the table accepts DELETE (exact
        # full-row retraction) and downstream plans must pick their
        # retraction-capable variants — exactly the append_only=False
        # path every changelog operator already implements
        retract = str(stmt.with_options.get(
            "retract", "false")).lower() in ("true", "1", "yes")
        return CatalogEntry(
            stmt.name, "source", schema, reader_factory=factory,
            watermark=wm, append_only=not retract,
            definition=self._definition_text(stmt),
            dml=dml, stream_key=pk,
        )

    def _filetail_source(self, stmt: ast.CreateSource) -> CatalogEntry:
        """External JSONL source tailed from disk (ref SplitReader +
        JSON parser, src/connector/src/source/base.rs:596)."""
        from risingwave_tpu.connector.file_source import FileTailSplitReader

        schema, wm, _ = self._declared_schema(stmt)
        opts = stmt.with_options
        path = opts.get("path")
        if not path:
            raise ValueError("filetail needs WITH (path = '...')")
        fmt = opts.get("format", "json")
        if fmt != "json":
            raise ValueError(f"filetail format {fmt!r} (json only)")
        cap = self.config.chunk_capacity
        rate = int(opts.get("rate.limit", cap))

        def factory(split_id: int = 0, num_splits: int = 1):
            return FileTailSplitReader(
                path, schema, chunk_capacity=cap,
                split_id=split_id, num_splits=num_splits,
                max_rows_per_chunk=rate,
            )

        return CatalogEntry(
            stmt.name, "source", schema, reader_factory=factory,
            watermark=wm, append_only=True, definition=self._definition_text(stmt),
        )

    def _datagen_source(self, stmt: ast.CreateSource) -> CatalogEntry:
        schema, wm, _ = self._declared_schema(stmt)
        cap = self.config.chunk_capacity

        def factory(split_id: int = 0, num_splits: int = 1):
            return _DatagenReader(schema, cap, split_id, num_splits)

        return CatalogEntry(
            stmt.name, "source", schema, reader_factory=factory,
            watermark=wm, append_only=True, definition=self._definition_text(stmt),
        )

    def _refresh_dml_widths(self) -> None:
        """Re-derive auto varchar widths for DML tables before planning.

        The reference's VARCHAR is unbounded (utf8_array.rs); a device
        column needs a static width before the job's programs compile,
        so width follows the observed max at plan time.  Running jobs
        keep their compiled widths; TableDmlManager.insert refuses data
        that would silently truncate in one of them."""
        for entry in self.catalog.list("source"):
            if entry.dml is not None and entry.dml.auto_width_cols:
                entry.schema = entry.dml.refresh_schema()

    def _build_job(self, plan, name: str):
        """Instantiate the runtime job for a plan (shared MV/sink path).

        When the session sets ``streaming_parallelism`` > 1, eligible
        aggregation plans run vnode-sharded over the device mesh
        (ref: adaptive parallelism, ADAPTIVE streaming jobs).

        Returns (job, terminal_executor, state_index, dag_node_ids,
        is_new_job)."""
        ckpt_freq = int(self.system_params.get("checkpoint_frequency"))
        par = int(self.session_config.get("streaming_parallelism"))
        if par == 0:  # adaptive: all devices (ref ADAPTIVE parallelism)
            import jax as _jax
            par = len(_jax.devices())
        if par > 1 and isinstance(plan, UnaryPlan):
            sharded = self._try_sharded_job(plan, name, par, ckpt_freq)
            if sharded is not None:
                job, terminal, state_index = sharded
                return job, terminal, state_index, None, True
        if par > 1 and isinstance(plan, DagPlan):
            sharded = self._try_sharded_dag_plan(plan, name, par, ckpt_freq)
            if sharded is not None:
                job, terminal, state_index, dag_meta = sharded
                return job, terminal, state_index, dag_meta, True
        if isinstance(plan, UnaryPlan):
            job = StreamingJob(
                plan.reader, plan.fragment, name,
                checkpoint_frequency=ckpt_freq,
                checkpoint_store=self.checkpoint_store,
            )
            terminal = plan.fragment.executors[plan.mv_index]
            return job, terminal, (plan.mv_index,), None, True
        return self._build_dag_job(plan, name, ckpt_freq)

    # -- DAG jobs: joins, cascades, shared upstreams ---------------------
    def _ensure_dag(self, entry: CatalogEntry) -> tuple[DagJob, int]:
        """Upgrade an MV's job to a DagJob in place (states preserved) so
        downstream MVs can attach; returns (job, materialize node id).

        Ref: the reference's jobs are always graph-shaped; here linear
        jobs use the leaner StreamingJob until something taps them."""
        job = entry.job
        if isinstance(job, DagJob):
            # sharded join jobs attach downstream nodes per-shard (the
            # whole DAG runs inside one shard_map; the caller validates
            # the new chain is per-key-safe before mutating anything)
            return job, entry.mv_state_index[0]
        if not isinstance(job, StreamingJob):
            raise PlanError(
                f"MV-on-MV over {type(job).__name__} (sharded upstream): "
                "next round"
            )
        src_name = f"_src_{entry.name}"
        dag = DagJob(
            {src_name: job.source},
            [FragNode(job.fragment, ("source", src_name))],
            name=job.name,
            checkpoint_frequency=job.checkpoint_frequency,
            checkpoint_store=job.checkpoint_store,
        )
        dag.states = (job.states,)
        dag.epoch = job.epoch
        dag.barriers_seen = job.barriers_seen
        dag.committed_epoch = job.committed_epoch
        dag.maintenance_interval = job.maintenance_interval
        dag.snapshot_interval = job.snapshot_interval
        # the checkpoint pipeline migrates with the job: the uploader
        # queue (FIFO, same job name) keeps in-flight epochs ordered
        # ahead of the reseed below; the shadow is dropped — the state
        # tree changed shape, the reseed re-bases it
        dag.sealed_epoch = job.sealed_epoch
        dag._uploader = job._uploader
        dag.upload_window = job.upload_window
        dag.metrics = job.metrics
        if hasattr(job, "vnode_gate_idx"):
            # a partitioned upstream keeps its scale-plane identity
            # through the upgrade: the gate now lives inside node 0's
            # fragment, the checkpoint lineage and vnode ownership
            # carry over, and future repartitions drive the DagJob
            # handover path
            dag.vnode_gates = [(0, job.vnode_gate_idx)]
            dag.n_vnodes = job.n_vnodes
            dag.vnodes = job.vnodes
            dag.ckpt_key = job.ckpt_key
            dag.shuffle_cols = dict(getattr(job, "shuffle_cols", {}))
            dag.edge_kinds = dict(getattr(job, "edge_kinds", {}))
        self.jobs[self.jobs.index(job)] = dag
        entry.job = dag
        entry.mv_state_index = (0,) + tuple(entry.mv_state_index)
        entry.dag_nodes = [0]
        entry.dag_sources = [src_name]
        # retained checkpoints hold the StreamingJob-shaped state tree;
        # re-snapshot so recover() sees the DagJob shape (not during
        # bootstrap replay: states are fresh, the durable checkpoint
        # already holds the final-topology state)
        if not self._replaying:
            dag.reseed_checkpoint()
        return dag, 0

    # -- batch serving over snapshots -----------------------------------
    def _serve_batch(self, select: ast.Select):
        """Serving reads through the SAME compiled executor pipeline as
        streaming — scan → filter → project → agg → join over one-shot
        bounded snapshot sources, jit-cached per query shape.

        Ref: the reference's batch engine (src/batch/src/executor/
        mod.rs:46) + local execution mode (scheduler/local.rs:60).  The
        TPU-first twist: batch IS streaming over bounded input — the
        planner's dataflow runs to completion on a snapshot, so serving
        semantics can never drift from the device kernels (the old
        interpreted `_serve_agg` path re-implemented SQL in host
        Python; it is gone)."""
        import dataclasses

        key = repr(select)
        if not hasattr(self, "_serving_cache"):
            self._serving_cache: dict = {}
        hit = self._serving_cache.get(key)
        if hit is None:
            stripped = dataclasses.replace(
                select, order_by=(), limit=None, offset=None
            )
            plan = self.planner.plan(stripped)
            if isinstance(plan, UnaryPlan):
                plan = DagPlan(
                    sources={"_in": plan.reader},
                    nodes=[FragNode(plan.fragment, ("source", "_in"))],
                    mv_node=0, mv_index=plan.mv_index,
                )
            readers: dict[str, Any] = {}
            for name, r in plan.sources.items():
                if isinstance(r, MvTap):
                    readers[name] = _SnapshotReader(
                        self, self.catalog.get(r.name)
                    )
                elif hasattr(r, "pending"):
                    readers[name] = r  # bounded (table-history cursor)
                else:
                    raise PlanError(
                        "serving reads over unbounded sources: create "
                        "a materialized view instead"
                    )
            job = DagJob(readers, plan.nodes, "_serve",
                         checkpoint_frequency=1, checkpoint_store=None)
            job.snapshot_interval = 1 << 30  # no commits: one-shot
            terminal = plan.nodes[plan.mv_node].fragment.executors[
                plan.mv_index
            ]
            hit = (job, plan, terminal, readers)
            self._serving_cache[key] = hit
        job, plan, terminal, readers = hit
        # fresh state + fresh snapshot every execution; the COMPILED
        # programs persist in the job (static shapes)
        job.states = job._init_states()
        for r in readers.values():
            if hasattr(r, "reset"):
                r.reset()
            else:
                r.offset = 0  # table cursor rewinds over shared history
        for _ in range(1 << 20):
            if not any(r.pending() for r in readers.values()):
                break
            job.chunk_round()
        job.inject_barrier()  # flush + drain emissions
        job.inject_barrier()  # residual drains (maintenance pass)
        st = job.states[plan.mv_node][plan.mv_index]
        rows = terminal.to_host(st)
        schema = terminal.in_schema
        keep = [i for i, f in enumerate(schema)
                if not f.name.startswith("_hidden_")]
        self._last_columns = [schema[i].name for i in keep]
        self._last_fields = [schema[i] for i in keep]
        rows = [tuple(r[i] for i in keep) for r in rows]
        out_schema = Schema(tuple(schema[i] for i in keep))
        return self._host_order_limit(rows, select, out_schema)

    def _host_order_limit(self, rows: list, select: ast.Select,
                          schema: Schema) -> list:
        """ORDER BY (output columns) / LIMIT / OFFSET on host rows."""
        if select.order_by:
            for oi in reversed(select.order_by):
                e = oi.expr
                if isinstance(e, ast.ColumnRef):
                    i = schema.index_of(e.name)
                elif isinstance(e, ast.Literal) and e.type_name == "int":
                    if not 1 <= e.value <= len(schema):
                        raise PlanError(
                            f"ORDER BY position {e.value} is not in "
                            f"the select list (1..{len(schema)})"
                        )
                    i = e.value - 1  # ORDER BY <position>
                else:
                    raise PlanError(
                        "serving ORDER BY supports output columns"
                    )
                rows.sort(
                    key=lambda r: (r[i] is None, r[i]),
                    reverse=oi.descending,
                )
        if select.offset:
            rows = rows[select.offset:]
        if select.limit is not None:
            rows = rows[:select.limit]
        return rows

    def _mv_snapshot_chunk(self, entry: CatalogEntry):
        """The upstream MV's current rows as ONE insert chunk (device-
        resident — backfill never leaves HBM).  Ref: arrangement
        backfill reads the upstream state table's snapshot."""
        import jax.numpy as jnp

        from risingwave_tpu.stream.materialize import (
            AppendOnlyMaterialize,
            MaterializeExecutor,
        )

        st = entry.job.states
        for i in entry.mv_state_index:
            st = st[i]
        ex = entry.mv_executor
        mesh = getattr(entry.job, "mesh", None)
        if mesh is not None:
            # sharded upstream: the snapshot is one STACKED chunk
            # ([shard, cap, ...] leaves) consumed by backfill_node's
            # shard_map program — each shard replays its own partition
            import jax as _jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            n_shards = entry.job.n_shards
            if isinstance(ex, MaterializeExecutor):
                valid = st.table.occupied
                cap = ex.table_size
            elif isinstance(ex, AppendOnlyMaterialize):
                valid = jnp.arange(ex.ring_size, dtype=jnp.int64)[
                    None, :] < st.cursor[:, None]
                cap = ex.ring_size
            else:
                raise PlanError("cannot backfill from a sink")
            chunk = Chunk(
                tuple(st.values),
                jnp.zeros((n_shards, cap), jnp.int8),
                valid,
                ex.in_schema,
            )
            return _jax.device_put(
                chunk, NamedSharding(mesh, P(entry.job.AXIS))
            )
        if isinstance(ex, MaterializeExecutor):
            valid = st.table.occupied
            cap = ex.table_size
            vn_set, n_vn = self._mv_vnode_set(entry)
            if vn_set is not None:
                valid = self._vnode_filtered_mv_state(
                    st, vn_set, n_vn
                ).table.occupied
        elif isinstance(ex, AppendOnlyMaterialize):
            valid = jnp.arange(ex.ring_size, dtype=jnp.int64) < st.cursor
            cap = ex.ring_size
        else:
            raise PlanError("cannot backfill from a sink")
        return Chunk(
            tuple(st.values),
            jnp.zeros((cap,), jnp.int8),  # all inserts
            valid,
            ex.in_schema,
        )

    def _build_dag_job(self, plan: DagPlan, name: str, ckpt_freq: int):
        import dataclasses

        taps = {n: r for n, r in plan.sources.items()
                if isinstance(r, MvTap)}
        if not taps:
            # deep multiway plans run STAGED: per-node dispatches with
            # host-driven join drains — fused drain loops embed each
            # join's downstream subgraph and XLA compile memory blows
            # up around 4+ chained joins (TPC-H q2/q8/q9)
            n_joins = sum(isinstance(n, JoinNode) for n in plan.nodes)
            job = DagJob(
                plan.sources, plan.nodes, name,
                checkpoint_frequency=ckpt_freq,
                checkpoint_store=self.checkpoint_store,
                staged=n_joins >= 4,
            )
            self._prime_temporal_builds(job, range(len(job.nodes)))
            terminal = plan.nodes[plan.mv_node].fragment.executors[
                plan.mv_index
            ]
            return job, terminal, (plan.mv_node, plan.mv_index), \
                (list(range(len(plan.nodes))), list(plan.sources)), True

        # validate every tap BEFORE mutating any live job: a failure
        # mid-attach would otherwise leave half-merged jobs behind
        for sname, tap in taps.items():
            entry = self.catalog.get(tap.name)
            if not isinstance(entry.job, (DagJob, StreamingJob)):
                raise PlanError(
                    f"MV-on-MV over {type(entry.job).__name__} (sharded "
                    "upstream): next round"
                )
        mesh_jobs = {
            self.catalog.get(tap.name).job
            for tap in taps.values()
            if getattr(self.catalog.get(tap.name).job, "mesh", None)
            is not None
        }
        exchange_specs: dict[int, list] = {}
        if mesh_jobs:
            exchange_specs = self._plan_mesh_attach(
                plan, taps, mesh_jobs
            )
        part_jobs = {
            self.catalog.get(tap.name).job
            for tap in taps.values()
            if getattr(self.catalog.get(tap.name).job,
                       "n_vnodes", None) is not None
        }
        if part_jobs:
            self._plan_partition_attach(plan, taps)

        # attach: resolve every tap to its upstream job's MV node
        tap_refs: dict[str, int] = {}
        tap_entries: dict[str, CatalogEntry] = {}
        target: DagJob | None = None
        for sname, tap in taps.items():
            entry = self.catalog.get(tap.name)
            ujob, unode = self._ensure_dag(entry)
            if target is None:
                target = ujob
            elif ujob is not target:
                target = self._merge_dag_jobs(target, ujob)
            tap_entries[sname] = entry
        # tap node ids read after all merges (merges remap them)
        for sname, tap in taps.items():
            tap_refs[sname] = self.catalog.get(tap.name).mv_state_index[0]

        base = len(target.nodes)
        src_rename: dict[str, str] = {}
        for sname, reader in plan.sources.items():
            if sname in taps:
                continue
            new_name = sname
            i = 1
            while new_name in target.sources:
                new_name = f"{sname}_{i}"
                i += 1
            src_rename[sname] = new_name
            target.add_source(new_name, reader)

        def remap(ref):
            kind, key = ref
            if kind == "node":
                return ("node", base + key)
            if key in tap_refs:
                return ("node", tap_refs[key])
            return ("source", src_rename[key])

        rewritten = []
        for n in plan.nodes:
            if isinstance(n, FragNode):
                rewritten.append(dataclasses.replace(
                    n, input=remap(n.input)
                ))
            else:
                rewritten.append(dataclasses.replace(
                    n, left=remap(n.left), right=remap(n.right)
                ))
        ids = target.add_nodes(rewritten)

        # sharded attach: mark the derived exchange edges BEFORE any
        # backfill/step program compiles — the snapshot replay and the
        # live changelog cross the same all_to_all (dag._exchange)
        for pi, specs in exchange_specs.items():
            for side, key_fn in specs:
                target.exchanges[(ids[pi], side)] = key_fn
        if exchange_specs:
            target._rebuild()

        # backfill: every NEW input slot that consumes a tapped MV
        # replays its current snapshot before going live (device-side,
        # one chunk).  Per input SLOT, not per tap — a self-join of one
        # MV taps it on both sides and each side backfills exactly once
        # (left before right: the right pass probes the filled left
        # side, producing the complete snapshot x snapshot join).
        tap_by_node = {tap_refs[s]: e for s, e in tap_entries.items()}
        snapshots: dict[int, Any] = {}

        def snap_for(tap_node: int):
            if tap_node not in snapshots:
                snapshots[tap_node] = self._mv_snapshot_chunk(
                    tap_by_node[tap_node]
                )
            return snapshots[tap_node]

        for nid in ids:
            node = target.nodes[nid]
            if isinstance(node, FragNode):
                slots = [(node.input, None)]
            else:
                slots = [(node.left, "left"), (node.right, "right")]
            for ref, side in slots:
                if ref[0] == "node" and ref[1] in tap_by_node:
                    target.backfill_node(
                        nid, [snap_for(ref[1])], side=side
                    )

        self._prime_temporal_builds(target, ids)
        if not self._replaying:
            target.reseed_checkpoint()
        terminal = rewritten[plan.mv_node].fragment.executors[plan.mv_index]
        return target, terminal, (ids[plan.mv_node], plan.mv_index), \
            (ids, list(src_rename.values())), False

    def _plan_mesh_attach(self, plan: DagPlan, taps: dict,
                          mesh_jobs: set) -> dict[int, list]:
        """MV-on-MV over SHARDED join jobs: derive the device hash
        exchange each attached node needs (ROADMAP multi-device item).

        The attached nodes run per-shard inside the upstream's
        shard_map.  A per-key-safe chain (project/filter/materialize)
        stays shard-local — a joined row's changelog always lands on
        the shard owning its join key.  Cross-shard shapes no longer
        raise; they get an ``all_to_all`` exchange on the attach edge
        (keyed by the same ``hash64``/crc32 vnode mix as every other
        exchange) so rows re-route to their new key owners:

        - HashAgg over REDUCED keys → exchange on its group-by keys
          (every group lands whole on one shard);
        - global agg / global TopN (no keys) → constant-key exchange
          to ONE owning shard (the reference's singleton fragment);
        - grouped TopN → exchange on its partition keys;
        - a new JoinNode (join of two sharded MVs; their mesh jobs
          merge first) → exchange per side on its equi keys.

        Still raising: un-sharded/new sources mixed in, temporal
        joins, shapes whose keys are not evaluable on the attach-edge
        chunk (a projection ahead of a keyed stateful op), and
        executors outside the gated set.

        Returns ``{plan_node_id: [(side, key_fn)]}`` (side None for a
        FragNode input edge)."""
        from risingwave_tpu.parallel.exchange import single_shard_keys
        from risingwave_tpu.stream.executor import (
            FilterExecutor as _F,
            ProjectExecutor as _P,
        )
        from risingwave_tpu.stream.hash_agg import (
            HashAggExecutor as _A,
        )
        from risingwave_tpu.stream.materialize import (
            AppendOnlyMaterialize as _AOM,
            MaterializeExecutor as _M,
        )
        from risingwave_tpu.stream.temporal_join import (
            TemporalJoinExecutor as _TJ,
        )
        from risingwave_tpu.stream.top_n import GroupTopNExecutor as _T

        if any(
            getattr(self.catalog.get(t.name).job, "mesh", None) is None
            for t in taps.values()
        ):
            raise PlanError(
                "MV-on-MV joining a sharded job with an un-sharded "
                "job: next round"
            )
        if len({j.n_shards for j in mesh_jobs}) > 1:
            raise PlanError(
                "MV-on-MV joining sharded jobs of different "
                "parallelism: next round"
            )
        if len(taps) != len(plan.sources):
            raise PlanError(
                "MV-on-MV over a sharded join job cannot add new "
                "sources: next round"
            )

        specs: dict[int, list] = {}
        for i, n in enumerate(plan.nodes):
            if isinstance(n, JoinNode):
                if isinstance(n.join, _TJ):
                    raise PlanError(
                        "temporal join over a sharded job (build side "
                        "replicates, not partitions): next round"
                    )
                specs[i] = [
                    ("left", lambda c, ks=n.join.left_keys:
                        _join_exchange_keys(ks, c)),
                    ("right", lambda c, ks=n.join.right_keys:
                        _join_exchange_keys(ks, c)),
                ]
                continue
            execs = n.fragment.executors
            stateful = [ex for ex in execs
                        if not isinstance(ex, (_F, _P, _M, _AOM))]
            if not stateful:
                continue  # per-key-safe chain: stays shard-local
            if len(stateful) > 1:
                raise PlanError(
                    "MV-on-MV over a sharded job with more than one "
                    "keyed operator per fragment: next round"
                )
            ex = stateful[0]
            pos = execs.index(ex)
            if isinstance(ex, _A):
                keyed = bool(ex.group_by)
                key_fn = (
                    (lambda c, a=ex: [e.eval(c) for _, e in a.group_by])
                    if keyed else single_shard_keys
                )
            elif isinstance(ex, _T):
                keyed = bool(ex.group_by)
                key_fn = (
                    (lambda c, t=ex: [k.eval(c) for k in t.group_by])
                    if keyed else single_shard_keys
                )
            else:
                raise PlanError(
                    "MV-on-MV over a sharded job supports project/"
                    "filter/materialize chains, aggs, TopN, and joins "
                    f"(got {type(ex).__name__}): next round"
                )
            # a KEYED op's keys evaluate on the attach-edge chunk:
            # only filters may precede it (they preserve the schema);
            # an unkeyed (constant-route) op tolerates any per-key-
            # safe prefix — the exchange does not read columns
            if keyed and any(not isinstance(p, _F)
                             for p in execs[:pos]):
                raise PlanError(
                    "MV-on-MV over a sharded job: a projection ahead "
                    "of a keyed agg/TopN (keys not evaluable on the "
                    "attach edge): next round"
                )
            specs[i] = [(None, key_fn)]
        return specs

    def _plan_partition_attach(self, plan: DagPlan,
                               taps: dict) -> None:
        """MV-on-MV over a vnode-PARTITIONED upstream: the worker-
        topology analog of ``_plan_mesh_attach``, compiled against the
        cluster exchange plane.  The attach edge's exchange must be
        the IDENTITY choreography (``ExchangeSpec.mode="local"``):
        every keyed state the new chain adds must key on the
        upstream's distribution value, so each partition's changelog
        already lives on its owner and no cross-worker row movement is
        needed — the cheapest exchange there is.  Concretely, every
        attached HashAgg's LEADING group-by key and every attached
        Materialize's LEADING pk column must trace (through plain
        InputRef hops, including through earlier attached aggs' group
        keys) back to the upstream MV's leading pk column.  Reduced-
        key aggs, TopN, joins of partitioned MVs, and new sources
        raise ``PlanError`` — a true cross-partition data exchange on
        the attach edge is the next round."""
        from risingwave_tpu.expr.node import InputRef
        from risingwave_tpu.stream.executor import (
            FilterExecutor as _F,
            ProjectExecutor as _P,
        )
        from risingwave_tpu.stream.hash_agg import HashAggExecutor
        from risingwave_tpu.stream.materialize import (
            MaterializeExecutor,
        )

        if len(taps) != 1 or len(plan.sources) != len(taps):
            raise PlanError(
                "MV-on-MV over a partitioned upstream supports "
                "exactly one upstream MV and no new sources: "
                "next round"
            )
        (tap_sname, tap), = taps.items()
        up_entry = self.catalog.get(tap.name)
        up_pk0 = up_entry.mv_executor.pk_indices[0]

        def trace_edge(ref, col) -> "int | None":
            """Trace a column on edge ``ref`` back to the tap source
            column (None = untraceable)."""
            while ref[0] == "node":
                node = plan.nodes[ref[1]]
                if isinstance(node, JoinNode):
                    return None
                idx = int(col)
                for ex in reversed(node.fragment.executors):
                    if isinstance(ex, (_F, MaterializeExecutor)):
                        continue
                    if isinstance(ex, _P):
                        if idx >= len(ex.exprs):
                            return None
                        e = ex.exprs[idx][1]
                        if not isinstance(e, InputRef):
                            return None
                        idx = e.index
                    elif isinstance(ex, HashAggExecutor):
                        # agg output = group keys ++ agg values; only
                        # a group-key column traces through
                        if idx >= len(ex.group_by):
                            return None
                        e = ex.group_by[idx][1]
                        if not isinstance(e, InputRef):
                            return None
                        idx = e.index
                    else:
                        return None
                col = idx
                ref = node.input
            return int(col) if ref == ("source", tap_sname) else None

        def trace_in_node(ni: int, pos: int, col) -> "int | None":
            """Trace ``col`` on the input edge of executor ``pos`` of
            node ``ni`` back to the tap source column."""
            node = plan.nodes[ni]
            idx = int(col)
            for ex in reversed(node.fragment.executors[:pos]):
                if isinstance(ex, (_F, MaterializeExecutor)):
                    continue
                if isinstance(ex, _P):
                    if idx >= len(ex.exprs):
                        return None
                    e = ex.exprs[idx][1]
                    if not isinstance(e, InputRef):
                        return None
                    idx = e.index
                elif isinstance(ex, HashAggExecutor):
                    if idx >= len(ex.group_by):
                        return None
                    e = ex.group_by[idx][1]
                    if not isinstance(e, InputRef):
                        return None
                    idx = e.index
                else:
                    return None
            return trace_edge(node.input, idx)

        for ni, node in enumerate(plan.nodes):
            if isinstance(node, JoinNode):
                raise PlanError(
                    "MV-on-MV joining a partitioned upstream: a "
                    "cross-partition join-key exchange on the attach "
                    "edge is the next round"
                )
            for pos, ex in enumerate(node.fragment.executors):
                if isinstance(ex, (_F, _P, MaterializeExecutor)):
                    if isinstance(ex, MaterializeExecutor):
                        k = ex.pk_indices[0]
                        e = trace_in_node(ni, pos, k)
                        if e is None or e != up_pk0:
                            raise PlanError(
                                "MV-on-MV over a partitioned "
                                "upstream: the new MV's leading pk "
                                "column must carry the upstream "
                                "distribution key: next round"
                            )
                    continue
                if isinstance(ex, HashAggExecutor):
                    if (ex.emit_on_window_close or ex._distinct_aggs
                            or ex._minput_aggs
                            or ex.watermark_group_idx is not None):
                        raise PlanError(
                            "MV-on-MV over a partitioned upstream: "
                            "DISTINCT/minput/EOWC/watermark "
                            "aggregations are not scale-eligible"
                        )
                    if not ex.group_by:
                        raise PlanError(
                            "MV-on-MV over a partitioned upstream: a "
                            "global aggregation reduces across "
                            "partitions (attach-edge exchange): "
                            "next round"
                        )
                    e0 = ex.group_by[0][1]
                    if not isinstance(e0, InputRef):
                        raise PlanError(
                            "MV-on-MV over a partitioned upstream: "
                            "the leading group-by key must be a "
                            "plain column: next round"
                        )
                    traced = trace_in_node(ni, pos, e0.index)
                    if traced is None or traced != up_pk0:
                        raise PlanError(
                            "MV-on-MV over a partitioned upstream "
                            "with REDUCED keys needs a cross-"
                            "partition exchange on the attach edge: "
                            "next round"
                        )
                    continue
                raise PlanError(
                    "MV-on-MV over a partitioned upstream supports "
                    "project/filter/materialize chains and same-key "
                    f"aggs (got {type(ex).__name__}): next round"
                )

    @staticmethod
    def _agg_shard_safe(agg, node, plan: DagPlan) -> bool:
        """True when every group of ``agg`` is guaranteed shard-local:
        its fragment directly consumes a join node, only filters
        precede it (positions preserved), and its GROUP BY InputRefs
        cover the join's probe-side equi-key InputRefs (rows route by
        join key ⇒ group determines shard)."""
        from risingwave_tpu.expr.node import InputRef as _IR
        from risingwave_tpu.stream.executor import (
            FilterExecutor as _F,
        )
        from risingwave_tpu.stream.hash_agg import (
            HashAggExecutor as _A,
        )

        kind, key = node.input
        if kind != "node" or not isinstance(plan.nodes[key], JoinNode):
            return False
        join = plan.nodes[key].join
        # INNER only: an outer join's NULL-padded rows live on the
        # UNMATCHED side's shard, not the shard of the (NULL) group
        # key — the NULL group would split across shards
        if getattr(join, "join_type", None) != "inner":
            return False
        for ex in node.fragment.executors:
            if ex is agg:
                break
            if not isinstance(ex, _F):
                return False
        if not all(isinstance(k, _IR) for k in join.left_keys):
            return False
        group_idx = {
            g.index for _, g in agg.group_by if isinstance(g, _IR)
        }
        jk = {k.index for k in join.left_keys}
        if not jk <= group_idx:
            return False
        # only ONE shard-safe agg per chain (a second agg over reduced
        # keys could merge groups across shards)
        return all(
            not isinstance(ex2, _A) or ex2 is agg
            for ex2 in node.fragment.executors
        )

    def _prime_temporal_builds(self, job: DagJob, node_ids) -> None:
        """Drain each temporal join's build-side source BEFORE any
        probe chunk flows: the build table must reflect the table's
        full current state at MV creation (ref temporal_join.rs reads
        the upstream table's storage directly; this local copy
        backfills instead)."""
        from risingwave_tpu.stream.temporal_join import (
            TemporalJoinExecutor,
        )

        for nid in node_ids:
            node = job.nodes[nid]
            if not (isinstance(node, JoinNode)
                    and isinstance(node.join, TemporalJoinExecutor)):
                continue
            ref = node.right
            while ref[0] == "node":
                n2 = job.nodes[ref[1]]
                if isinstance(n2, FragNode):
                    ref = n2.input
                else:
                    break  # joins feeding a temporal build: leave as-is
            if ref[0] != "source":
                continue
            r = job.sources.get(ref[1])
            for _ in range(1 << 16):
                if not (hasattr(r, "pending") and r.pending() > 0):
                    break
                job.run_chunk(ref[1])

    def _merge_dag_jobs(self, a: DagJob, b: DagJob) -> DagJob:
        """Fuse job ``b`` into ``a`` (a join of MVs living in different
        jobs): sources and nodes move over with remapped ids; catalog
        entries follow.  Two SHARDED jobs merge too (a join of two
        sharded MVs): equal-parallelism meshes span the same devices,
        so ``b``'s stacked states drop into ``a``'s mesh unchanged and
        its exchange edges remap with its node ids."""
        if (a.mesh is None) != (b.mesh is None):
            raise PlanError(
                "MV-on-MV joining a sharded job with an un-sharded "
                "job: next round"
            )
        if a.mesh is not None and a.n_shards != b.n_shards:
            raise PlanError(
                "MV-on-MV joining sharded jobs of different "
                "parallelism: next round"
            )
        offset = len(a.nodes)
        rename: dict[str, str] = {}
        for sname, reader in b.sources.items():
            new_name = sname
            i = 1
            while new_name in a.sources:
                new_name = f"{sname}_{i}"
                i += 1
            rename[sname] = new_name
            a.sources[new_name] = reader

        import dataclasses

        def remap(ref):
            kind, key = ref
            if kind == "node":
                return ("node", offset + key)
            return ("source", rename[key])

        moved = []
        for n in b.nodes:
            if n is None:
                moved.append(None)
            elif isinstance(n, FragNode):
                moved.append(dataclasses.replace(n, input=remap(n.input)))
            else:
                moved.append(dataclasses.replace(
                    n, left=remap(n.left), right=remap(n.right)
                ))
        a.nodes.extend(moved)
        a.states = tuple(list(a.states) + list(b.states))
        for (i, side), fn in b.exchanges.items():
            a.exchanges[(offset + i, side)] = fn
        a._rebuild()
        for entry in self.catalog.list():
            if entry.job is b:
                entry.job = a
                entry.mv_state_index = (
                    offset + entry.mv_state_index[0],
                ) + tuple(entry.mv_state_index[1:])
                if entry.dag_nodes is not None:
                    entry.dag_nodes = [offset + i for i in entry.dag_nodes]
        if b in self.jobs:
            self.jobs.remove(b)
        return a

    def _try_sharded_job(self, plan, name: str, par: int, ckpt_freq: int):
        import jax
        from risingwave_tpu.stream.executor import (
            FilterExecutor as _F,
            HopWindowExecutor as _H,
            ProjectExecutor as _P,
        )
        from risingwave_tpu.stream.hash_agg import HashAggExecutor as _A
        from risingwave_tpu.stream.sharded import (
            ShardedJob,
            ShardedStreamingJob,
            make_mesh,
        )

        reader = plan.reader
        if not (hasattr(reader, "impl") and hasattr(reader, "next_base")):
            return None
        from risingwave_tpu.stream.materialize import (
            AppendOnlyMaterialize as _AOM,
            MaterializeExecutor as _M,
        )

        from risingwave_tpu.stream.watermark import (
            WatermarkFilterExecutor as _W,
        )

        execs = plan.fragment.executors
        agg_idx = None
        for i, ex in enumerate(execs):
            if isinstance(ex, _A):
                if agg_idx is not None:
                    return None
                agg_idx = i
        if agg_idx is None:
            return None
        # prefix: stateless ops + watermark filters (each shard filters
        # its own substream; barrier-time pmin aligns the global
        # watermark — ShardedJob._wm_pass)
        prefix = execs[:agg_idx]
        if any(not isinstance(ex, (_F, _H, _P, _W)) for ex in prefix):
            return None
        # suffix after the agg: per-key-safe operators, plus a GLOBAL
        # TopN (group_by == []) — each shard keeps its own top-k band,
        # a guaranteed superset of the global top-k, and the serving
        # read applies the final order+limit over the merged shards
        # (ref: per-actor TopN + singleton merge, executor/top_n/; the
        # merge here rides the serving boundary instead of a singleton
        # fragment).  Sinks stay linear (host delivery ordering).
        from risingwave_tpu.stream.sink import SinkExecutor as _SK
        from risingwave_tpu.stream.top_n import GroupTopNExecutor as _T
        topn_spec = None
        has_sink = False
        for ex in execs[agg_idx + 1:]:
            if isinstance(ex, _T) and not ex.group_by \
                    and ex.rank_alias is None:
                topn_spec = (ex.order_by, ex.limit, ex.offset)
                continue
            if isinstance(ex, _SK):
                # per-shard ring cursors; host merge delivery at the
                # snapshot barrier (ShardedStreamingJob._deliver_sinks)
                has_sink = True
                continue
            if not isinstance(ex, (_F, _P, _M, _AOM)):
                return None
        if topn_spec is not None and has_sink:
            # a sink must see the GLOBAL band, not per-shard bands
            return None
        agg = execs[agg_idx]
        n = min(par, len(jax.devices()))
        if n < 2:
            return None
        mesh = make_mesh(n)
        # two-phase aggregation: a stateless in-chunk partial agg before
        # the exchange collapses duplicate keys, shrinking all_to_all
        # volume (ref §2.3 item 4 — local partial -> hash exchange ->
        # global combine)
        from risingwave_tpu.expr.node import InputRef as _IR
        from risingwave_tpu.stream.partial_agg import (
            TWO_PHASE_KINDS,
            PartialAggExecutor,
            translated_global_calls,
        )

        local_execs = list(prefix)
        keyed_execs = list(execs[agg_idx:])
        exchange_key_fn = lambda c: [e.eval(c) for _, e in agg.group_by]
        # two-phase is retraction-unsafe (partial min/max ignore signs;
        # global row_count counts partial rows) — append-only plans only
        if plan.append_only and all(
            a.kind in TWO_PHASE_KINDS and a.filter is None
            and not a.distinct
            for a in agg.aggs
        ):
            partial = PartialAggExecutor(
                agg.in_schema, agg.group_by, agg.aggs
            )
            n_keys = len(agg.group_by)
            global_agg = type(agg)(
                partial.out_schema,
                [(nm, _IR(i))
                 for i, (nm, _) in enumerate(agg.group_by)],
                translated_global_calls(agg.aggs, n_keys),
                table_size=agg.table_size,
                emit_capacity=agg.emit_capacity,
                # group-key positions are identical in the partial
                # output, so window cleaning/EOWC carry over directly
                watermark_group_idx=agg.watermark_group_idx,
                watermark_lag=agg.watermark_lag,
                watermark_src_col=agg.watermark_src_col,
                emit_on_window_close=agg.emit_on_window_close,
            )
            local_execs = local_execs + [partial]
            keyed_execs = [global_agg] + list(execs[agg_idx + 1:])
            exchange_key_fn = (
                lambda c, k=n_keys: [c.column(i) for i in range(k)]
            )
        # spill-to-host draining isn't wired for the sharded runtime
        # yet: overflow stays a loud error there (next round: per-shard
        # rings drained via a gathered readback)
        for ex in keyed_execs:
            if getattr(ex, "spill_ring", 0):
                ex.spill_ring = 0
        if topn_spec is not None:
            # per-shard band must cover GLOBAL rank offset+limit (a
            # globally rank-o row may rank 0 on its shard)
            order_by, limit, offset = topn_spec
            keyed_execs = [
                _T(ex.in_schema, group_by=[], order_by=ex.order_by,
                   limit=limit + offset, offset=0,
                   pool_size=ex.pool_size,
                   emit_capacity=ex.emit_capacity,
                   append_only=ex.append_only)
                if isinstance(ex, _T) and not ex.group_by else ex
                for ex in keyed_execs
            ]
        sharded = ShardedJob(
            mesh,
            source_fn=reader.impl,
            chunk_capacity=reader.cap,
            local_executors=local_execs,
            exchange_key_fn=exchange_key_fn,
            keyed_executors=keyed_execs,
        )
        job = ShardedStreamingJob(
            sharded, reader, name,
            checkpoint_frequency=ckpt_freq,
            checkpoint_store=self.checkpoint_store,
        )
        # index into the SHARDED executor list (the two-phase rewrite
        # inserts a partial agg, shifting positions vs the linear plan)
        terminal = keyed_execs[-1]
        if topn_spec is not None:
            # the serving read applies the GLOBAL order+limit over the
            # merged per-shard bands
            terminal.serving_topn = topn_spec
        return job, terminal, (len(local_execs) + len(keyed_execs) - 1,)

    def _try_sharded_dag_plan(self, plan: DagPlan, name: str, par: int,
                              ckpt_freq: int):
        """Shard a join-shaped DAG plan over the device mesh.

        Ref: every stateful fragment is vnode-parallel with hash
        exchanges on its inputs (src/meta/src/stream/stream_graph/
        actor.rs:435, dispatch.rs:949).  Here: the whole DAG runs
        per-shard inside one shard_map, with all_to_all exchanges on
        each join input edge routing rows by that side's equi keys.
        Join OUTPUT rows stay shard-local for the downstream
        materialize — a joined row's stream key contains its join key,
        so a given key's changelog always lands on the owning shard.

        Eligible: traceable sources (no MvTaps), stateless(+watermark)
        prefixes, joins, and a per-key-safe post chain (project/filter/
        materialize — no sinks/TopN, which need host delivery or global
        order)."""
        import jax
        from risingwave_tpu.stream.executor import (
            FilterExecutor as _F,
            HopWindowExecutor as _H,
            ProjectExecutor as _P,
        )
        from risingwave_tpu.stream.materialize import (
            AppendOnlyMaterialize as _AOM,
            MaterializeExecutor as _M,
        )
        from risingwave_tpu.stream.sharded import make_mesh
        from risingwave_tpu.stream.watermark import (
            WatermarkFilterExecutor as _W,
        )

        if any(isinstance(r, MvTap) for r in plan.sources.values()):
            return None
        # traceable sources generate per-shard inside the program;
        # host-chunk sources (DML tables) enter on shard 0 and re-route
        # at the first exchange edge — both shard
        joins = [i for i, n in enumerate(plan.nodes)
                 if isinstance(n, JoinNode)]
        if not joins:
            return None
        from risingwave_tpu.stream.temporal_join import (
            TemporalJoinExecutor as _TJ,
        )
        if any(isinstance(plan.nodes[i].join, _TJ) for i in joins):
            # temporal build tables replicate, not partition: meshless
            return None
        join_inputs: set = set()
        for i in joins:
            join_inputs.add(plan.nodes[i].left)
            join_inputs.add(plan.nodes[i].right)
        for i, n in enumerate(plan.nodes):
            if isinstance(n, JoinNode):
                continue
            if ("node", i) in join_inputs or n.input[0] == "source":
                # pre-join prefix: stateless + watermark filters
                if any(not isinstance(ex, (_F, _H, _P, _W))
                       for ex in n.fragment.executors):
                    return None
            else:
                # post-join chain: per-key-safe only.  A HashAgg is
                # per-key-safe when its GROUP BY keys cover the
                # upstream join's equi keys (rows are routed by join
                # key, so every such group lives on one shard)
                from risingwave_tpu.stream.hash_agg import (
                    HashAggExecutor as _A,
                )
                for ex in n.fragment.executors:
                    if isinstance(ex, (_F, _P, _M, _AOM)):
                        continue
                    if isinstance(ex, _A) and self._agg_shard_safe(
                            ex, n, plan):
                        continue
                    return None
        n = min(par, len(jax.devices()))
        if n < 2:
            return None
        exchanges = {}
        for i in joins:
            join = plan.nodes[i].join
            exchanges[(i, "left")] = (
                lambda c, ks=join.left_keys: _join_exchange_keys(ks, c)
            )
            exchanges[(i, "right")] = (
                lambda c, ks=join.right_keys: _join_exchange_keys(ks, c)
            )
        job = DagJob(
            plan.sources, plan.nodes, name,
            checkpoint_frequency=ckpt_freq,
            checkpoint_store=self.checkpoint_store,
            mesh=make_mesh(n),
            exchanges=exchanges,
        )
        terminal = plan.nodes[plan.mv_node].fragment.executors[
            plan.mv_index
        ]
        return job, terminal, (plan.mv_node, plan.mv_index), \
            (list(range(len(plan.nodes))), list(plan.sources))

    def _create_mview(self, stmt: ast.CreateMaterializedView):
        from risingwave_tpu.stream.materialize import AppendOnlyMaterialize

        if stmt.name in self.catalog:
            # checked BEFORE building: _build_job mutates live shared
            # jobs (attach/merge), which must not happen for a
            # doomed-to-fail duplicate
            if stmt.if_not_exists:
                return None
            raise ValueError(f"{stmt.name!r} already exists")
        self._refresh_dml_widths()
        self.planner.parallel_hint = int(
            self.session_config.get("streaming_parallelism")
        )
        plan = self.planner.plan(stmt.query,
                                 eowc=stmt.emit_on_window_close)
        job, mv_exec, state_index, dag_meta, is_new = self._build_job(
            plan, stmt.name
        )
        entry = CatalogEntry(
            stmt.name, "mview", mv_exec.in_schema,
            job=job, mv_executor=mv_exec, mv_state_index=state_index,
            append_only=isinstance(mv_exec, AppendOnlyMaterialize),
            dag_nodes=dag_meta[0] if dag_meta else None,
            dag_sources=dag_meta[1] if dag_meta else None,
            stream_key=list(getattr(mv_exec, "pk_indices", [])) or None,
            ttl=self._mv_ttl_option(stmt, mv_exec),
            definition=self._definition_text(stmt),
        )
        self.catalog.create(entry)
        if is_new:
            self.jobs.append(job)
        return None

    @staticmethod
    def _mv_ttl_option(stmt: ast.CreateMaterializedView, mv_exec):
        """Validate WITH (ttl = '<n>') at CREATE time: retention in
        units of the LEADING export-pk column, which must be an
        int-family NOT NULL column (the expiry horizon is one
        memcomparable byte bound — strings/floats/nullable keys have
        no sound integer horizon)."""
        opts = dict(stmt.with_options or {})
        ttl_raw = opts.pop("ttl", None)
        if opts:
            bad = sorted(opts)[0]
            raise ValueError(
                f"unknown materialized-view option {bad!r} "
                "(supported: ttl)"
            )
        if ttl_raw is None:
            return None
        try:
            ttl = int(str(ttl_raw))
        except ValueError:
            raise ValueError(
                f"ttl must be an integer, got {ttl_raw!r}"
            ) from None
        if ttl <= 0:
            raise ValueError("ttl must be positive")
        schema = mv_exec.in_schema
        pk = list(getattr(mv_exec, "pk_indices", ()))
        if not pk:
            raise ValueError(
                "WITH (ttl = ...) needs a materialized view with a "
                "primary key (the horizon tracks the leading pk "
                "column)"
            )
        f = schema[pk[0]]
        if f.data_type.is_string or f.data_type == DataType.DECIMAL \
                or f.data_type in (DataType.FLOAT32, DataType.FLOAT64) \
                or f.nullable:
            raise ValueError(
                f"WITH (ttl = ...) needs an int-family NOT NULL "
                f"leading pk column (got {f.name!r}: "
                f"{f.data_type.value})"
            )
        return (f.name, ttl)

    def _create_index(self, stmt: ast.CreateIndex):
        """``CREATE INDEX ix ON mv(col, ...)``: a small secondary-index
        MV — ``SELECT col..., <upstream pk>... FROM mv`` maintained
        through the ordinary MV-on-MV attach path — whose EXPORT key
        is ``(col..., upstream pk)``, so the shared serving keyspace
        sorts its rows by the indexed columns and a serving replica
        answers ``WHERE col = x`` with one contiguous index range scan
        plus pk point-gets instead of a full scan (ref: the frontend's
        index selection over index TableCatalogs)."""
        from risingwave_tpu.stream.materialize import AppendOnlyMaterialize

        if stmt.name in self.catalog:
            if stmt.if_not_exists:
                return None
            raise ValueError(f"{stmt.name!r} already exists")
        upstream = self.catalog.get(stmt.table)
        if upstream.kind != "mview":
            raise ValueError(
                f"{stmt.table!r} is not a materialized view"
            )
        if not upstream.stream_key:
            raise ValueError(
                f"CREATE INDEX on {stmt.table!r}: append-only MVs "
                "have no stream key to index"
            )
        by_name = {f.name: i for i, f in enumerate(upstream.schema)}
        for c in stmt.columns:
            if c not in by_name:
                raise ValueError(
                    f"column {c!r} does not exist in {stmt.table!r}"
                )
        ix_cols = [by_name[c] for c in stmt.columns]
        pk_cols = list(upstream.stream_key)
        items, used = [], set()
        for j, i in enumerate(ix_cols + pk_cols):
            base = upstream.schema[i].name
            alias = base if base not in used else f"_idx{j}_{base}"
            used.add(alias)
            items.append(
                ast.SelectItem(ast.ColumnRef(base), alias)
            )
        query = ast.Select(tuple(items), ast.TableRef(stmt.table))
        self._refresh_dml_widths()
        self.planner.parallel_hint = int(
            self.session_config.get("streaming_parallelism")
        )
        plan = self.planner.plan(query)
        job, mv_exec, state_index, dag_meta, is_new = self._build_job(
            plan, stmt.name
        )
        entry = CatalogEntry(
            stmt.name, "mview", mv_exec.in_schema,
            job=job, mv_executor=mv_exec, mv_state_index=state_index,
            append_only=isinstance(mv_exec, AppendOnlyMaterialize),
            dag_nodes=dag_meta[0] if dag_meta else None,
            dag_sources=dag_meta[1] if dag_meta else None,
            stream_key=list(getattr(mv_exec, "pk_indices", []))
            or None,
            index_on=(stmt.table, tuple(stmt.columns)),
            export_pk=tuple(range(len(ix_cols) + len(pk_cols))),
            definition=self._definition_text(stmt),
        )
        self.catalog.create(entry)
        if is_new:
            self.jobs.append(job)
        # the upstream's serve-schema doc must advertise the index
        self._schema_published.discard(stmt.table)
        return None

    def _create_sink(self, stmt: ast.CreateSink):
        from risingwave_tpu.connector.sinks import create_sink

        if stmt.name in self.catalog:
            if stmt.if_not_exists:
                return None
            raise ValueError(f"{stmt.name!r} already exists")
        if stmt.query is not None:
            query = stmt.query
        else:
            query = ast.Select(
                (ast.SelectItem(ast.Star(), None),),
                ast.TableRef(stmt.from_rel),
            )
        sink = create_sink(stmt.with_options)
        self._refresh_dml_widths()
        self.planner.parallel_hint = int(
            self.session_config.get("streaming_parallelism")
        )
        plan = self.planner.plan(query, sink=sink)
        job, sink_exec, _, dag_meta, is_new = self._build_job(
            plan, stmt.name
        )
        entry = CatalogEntry(
            stmt.name, "sink", sink_exec.in_schema,
            job=job, mv_executor=sink_exec,
            dag_nodes=dag_meta[0] if dag_meta else None,
            dag_sources=dag_meta[1] if dag_meta else None,
            definition=self._definition_text(stmt),
        )
        self.catalog.create(entry)
        if is_new:
            self.jobs.append(job)
        return None

    # -- the global barrier loop ----------------------------------------
    def tick(self, barriers: int = 1,
             chunks_per_barrier: int | None = None) -> None:
        """Advance every streaming job (meta's PeriodicBarriers analog)."""
        if chunks_per_barrier is None:
            chunks_per_barrier = int(
                self.system_params.get("chunks_per_barrier")
            )
        # runtime-mutable cadence (ref ALTER SYSTEM SET applies live)
        ckpt_freq = int(self.system_params.get("checkpoint_frequency"))
        maint = int(self.system_params.get(
            "maintenance_interval_checkpoints"
        ))
        snap_iv = int(self.system_params.get(
            "snapshot_interval_checkpoints"
        ))
        stall_hook = self._storage_stall_hook \
            if self.hummock is not None else None
        upload_window = int(self.system_params.get(
            "checkpoint_upload_window"
        ))
        for _ in range(barriers):
            for job in self.jobs:
                job.checkpoint_frequency = ckpt_freq
                job.maintenance_interval = maint
                job.snapshot_interval = snap_iv
                job.upload_window = upload_window
                if getattr(job, "metrics", None) is None:
                    job.metrics = self.metrics
                if hasattr(job, "write_stall_hook"):
                    job.write_stall_hook = stall_hook
                t0 = time.perf_counter()
                if hasattr(job, "run_chunks"):
                    # traceable sources batch the whole inter-barrier
                    # window into one dispatch (q1 host-overhead fix)
                    rows = job.run_chunks(chunks_per_barrier)
                else:
                    rows = 0
                    for _ in range(chunks_per_barrier):
                        rows += job.chunk_round()
                t1 = time.perf_counter()
                job.inject_barrier()
                t2 = time.perf_counter()
                self.metrics.inc("stream_rows_total", rows, job=job.name)
                self._observe_barrier(job.name, t2 - t0,
                                      dispatch=t1 - t0, seal=t2 - t1)
        # batch boundary = durability point: uploads sealed inside the
        # window pipelined against the barrier loop; they must land
        # before tick() returns (tests/FLUSH/restart determinism).
        # Cluster workers are driven via tick_job instead — there the
        # seal/ack split is the meta's global protocol.
        for job in self.jobs:
            if hasattr(job, "drain_uploads"):
                job.drain_uploads()
            self._export_checkpoint_gauges(job)

    def tick_job(self, name: str, chunks_per_barrier: int = 1,
                 source_limits: dict | None = None) -> int:
        """Advance ONE job a single barrier round (the cluster worker's
        barrier RPC — meta drives each job's rounds individually so a
        reassigned job can catch up while the rest hold).  Returns the
        job's committed epoch after the barrier.

        ``source_limits`` (cluster scale plane) fences DML-table
        consumption at a meta-chosen history position so every
        partition of the job consumes the identical prefix this round
        — source cursors stay aligned across workers, which is what
        makes checkpoint-slice handover exact."""
        job = self._job_by_name(name)
        if source_limits:
            self._apply_source_limits(job, source_limits)
        ckpt_freq = int(self.system_params.get("checkpoint_frequency"))
        job.checkpoint_frequency = ckpt_freq
        job.maintenance_interval = int(self.system_params.get(
            "maintenance_interval_checkpoints"
        ))
        job.snapshot_interval = int(self.system_params.get(
            "snapshot_interval_checkpoints"
        ))
        job.upload_window = int(self.system_params.get(
            "checkpoint_upload_window"
        ))
        if getattr(job, "metrics", None) is None:
            job.metrics = self.metrics
        t0 = time.perf_counter()
        with GLOBAL_TRACE.span("dispatch", job=name) as _sp:
            if hasattr(job, "run_chunks"):
                rows = job.run_chunks(chunks_per_barrier)
            else:
                rows = 0
                for _ in range(chunks_per_barrier):
                    rows += job.chunk_round()
            _sp.set(rows=rows)
        t1 = time.perf_counter()
        fenced = bool(source_limits) \
            and getattr(job, "n_vnodes", None) is not None
        if fenced:
            # Exchange-lite: a partitioned barrier consumes EXACTLY to
            # the round fence, however many chunks that takes — every
            # partition's cursor seals ON the fence, so handover
            # cursor checks hold even though shuffled partitions see
            # different owned-row densities.  (Bounded: pending() is
            # capped by min(local history, fence).)
            with GLOBAL_TRACE.span("source_drain", job=name):
                for _ in range(1 << 20):
                    if not self._fenced_pending(job):
                        break
                    rows += job.run_chunks(chunks_per_barrier) \
                        if hasattr(job, "run_chunks") \
                        else job.chunk_round()
        t2 = time.perf_counter()
        with GLOBAL_TRACE.span("seal", job=name):
            job.inject_barrier()
        t3 = time.perf_counter()
        self.metrics.inc("stream_rows_total", rows, job=job.name)
        self._observe_barrier(
            job.name, t3 - t0, dispatch=t1 - t0,
            source_drain=(t2 - t1) if fenced else None,
            seal=t3 - t2,
        )
        self._export_checkpoint_gauges(job)
        # the SEAL, not the durable commit: the cluster's global epoch
        # advances only when every job's upload acks (meta polls
        # job_epochs) — the per-job barrier RPC never blocks on I/O
        return getattr(job, "sealed_epoch", job.committed_epoch)

    #: rolling window feeding the spike-ratio gauge; ~128 barriers of
    #: history keeps the median stable while a 1-in-100 spike still
    #: lands in the p99 seat
    _SPIKE_WINDOW = 128
    #: below this many observations the ratio is noise, not signal
    _SPIKE_MIN_SAMPLES = 8

    def _observe_barrier(self, job_name: str, dt: float,
                         **phases) -> None:
        """Barrier-latency attribution: the total histogram, per-phase
        histograms (``barrier_phase_seconds{job,phase}``), and the
        rolling tail gauge ``barrier_spike_ratio{job}`` = p99/median
        over the last window — the number the tail-latency gates
        (``cluster_stress --assert`` / ``profile_q8 --assert``) bound.
        Quantiles here are exact over the window (sorted host floats),
        not histogram-bucket bounds: a spike ratio of 1.0 must mean
        a genuinely flat tail, not two latencies in one bucket."""
        self.metrics.observe("barrier_latency_seconds", dt,
                             job=job_name)
        for phase, secs in phases.items():
            if secs is None:
                continue
            self.metrics.observe("barrier_phase_seconds", secs,
                                 job=job_name, phase=phase)
        lat = self._barrier_lat.get(job_name)
        if lat is None:
            lat = self._barrier_lat[job_name] = deque(
                maxlen=self._SPIKE_WINDOW)
        lat.append(dt)
        if len(lat) >= self._SPIKE_MIN_SAMPLES:
            s = sorted(lat)
            med = s[len(s) // 2]
            p99 = s[min(len(s) - 1, int(0.99 * len(s)))]
            self.metrics.set_gauge(
                "barrier_spike_ratio", p99 / max(med, 1e-9),
                job=job_name,
            )

    def _retire_job_series(self, job_name: str) -> None:
        """DROP retires the job's whole scrape footprint: every series
        labeled ``job=<name>`` — barrier latency/phase histograms,
        spike ratio, join gauges, checkpoint gauges — the way the
        cluster meta retires a dead worker's per-worker series.
        Without this, a dropped MV's gauges linger forever."""
        self.metrics.remove_where(job=job_name)
        self._barrier_lat.pop(job_name, None)

    def _export_checkpoint_gauges(self, job) -> None:
        """Cheap (no device sync) checkpoint-pipeline gauges."""
        self.metrics.set_gauge("committed_epoch", job.committed_epoch,
                               job=job.name)
        sealed = getattr(job, "sealed_epoch", job.committed_epoch)
        self.metrics.set_gauge("sealed_epoch", sealed, job=job.name)
        self.metrics.set_gauge(
            "checkpoint_seal_lag_epochs",
            max(0, sealed - job.committed_epoch), job=job.name,
        )
        if hasattr(job, "upload_queue_depth"):
            self.metrics.set_gauge(
                "checkpoint_upload_queue_depth",
                job.upload_queue_depth(), job=job.name,
            )
        up = getattr(job, "_uploader", None)
        if up is not None:
            self.metrics.set_gauge("checkpoint_uploads_total",
                                   up.uploads_total, job=job.name)
            self.metrics.set_gauge("checkpoint_upload_seconds_total",
                                   up.upload_seconds_total,
                                   job=job.name)
            self.metrics.set_gauge("checkpoint_upload_stall_seconds_total",
                                   up.stall_seconds_total, job=job.name)

    def job_epochs(self, name: str) -> dict:
        """Seal-vs-durable positions of one job (the cluster meta polls
        this to decide when a round's uploads have all acked).  Also
        services the job's pending acks — the worker's barrier loop
        only runs when meta drives it, so durable progress must be
        observable between rounds."""
        job = self._job_by_name(name)
        if hasattr(job, "_process_upload_acks"):
            job._process_upload_acks()
        return {
            "sealed": getattr(job, "sealed_epoch", job.committed_epoch),
            "durable": job.committed_epoch,
            "upload_queue": job.upload_queue_depth()
            if hasattr(job, "upload_queue_depth") else 0,
        }

    def drain_uploads(self) -> None:
        """Flush every job's checkpoint-upload queue (orderly stop)."""
        for job in self.jobs:
            if hasattr(job, "drain_uploads"):
                job.drain_uploads()

    def collect_checkpoint_metrics(self) -> None:
        """Snapshot-pipeline observability requiring a device readback
        (dirty-block ratio) — on-demand like collect_join_metrics; the
        steady loop never calls it."""
        for job in self.jobs:
            self._export_checkpoint_gauges(job)
            shadow = getattr(job, "_shadow", None)
            if shadow is not None:
                self.metrics.set_gauge(
                    "snapshot_dirty_block_ratio",
                    shadow.dirty_ratio(), job=job.name,
                )
                self.metrics.set_gauge(
                    "snapshot_shadow_blocks", shadow.total_blocks,
                    job=job.name,
                )
            if hasattr(job, "stall_seconds"):
                self.metrics.set_gauge(
                    "checkpoint_stall_seconds_total",
                    job.stall_seconds, job=job.name,
                )

    def _job_by_name(self, name: str):
        for job in self.jobs:
            if job.name == name:
                return job
        raise ValueError(f"unknown streaming job {name!r}")

    def recover(self) -> None:
        """Restore every job from its last committed checkpoint
        (ref §3.5: meta-driven recovery across all streaming jobs)."""
        for job in self.jobs:
            job.recover()

    # -- cluster job export / adoption ----------------------------------
    def export_job_ddl(self, name: str) -> list[str]:
        """The DDL statements that recreate one MV/sink's job on a
        fresh engine: every source/table definition (in catalog order —
        cheap and closed over any FROM reference), then the entry's own
        definition.  The meta service ships exactly this shape when it
        places or reassigns a job."""
        entry = self.catalog.get(name)
        ddls = [e.definition for e in self.catalog.list("source")
                if e.definition]
        if entry.definition:
            ddls.append(entry.definition)
        return ddls

    def adopt_job(self, ddl: list[str], name: str,
                  recover: bool = True) -> int:
        """Replay a shipped job's DDL, skipping objects this engine
        already has (a survivor adopting its second job reuses its
        sources), then recover the job from the last durable
        checkpoint — state AND source cursors rewind to the same
        commit, so replay is exact.  Returns the recovered committed
        epoch (0 = fresh job, nothing durable yet)."""
        from risingwave_tpu.sql.parser import parse_with_text

        for sql in ddl:
            for text, stmt in parse_with_text(sql):
                nm = getattr(stmt, "name", None)
                if isinstance(stmt, (ast.CreateSource,
                                     ast.CreateMaterializedView,
                                     ast.CreateIndex,
                                     ast.CreateSink)) \
                        and nm in self.catalog:
                    continue
                if isinstance(stmt, ast.CreateFunction) \
                        and nm in self.functions:
                    continue
                if isinstance(stmt, ast.DropStatement) \
                        and nm not in self.catalog:
                    continue  # dropped before this worker ever saw it
                self.execute(text)
        entry = self.catalog.get(name)
        if entry.job is None:
            raise ValueError(f"{name!r} did not produce a streaming job")
        if recover:
            entry.job.recover()
        # adoption moves the MV export diff base: whatever this engine
        # exported in a previous ownership is stale against the shared
        # manifest — re-seed from storage on the next export
        self._exported.clear()
        return entry.job.committed_epoch

    # -- elastic scale plane (cluster/scale) -----------------------------
    def _job_sources(self, job) -> list:
        """Every source reader of a job (one for a linear StreamingJob,
        the sources dict for a DagJob)."""
        if hasattr(job, "sources"):
            return list(job.sources.values())
        src = getattr(job, "source", None)
        return [src] if src is not None else []

    def _table_of_reader(self, reader) -> str | None:
        rows = getattr(reader, "_rows", None)
        if rows is None:
            return None
        for e in self.catalog.list("source"):
            if e.dml is not None and rows is e.dml._history:
                return e.name
        return None

    def _dml_tables_of(self, job) -> list[str]:
        """Names of the DML tables this job's sources read (the tables
        the cluster exchanges worker↔worker so partitions see aligned
        streams)."""
        out: list[str] = []
        for src in self._job_sources(job):
            t = self._table_of_reader(src)
            if t is not None and t not in out:
                out.append(t)
        return out

    def _apply_source_limits(self, job, limits: dict) -> None:
        for src in self._job_sources(job):
            if not hasattr(src, "limit"):
                continue
            tbl = self._table_of_reader(src)
            if tbl is not None and tbl in limits:
                src.limit = int(limits[tbl])

    def _fenced_pending(self, job) -> int:
        """Unconsumed positions below the round fence across the job's
        fenced sources — a partitioned barrier drives this to ZERO so
        every partition's cursor lands exactly ON the fence (stronger
        than the PR-7 identical-consumption-math alignment, and the
        property that keeps shuffled cursors equal even though each
        partition's owned-row density differs)."""
        return sum(
            src.pending() for src in self._job_sources(job)
            if getattr(src, "limit", None) is not None
        )

    @staticmethod
    def _trace_input_col(prefix_execs, col: int) -> int | None:
        """Trace an output column of an executor chain back to an
        input column of the chain's first executor, or None when any
        hop is not a plain InputRef (the shuffle planner then degrades
        the edge to replicate mode — the gate still filters)."""
        from risingwave_tpu.expr.node import InputRef
        from risingwave_tpu.stream.executor import (
            FilterExecutor,
            HopWindowExecutor,
            ProjectExecutor,
        )

        idx = int(col)
        for ex in reversed(list(prefix_execs)):
            if isinstance(ex, FilterExecutor):
                continue
            if isinstance(ex, HopWindowExecutor):
                # row expansion appends window_start; input columns
                # keep their positions
                if idx >= len(ex.in_schema):
                    return None
                continue
            if isinstance(ex, ProjectExecutor):
                if idx >= len(ex.exprs):
                    return None
                e = ex.exprs[idx][1]
                if not isinstance(e, InputRef):
                    return None
                idx = e.index
                continue
            return None
        return idx

    def _trace_source_col(self, prefix_execs, dist_expr) -> int | None:
        """Raw source-column index of a distribution-key expression
        evaluated AFTER ``prefix_execs`` (the shuffle key the ingest
        leader hashes), or None when untraceable."""
        from risingwave_tpu.expr.node import InputRef

        if not isinstance(dist_expr, InputRef):
            return None
        return self._trace_input_col(prefix_execs, dist_expr.index)

    def partition_job(self, name: str, n_vnodes: int,
                      ckpt_key: str) -> dict:
        """Rebuild a freshly-adopted job as ONE partition of a
        vnode-partitioned cluster job (the scale plane's unit):
        ``VnodeGateExecutor``s land on the keyed edges and mask rows
        to the owned vnode set; the checkpoint lineage moves to
        ``ckpt_key`` so every partition checkpoints independently in
        the SHARED store.

        Exchange-lite shapes (raises ``PlanError`` otherwise — the
        worker falls back to whole-job placement):

        - a linear ``StreamingJob`` carrying exactly one MV:
          stateless prefix → one ``HashAggExecutor`` → Materialize
          (gate before the agg, routed by the leading GROUP BY key);
        - a two-source JOIN ``DagJob``: source → gate per side (routed
          by that side's FIRST equi key) → hash join (rebuilt with
          dense retractable sides — sliceable whole-key buckets) →
          project/filter → Materialize whose LEADING pk column is the
          preserved side's join key (one hash domain for routing,
          state slicing, serving filters, and export seeding);
        - no DISTINCT / minput / EOWC / watermark-driven cleaning /
          temporal joins, and every routing key a NOT NULL
          integer-family value.

        The returned spec carries ``shuffle_cols`` — the raw source
        column each DML table routes by when every hop back from the
        key is a plain InputRef — which the meta's ``ExchangePlanner``
        compiles into the sliced-ingest choreography (untraceable keys
        degrade that table's edge to replicate mode)."""
        from risingwave_tpu.cluster.scale.gate import VnodeGateExecutor
        from risingwave_tpu.stream.executor import (
            FilterExecutor,
            HopWindowExecutor,
            ProjectExecutor,
        )
        from risingwave_tpu.stream.fragment import Fragment
        from risingwave_tpu.stream.hash_agg import HashAggExecutor
        from risingwave_tpu.stream.materialize import MaterializeExecutor

        entry = self.catalog.get(name)
        job = entry.job
        if hasattr(job, "vnode_gate_idx") or hasattr(job, "vnode_gates"):
            # already a partition on this engine (a restarted meta
            # re-adopting lineages): re-point the checkpoint lineage —
            # the caller's recover() then loads it
            if job.n_vnodes != n_vnodes:
                raise PlanError(
                    f"{name!r}: vnode ring mismatch "
                    f"({job.n_vnodes} vs {n_vnodes})"
                )
            job.ckpt_key = ckpt_key
            return {
                "partitioned": True,
                "dml_tables": self._dml_tables_of(job),
                "shuffle_cols": getattr(job, "shuffle_cols", {}),
                "edge_kinds": getattr(job, "edge_kinds", {}),
            }
        if entry.kind != "mview":
            raise PlanError(
                f"{name!r} is not a streaming MV: not scale-eligible"
            )
        if isinstance(job, DagJob):
            return self._partition_dag_job(entry, n_vnodes, ckpt_key)
        if not isinstance(job, StreamingJob):
            raise PlanError(
                f"{name!r} is not a linear streaming MV: not "
                "scale-eligible"
            )
        riders = [e for e in self.catalog.list() if e.job is job]
        if riders != [entry]:
            raise PlanError(
                f"{name!r} shares its job with other MVs/sinks: not "
                "scale-eligible"
            )
        if job.barriers_seen:
            raise PlanError(
                f"{name!r} already ran unpartitioned barriers: "
                "partitioning happens at adoption"
            )
        execs = list(job.fragment.executors)
        aggs = [i for i, ex in enumerate(execs)
                if isinstance(ex, HashAggExecutor)]
        if len(aggs) != 1 or not isinstance(execs[-1],
                                            MaterializeExecutor):
            raise PlanError(
                f"{name!r}: scale-eligible jobs are "
                "source → agg → materialize"
            )
        agg_idx = aggs[0]
        agg = execs[agg_idx]
        for ex in execs[:agg_idx]:
            if not isinstance(ex, (FilterExecutor, ProjectExecutor,
                                   HopWindowExecutor)):
                raise PlanError(
                    f"{name!r}: stateful/watermark prefix executor "
                    f"{type(ex).__name__}: not scale-eligible"
                )
        for ex in execs[agg_idx + 1:-1]:
            if not isinstance(ex, (FilterExecutor, ProjectExecutor)):
                raise PlanError(
                    f"{name!r}: post-agg executor {type(ex).__name__}: "
                    "not scale-eligible"
                )
        if (agg.emit_on_window_close or agg._distinct_aggs
                or agg._minput_aggs
                or agg.watermark_group_idx is not None):
            raise PlanError(
                f"{name!r}: DISTINCT/minput/EOWC/watermark "
                "aggregations are not scale-eligible"
            )
        dist_expr = agg.group_by[0][1]
        f = dist_expr.return_field(agg.in_schema)
        if f.nullable or not np.issubdtype(
                np.dtype(f.data_type.physical_dtype), np.integer):
            raise PlanError(
                f"{name!r}: distribution key {agg.group_by[0][0]!r} "
                "must be a NOT NULL integer-family column"
            )
        # spill-to-host draining is not wired for partitioned state
        # handover: overflow stays a loud error (the sharded mesh path
        # makes the same call)
        for ex in execs:
            if getattr(ex, "spill_ring", 0):
                ex.spill_ring = 0
        gate = VnodeGateExecutor(agg.in_schema, dist_expr, n_vnodes)
        frag = Fragment(execs[:agg_idx] + [gate] + execs[agg_idx:],
                        name=f"{name}_part")
        part = StreamingJob(
            job.source, frag, name,
            checkpoint_frequency=job.checkpoint_frequency,
            checkpoint_store=job.checkpoint_store,
        )
        part.maintenance_interval = job.maintenance_interval
        part.snapshot_interval = job.snapshot_interval
        part.metrics = job.metrics
        part.ckpt_key = ckpt_key
        part.vnode_gate_idx = agg_idx
        part.n_vnodes = n_vnodes
        part.vnodes = frozenset(range(n_vnodes))
        self.jobs[self.jobs.index(job)] = part
        entry.job = part
        entry.mv_state_index = (entry.mv_state_index[0] + 1,) \
            + tuple(entry.mv_state_index[1:])
        self._serving_cache = {}
        # exchange plan input: which raw source column each DML table
        # routes by (None/absent = untraceable → replicate edge)
        tables = self._dml_tables_of(part)
        src_col = self._trace_source_col(execs[:agg_idx], dist_expr)
        part.shuffle_cols = {t: src_col for t in tables} \
            if src_col is not None else {}
        part.edge_kinds = {t: "source" for t in tables}
        self._apply_reader_filters(part)
        return {
            "partitioned": True,
            "dist": agg.group_by[0][0],
            "dml_tables": tables,
            "shuffle_cols": part.shuffle_cols,
            "edge_kinds": part.edge_kinds,
        }

    def _partition_dag_job(self, entry: CatalogEntry, n_vnodes: int,
                           ckpt_key: str) -> dict:
        """Partition a two-source JOIN DagJob: gate each source edge by
        that side's FIRST equi-key vnode (equal join keys share their
        first column, so rows that can ever match co-locate), rebuild
        the join with DENSE retractable sides (whole-key bucket
        entries — the sliceable layout ``handover`` moves), and
        require the MV's leading pk column to carry the preserved
        side's join key so every keyed state in the tree slices,
        serves, and exports in ONE vnode hash domain."""
        from risingwave_tpu.cluster.scale.gate import VnodeGateExecutor
        from risingwave_tpu.expr.node import InputRef
        from risingwave_tpu.stream.dag import FragNode, JoinNode
        from risingwave_tpu.stream.executor import (
            FilterExecutor,
            ProjectExecutor,
        )
        from risingwave_tpu.stream.fragment import Fragment
        from risingwave_tpu.stream.hash_join import HashJoinExecutor
        from risingwave_tpu.stream.materialize import MaterializeExecutor

        name = entry.name
        job = entry.job
        riders = [e for e in self.catalog.list() if e.job is job]
        if riders != [entry]:
            raise PlanError(
                f"{name!r} shares its job with other MVs/sinks: not "
                "scale-eligible"
            )
        if job.barriers_seen:
            raise PlanError(
                f"{name!r} already ran unpartitioned barriers: "
                "partitioning happens at adoption"
            )
        if getattr(job, "mesh", None) is not None or job.staged:
            raise PlanError(
                f"{name!r}: sharded/staged DAGs do not partition "
                "across workers yet (mesh×vnode composition is the "
                "next round)"
            )
        live = [(i, n) for i, n in enumerate(job.nodes)
                if n is not None]
        if len(live) != 2 or not isinstance(live[0][1], JoinNode) \
                or not isinstance(live[1][1], FragNode):
            raise PlanError(
                f"{name!r}: partitioned DAGs are source ⋈ source → "
                "materialize: not scale-eligible"
            )
        jn = live[0][1]
        frag_node = live[1][1]
        join = jn.join
        if not isinstance(join, HashJoinExecutor):
            raise PlanError(
                f"{name!r}: only hash equi-joins partition (got "
                f"{type(join).__name__}): not scale-eligible"
            )
        if join.join_type == "full_outer":
            raise PlanError(
                f"{name!r}: FULL OUTER join has no always-non-NULL "
                "routing column: not scale-eligible"
            )
        if join.left_clean is not None or join.right_clean is not None:
            raise PlanError(
                f"{name!r}: watermark-cleaned join state is not "
                "sliceable: not scale-eligible"
            )
        if jn.left[0] != "source" or jn.right[0] != "source" \
                or jn.left == jn.right:
            raise PlanError(
                f"{name!r}: join sides must read two distinct "
                "sources directly: not scale-eligible"
            )
        if frag_node.input != ("node", live[0][0]):
            raise PlanError(
                f"{name!r}: materialize must consume the join: not "
                "scale-eligible"
            )
        for ks, schema in ((join.left_keys, join.left_schema),
                           (join.right_keys, join.right_schema)):
            k0 = ks[0]
            if not isinstance(k0, InputRef):
                raise PlanError(
                    f"{name!r}: first join key must be a plain "
                    "column: not scale-eligible"
                )
            f = k0.return_field(schema)
            if f.nullable or not np.issubdtype(
                    np.dtype(f.data_type.physical_dtype), np.integer):
                raise PlanError(
                    f"{name!r}: routing key {f.name!r} must be a "
                    "NOT NULL integer-family column"
                )
        execs = list(frag_node.fragment.executors)
        mats = [i for i, ex in enumerate(execs)
                if isinstance(ex, MaterializeExecutor)]
        if len(mats) != 1 or mats[0] != len(execs) - 1 or any(
                not isinstance(ex, (FilterExecutor, ProjectExecutor))
                for ex in execs[:-1]):
            raise PlanError(
                f"{name!r}: post-join chain must be project/filter → "
                "materialize: not scale-eligible"
            )
        mv = execs[-1]
        # the MV's LEADING pk column must carry the preserved side's
        # join key — that one value is the row's vnode identity for
        # state slicing, serving filters, and export seeding
        left_pos = join.left_keys[0].index
        if join.emit_pairs:
            right_pos = len(join.left_schema) \
                + join.right_keys[0].index
        else:  # semi/anti: output is the preserved side alone
            right_pos = join.right_keys[0].index
        allowed = set()
        if join.join_type == "inner":
            allowed = {left_pos, right_pos}
        elif join.preserve_left:
            allowed = {left_pos}
        else:
            allowed = {right_pos}
        traced = self._trace_input_col(execs[:-1], mv.pk_indices[0])
        if traced is None or traced not in allowed:
            raise PlanError(
                f"{name!r}: the MV's leading pk column must be the "
                "preserved side's join key: not scale-eligible"
            )
        # rebuild the join with DENSE (sliceable) sides; pool sides
        # bump-allocate a shared row pool whose (hash, rank) tags do
        # not slice by key
        dense = HashJoinExecutor(
            join.left_schema, join.right_schema,
            join.left_keys, join.right_keys,
            table_size=join.table_size,
            left_bucket_cap=join.left_bucket_cap,
            right_bucket_cap=join.right_bucket_cap,
            left_table_size=join.left_table_size,
            right_table_size=join.right_table_size,
            out_capacity=join.out_capacity,
            join_type=join.join_type,
            left_storage="dense", right_storage="dense",
        )
        gate_l = VnodeGateExecutor(
            join.left_schema, list(join.left_keys), n_vnodes
        )
        gate_r = VnodeGateExecutor(
            join.right_schema, list(join.right_keys), n_vnodes
        )
        lname, rname = jn.left[1], jn.right[1]
        for ex in execs:
            if getattr(ex, "spill_ring", 0):
                ex.spill_ring = 0
        part = DagJob(
            dict(job.sources),
            [
                FragNode(Fragment([gate_l], name=f"{name}_gate_l"),
                         ("source", lname)),
                FragNode(Fragment([gate_r], name=f"{name}_gate_r"),
                         ("source", rname)),
                JoinNode(dense, ("node", 0), ("node", 1)),
                FragNode(Fragment(execs, name=f"{name}_part"),
                         ("node", 2)),
            ],
            name=job.name,
            checkpoint_frequency=job.checkpoint_frequency,
            checkpoint_store=job.checkpoint_store,
        )
        part.maintenance_interval = job.maintenance_interval
        part.snapshot_interval = job.snapshot_interval
        part.metrics = getattr(job, "metrics", None)
        part.ckpt_key = ckpt_key
        part.vnode_gates = [(0, 0), (1, 0)]
        part.n_vnodes = n_vnodes
        part.vnodes = frozenset(range(n_vnodes))
        self.jobs[self.jobs.index(job)] = part
        entry.job = part
        entry.mv_state_index = (3, len(execs) - 1)
        entry.dag_nodes = [0, 1, 2, 3]
        self._serving_cache = {}
        # shuffle plan: each side's table routes by its own key column
        part.shuffle_cols = {}
        for src_name, keys in ((lname, join.left_keys),
                               (rname, join.right_keys)):
            tbl = self._table_of_reader(part.sources[src_name])
            if tbl is not None:
                part.shuffle_cols[tbl] = keys[0].index
        part.edge_kinds = {t: "join" for t in part.shuffle_cols}
        self._apply_reader_filters(part)
        return {
            "partitioned": True,
            "dist": join.left_schema[left_pos].name,
            "dml_tables": self._dml_tables_of(part),
            "shuffle_cols": part.shuffle_cols,
            "edge_kinds": part.edge_kinds,
        }

    def set_job_vnodes(self, name: str, vnodes) -> None:
        """Swap the partition's owned-vnode mask (STATE, not code: the
        compiled fragment programs never retrace).  The gate's dropped
        counter rides along untouched — it audits the whole life of
        the partition, not one ownership."""
        import jax.numpy as jnp

        def _with_mask(gate, old_state):
            dropped = old_state[1] if isinstance(old_state, tuple) \
                else jnp.zeros((), jnp.int64)
            return (gate.make_mask(job.vnodes), dropped)

        entry = self.catalog.get(name)
        job = entry.job
        job.vnodes = frozenset(int(v) for v in vnodes)
        if hasattr(job, "vnode_gates"):
            states = list(job.states)
            for ni, ei in job.vnode_gates:
                gate = job.nodes[ni].fragment.executors[ei]
                node_states = list(states[ni])
                node_states[ei] = _with_mask(gate, node_states[ei])
                states[ni] = tuple(node_states)
            job.states = tuple(states)
        else:
            gi = job.vnode_gate_idx
            gate = job.fragment.executors[gi]
            states = list(job.states)
            states[gi] = _with_mask(gate, states[gi])
            job.states = tuple(states)
        self._apply_reader_filters(job)

    def apply_shuffle_plan(self, tables: dict) -> None:
        """Install the pushed choreography's per-table shuffle spec —
        ``{table: {"key_col", "n_vnodes", "mode"}}`` — and refresh
        every partitioned job's reader filters against it.  Called by
        the worker on every routing push."""
        self._shuffle_tables = {
            t: e for t, e in (tables or {}).items()
            if e.get("mode") == "shuffle"
            and e.get("key_col") is not None
        }
        for job in self.jobs:
            if getattr(job, "n_vnodes", None) is not None:
                self._apply_reader_filters(job)

    def _apply_reader_filters(self, job) -> None:
        """Point the job's DML readers at its owned vnode set on every
        shuffled table (the reader packs chunks with owned rows only —
        the gate downstream is the assert)."""
        plan = getattr(self, "_shuffle_tables", None) or {}
        own = getattr(job, "vnodes", None)
        for src in self._job_sources(job):
            if not hasattr(src, "vnode_filter"):
                continue
            tbl = self._table_of_reader(src)
            spec = plan.get(tbl)
            # the job's own traced key must agree with the pushed plan
            # (planner compiles from the same spec, but stay paranoid)
            mine = getattr(job, "shuffle_cols", {}).get(tbl)
            if spec is None or own is None or mine is None \
                    or int(spec["key_col"]) != int(mine):
                src.vnode_filter = None
                continue
            src.vnode_filter = (
                int(spec["key_col"]),
                frozenset(int(v) for v in own),
                int(spec["n_vnodes"]),
            )

    def table_consumption_floor(self, table: str) -> int:
        """Lowest unconsumed history position across this engine's
        readers of one DML table — positions below it are never read
        again, so the worker's fence completeness audit starts here
        instead of rescanning the whole history every round."""
        entry = self.catalog.get(table) if table in self.catalog \
            else None
        if entry is None or entry.dml is None:
            return 0
        floors = [
            src.offset
            for job in self.jobs
            for src in self._job_sources(job)
            if getattr(src, "_rows", None) is entry.dml._history
        ]
        return min(floors) if floors else 0

    def partition_stats(self) -> dict:
        """Per-partitioned-job observability: owned vnodes, the
        device gate-drop audit counters, and reader-side filtered-row
        counts (one device readback per gate — off the hot path)."""
        out: dict = {}
        for job in self.jobs:
            if getattr(job, "n_vnodes", None) is None:
                continue
            dropped = 0
            if hasattr(job, "vnode_gates"):
                for ni, ei in job.vnode_gates:
                    st = job.states[ni][ei]
                    if isinstance(st, tuple):
                        dropped += int(np.asarray(st[1]))
            elif hasattr(job, "vnode_gate_idx"):
                st = job.states[job.vnode_gate_idx]
                if isinstance(st, tuple):
                    dropped += int(np.asarray(st[1]))
            out[job.name] = {
                "vnodes": sorted(job.vnodes),
                "gate_dropped": dropped,
                "reader_filtered": sum(
                    getattr(s, "filtered_rows", 0)
                    for s in self._job_sources(job)
                ),
                "shuffle_cols": dict(getattr(job, "shuffle_cols", {})),
            }
        return out

    def repartition_job(self, name: str, vnodes, transfers: list,
                        rewind_epoch: int | None = None) -> dict:
        """Apply one handover step to this worker's partition: rewind
        to the handover epoch if the partition ran ahead (uncommitted
        round), evict stale entries in the gained vnodes, transplant
        each donor's checkpoint slice, then swap the owned mask.

        ``transfers``: ``[{"ckpt": donor_lineage, "epoch": e,
        "vnodes": [...]}]`` — the slices are read from the SHARED
        checkpoint store; only moved vnodes' entries leave disk."""
        from risingwave_tpu.cluster.scale.handover import (
            clear_job_vnodes,
            slice_job_states,
            transplant_job,
        )
        from risingwave_tpu.stream.runtime import restore_source

        entry = self.catalog.get(name)
        job = entry.job
        if not hasattr(job, "vnode_gate_idx") \
                and not hasattr(job, "vnode_gates"):
            raise PlanError(f"{name!r} is not a partitioned job")
        is_dag = isinstance(job, DagJob)
        if rewind_epoch is not None and (
                job.committed_epoch != rewind_epoch
                or job.sealed_epoch != rewind_epoch):
            job.recover(rewind_epoch)

        def _src_state():
            if is_dag:
                return {n: (s.state() if hasattr(s, "state") else {})
                        for n, s in job.sources.items()}
            return job.source.state() \
                if hasattr(job.source, "state") else {}

        def _check_cursor(ours, donor) -> None:
            if ("offset" in ours and "offset" in donor
                    and ours["offset"] != donor["offset"]):
                raise RuntimeError(
                    f"handover cursor mismatch for {name!r}: "
                    f"local {ours['offset']} vs donor "
                    f"{donor['offset']}"
                )

        stats = []
        cleared = 0
        if transfers:
            gained = sorted(
                set(int(v) for t in transfers for v in t["vnodes"])
            )
            job.states, cleared = clear_job_vnodes(
                job, job.states, gained, job.n_vnodes
            )
            fresh = job.barriers_seen == 0 and job.committed_epoch == 0
            for t in transfers:
                loaded = self.checkpoint_store.load(
                    t["ckpt"], int(t["epoch"])
                )
                if loaded is None:
                    raise RuntimeError(
                        f"donor checkpoint {t['ckpt']}@{t['epoch']} "
                        "not found in the shared store"
                    )
                _, d_states, d_src = loaded
                sl = slice_job_states(
                    job, d_states, t["vnodes"], job.n_vnodes
                )
                job.states, moved = transplant_job(
                    job, job.states, sl
                )
                if fresh:
                    # all donors sealed the same round at the same
                    # fence: any donor's cursor is THE cursor of the
                    # handover epoch
                    if is_dag:
                        for sname, src in job.sources.items():
                            restore_source(src, d_src.get(sname, {}))
                    else:
                        restore_source(job.source, d_src)
                    fresh = False
                else:
                    ours = _src_state()
                    if is_dag:
                        for sname in job.sources:
                            _check_cursor(ours.get(sname, {}),
                                          d_src.get(sname, {}))
                    else:
                        _check_cursor(ours, d_src)
                stats.append({
                    "ckpt": t["ckpt"],
                    "vnodes": len(t["vnodes"]),
                    "entries": moved,
                })
        self.set_job_vnodes(name, vnodes)
        durable = 0
        if transfers and self.checkpoint_store is not None:
            # durably seal the POST-TRANSPLANT state under this
            # partition's lineage at its committed epoch (0 for a
            # fresh recipient): a recipient killed between the
            # transplant and its first post-handover seal would
            # otherwise re-adopt a lineage MISSING the moved vnodes'
            # state — the crash-mid-scale hole the scale_kill chaos
            # schedule proves closed
            self.checkpoint_store.invalidate(job.ckpt_key)
            self.checkpoint_store.save(
                job.ckpt_key, job.committed_epoch, job.states,
                _src_state(),
            )
            durable = job.committed_epoch
        # the export diff base is vnode-filtered: ownership changed, so
        # it re-seeds from the shared manifest on the next export
        self._exported.clear()
        return {"vnodes": len(job.vnodes), "cleared": cleared,
                "transfers": stats, "durable_epoch": durable}

    def _vnode_filtered_mv_state(self, st, vn_set, n_vn):
        """A materialize state narrowed to one vnode set: occupancy is
        masked by the stored leading-pk vnode, so stale slots (state a
        handover left behind) and co-owned rows never surface in reads
        or exports."""
        import jax.numpy as jnp

        from risingwave_tpu.cluster.scale.vnode import (
            vnode_member_mask,
            vnodes_of_ints,
        )
        from risingwave_tpu.state.hash_table import HashTable
        from risingwave_tpu.stream.materialize import MvState

        member = vnode_member_mask(vn_set, n_vn)
        key0 = st.table.key_cols[0]
        payload = key0.data if hasattr(key0, "null") else key0
        vn = vnodes_of_ints(payload, n_vn)
        occ = jnp.asarray(st.table.occupied) & member[vn]
        table = HashTable(st.table.key_cols, occ,
                          jnp.asarray(st.table.tombstone),
                          st.table.size)
        return MvState(table, st.values, st.overflow)

    def collect_join_metrics(self) -> None:
        """Export join-path observability into the Prometheus registry.

        ONE device readback per join node (gauges are snapshots, not
        stream counters), so this runs on demand — the scrape/ctl
        surface and tests call it; the steady-state loop never does
        (a sync readback stalls async dispatch; see bench.py).

        Gauges per join node:
        - ``join_probe_calls_per_chunk``: trace-time lookup_or_insert
          calls in the compiled update path (the fused (hash, rank)
          probe keeps this at 1 per append-only side);
        - ``join_probe_iters_per_chunk``: device probe-loop trips;
        - ``join_pool_occupancy``: bump-allocator fill of each pool
          side (live cursor / capacity);
        - ``join_emit_window_fill_ratio``: staged emission rows over
          drained window capacity (small = oversized out_capacity);
        - ``join_drain_windows_per_chunk``: emission windows per probe
          chunk (1 = no amplification re-dispatch).

        Plus ``dag_fused_fallback_total{reason}``: windows a DagJob
        could NOT run as one fused dispatch (staged plan, host-chunk
        source) — a silent degradation to per-chunk host dispatches is
        a throughput cliff, so it is counted per reason.

        Sharded jobs export the same gauges with counters SUMMED over
        the shard axis (chunks count per-shard pulls, so per-chunk
        ratios stay comparable to the linear job's).
        """
        import jax as _jax
        import numpy as _np

        from risingwave_tpu.stream.hash_join import PoolSideState

        for job in self.jobs:
            if not isinstance(job, DagJob):
                continue
            for reason, count in job.fused_fallbacks.items():
                self.metrics.set_gauge(
                    "dag_fused_fallback_total", count,
                    job=job.name, reason=reason,
                )
            n_shards = job.n_shards
            for idx, node in enumerate(job.nodes):
                if not isinstance(node, JoinNode):
                    continue
                jstate = job.states[idx]
                if not hasattr(jstate, "chunks"):
                    continue  # non-HashJoin two-input node
                labels = {"job": job.name, "node": str(idx)}
                chunks = max(int(_np.asarray(jstate.chunks).sum()), 1)
                self.metrics.set_gauge(
                    "join_probe_iters_per_chunk",
                    float(_np.asarray(jstate.probe_iters).sum())
                    / chunks,
                    **labels,
                )
                out_cap = node.join.out_capacity
                windows = max(
                    int(_np.asarray(jstate.emit_windows).sum()), 1
                )
                self.metrics.set_gauge(
                    "join_emit_window_fill_ratio",
                    float(_np.asarray(jstate.emit_rows).sum())
                    / (windows * out_cap),
                    **labels,
                )
                self.metrics.set_gauge(
                    "join_drain_windows_per_chunk",
                    windows / chunks, **labels,
                )
                for side_name in ("left", "right"):
                    s = getattr(jstate, side_name)
                    if not isinstance(s, PoolSideState):
                        continue
                    from risingwave_tpu.stream.hash_join import (
                        _pool_capacity,
                    )
                    rows0 = s.rows if job.mesh is None else \
                        _jax.tree.map(lambda x: x[0], s.rows)
                    self.metrics.set_gauge(
                        "join_pool_occupancy",
                        float(_np.asarray(s.pool_len).sum())
                        / (_pool_capacity(rows0) * n_shards),
                        side=side_name, **labels,
                    )

    def audit_join_probe_counts(self) -> dict:
        """Trace each join's append-only update path and record how
        many table probes the compiled program performs per chunk —
        the regression guard behind the fused (hash, rank) design
        (exactly ONE lookup_or_insert per append-only side per chunk).

        Pure trace (jax.eval_shape — nothing executes, no state is
        touched).  Returns ``{(job, node_idx, side):
        {"lookup_or_insert": n, "lookup": m}}`` and exports each count
        as a ``join_probe_calls_per_chunk`` gauge."""
        import jax as _jax

        from risingwave_tpu.state.hash_table import (
            PROBE_STATS,
            reset_probe_stats,
        )

        out: dict = {}
        for job in self.jobs:
            if not isinstance(job, DagJob):
                continue
            for idx, node in enumerate(job.nodes):
                if not isinstance(node, JoinNode):
                    continue
                join = node.join
                if not hasattr(join, "storage_of"):
                    continue
                for side in ("left", "right"):
                    if join.storage_of(side) != "pool":
                        continue
                    schema = join.left_schema if side == "left" \
                        else join.right_schema
                    keys = join.left_keys if side == "left" \
                        else join.right_keys
                    clean = getattr(join, f"{side}_clean", None)
                    proto = _empty_chunk(schema, 4)
                    sstate = getattr(job.states[idx], side)
                    if job.mesh is not None:
                        # audit the per-shard program (drop the shard
                        # axis — every shard compiles the same body)
                        sstate = _jax.tree.map(
                            lambda x: _jax.ShapeDtypeStruct(
                                x.shape[1:], x.dtype
                            ), sstate,
                        )
                    reset_probe_stats()
                    _jax.eval_shape(
                        lambda s, c, keys=keys, clean=clean:
                            join._update_side_pool(s, c, keys, clean),
                        sstate, proto,
                    )
                    stats = dict(PROBE_STATS)
                    out[(job.name, idx, side)] = stats
                    self.metrics.set_gauge(
                        "join_probe_calls_per_chunk",
                        stats["lookup_or_insert"],
                        job=job.name, node=str(idx), side=side,
                    )
        return out

    # -- storage service (Hummock-lite) ---------------------------------
    def start_storage_service(self) -> None:
        """Start the background compactor (the fourth node role);
        server.py calls this, embedded tests drive synchronously."""
        if self.compactor is not None:
            self.compactor.start()

    def stop_storage_service(self) -> None:
        if self.compactor is not None:
            self.compactor.stop()

    def _storage_stall_hook(self) -> float:
        """The barrier loop's write-stall gate: block while storage L0
        is over the stall threshold (compaction behind ingest)."""
        return self.hummock.wait_below_stall(timeout=5.0)

    @staticmethod
    def _mv_storage_range(name: str) -> tuple[bytes, bytes]:
        """Key range of one MV in the shared storage keyspace (the
        TableKey table-prefix scheme, hummock_sdk/src/key.rs)."""
        lo = b"m:" + name.encode() + b"\x00"
        return lo, lo[:-1] + b"\x01"

    def _mv_export_items(self, entry: CatalogEntry) -> dict:
        """(storage key → pickled row) of an MV's CURRENT rows in the
        shared ``m:<name>\\0<pk>`` keyspace — the export seam both the
        single-node ``storage_export_mv`` and the cluster worker's
        per-barrier delta export build on.

        TTL MVs export only rows AT/ABOVE the expiry cutoff: rows
        below the horizon neither upsert (a compaction that dropped
        them must never see them resurrected by the next diff) nor
        tombstone (expiry is the compactor's job — the policy rides
        the manifest, see ``_ttl_policy``)."""
        import pickle as _pickle

        schema = entry.mv_executor.in_schema
        pk = entry.export_pk \
            if entry.export_pk is not None \
            else getattr(entry.mv_executor, "pk_indices",
                         tuple(range(len(schema))))
        lo, _ = self._mv_storage_range(entry.name)
        new: dict[bytes, bytes] = {}
        for row in self._mv_rows(entry):
            key = lo + b"".join(
                _mc_encode_value(row[i], schema[i]) for i in pk
            )
            new[key] = _pickle.dumps(tuple(row), protocol=4)
        cut = self._ttl_cutoffs.get(entry.name)
        if cut:
            new = {k: v for k, v in new.items() if k >= cut}
        return new

    def _ttl_policy(self, entry: CatalogEntry, epoch: int):
        """Derive (and monotonically advance) one TTL MV's expiry
        policy at export time: horizon = max observed leading
        export-pk value − ttl.  The max-observed value is the
        watermark proxy at barrier commit — it never regresses, so the
        horizon (and the byte cutoff compiled from it) only moves
        forward.  Returns the ``ExpiryPolicy`` to publish, or None
        when no horizon exists yet (empty MV)."""
        from risingwave_tpu.storage.pushdown import (
            ExpiryPolicy,
            table_prefix,
        )

        if entry.ttl is None:
            return None
        col_name, ttl = entry.ttl
        schema = entry.mv_executor.in_schema
        idx = schema.index_of(col_name)
        mx = None
        for row in self._mv_rows(entry):
            v = row[idx]
            if v is not None and (mx is None or v > mx):
                mx = v
        if mx is not None:
            horizon = int(mx) - int(ttl)
            cur = self._ttl_horizons.get(entry.name)
            if cur is None or horizon > cur:
                self._ttl_horizons[entry.name] = horizon
        horizon = self._ttl_horizons.get(entry.name)
        if horizon is None:
            return None
        prefix = table_prefix(entry.name)
        enc = _mc_encode_value(horizon, schema[idx])
        pol = ExpiryPolicy(
            table=entry.name, prefix=prefix,
            expire_below=prefix + bytes(enc), horizon=horizon,
            ttl=int(ttl), column=col_name, epoch=int(epoch),
        )
        self._ttl_cutoffs[entry.name] = pol.expire_below
        return pol

    def _publish_mv_schema(self, store, entry: CatalogEntry,
                           since_epoch: int | None = None) -> None:
        """Publish the MV's shape next to its data so an engine-free
        serving replica can encode pk probes and project columns
        without the binder (serve/reader.MvSchema loads this).

        Index MVs carry ``index_of``/``index_width`` plus the epoch
        their FIRST export rides (``since_epoch``) — a replica pinned
        before that epoch must not trust the index range (the doc is
        an unversioned side-channel; the data is versioned).  The
        upstream's doc lists its indexes so ``plan_read`` can rewrite
        equality predicates without a catalog."""
        import json as _json

        from risingwave_tpu.serve.reader import schema_key

        schema = entry.mv_executor.in_schema
        pk = entry.export_pk \
            if entry.export_pk is not None \
            else getattr(entry.mv_executor, "pk_indices",
                         tuple(range(len(schema))))
        cols = []
        for f in schema:
            if f.data_type.is_string:
                kind = "string"
            elif f.data_type == DataType.DECIMAL:
                kind = "decimal"
            elif f.data_type in (DataType.FLOAT32, DataType.FLOAT64):
                kind = "float"
            else:
                kind = "int"
            cols.append({
                "name": f.name, "kind": kind,
                "scale": int(getattr(f, "decimal_scale", 0) or 0),
                "hidden": f.name.startswith("_hidden_"),
                "nullable": bool(f.nullable),
            })
        doc = {"mv": entry.name, "columns": cols, "pk": list(pk)}
        if entry.index_on is not None:
            doc["index_of"] = entry.index_on[0]
            doc["index_width"] = len(entry.index_on[1])
            if since_epoch is not None:
                doc["since_epoch"] = int(since_epoch)
        idxs = [
            {"name": e.name, "cols": list(e.index_on[1])}
            for e in self.catalog.list("mview")
            if e.index_on is not None
            and e.index_on[0] == entry.name
        ]
        if idxs:
            doc["indexes"] = idxs
        store.put(schema_key(entry.name),
                  _json.dumps(doc).encode())

    def storage_export_mv(self, name: str) -> dict:
        """Export an MV's current rows into the storage service as an
        epoch-stamped changelog batch (upserts + tombstones for rows
        gone since the last export) — ONE new L0 SST, no merge I/O;
        the compactor folds it down in the background."""
        if self.hummock is None:
            raise PlanError("storage export needs a durable data_dir")
        entry = self.catalog.get(name)
        if entry.kind != "mview" or entry.job is None:
            raise PlanError(f"{name!r} is not a materialized view")
        epoch = entry.job.committed_epoch
        lo, hi = self._mv_storage_range(name)
        pol = self._ttl_policy(entry, epoch)
        new = self._mv_export_items(entry)
        cut = self._ttl_cutoffs.get(name)
        # keys below the cutoff get NO tombstone — expiry is the
        # compaction filter's job (the policy committed below)
        stale = [k for k, _ in self.hummock.scan(lo, hi)
                 if k not in new and not (cut and k < cut)]
        from risingwave_tpu.storage.sst import TOMBSTONE
        batch = sorted(new.items()) + [(k, TOMBSTONE) for k in stale]
        self.hummock.write_batch(batch, epoch=epoch)
        if pol is not None:
            self.hummock.set_policy(name, pol.to_doc())
        self._publish_mv_schema(self.hummock.store, entry,
                                since_epoch=epoch)
        self._schema_published.add(entry.name)
        self.metrics.inc("storage_mv_export_rows_total", len(new),
                         job=name)
        return {"mv": name, "epoch": epoch, "rows": len(new),
                "deletes": len(stale)}

    def _seed_exported(self, store, name: str) -> dict:
        """Rebuild the export diff base of one MV from the SHARED
        manifest (a fresh/adopting worker has no export memory; the
        committed storage state IS the base the next delta must diff
        against)."""
        from risingwave_tpu.serve.reader import (
            ManifestFollower,
            mv_key_range,
        )
        from risingwave_tpu.storage.sst import SstReader, merge_scan

        v = ManifestFollower(store).refresh(None)
        readers = [SstReader(store=store, key=s.key)
                   for lv in v.levels for s in lv
                   if s.key not in self._seed_exclude]
        try:
            lo, hi = mv_key_range(name)
            base = dict(merge_scan(readers, lo, hi))
        finally:
            for r in readers:
                r.close()
        entry = self.catalog.get(name) if name in self.catalog else None
        if entry is None or getattr(entry.job, "n_vnodes", None) is None:
            return base
        # partitioned MV: the manifest holds EVERY partition's rows;
        # the diff base keeps only this partition's vnodes, so narrowed
        # ownership never emits tombstones for rows another partition
        # now owns (and gained rows never re-upload unchanged)
        import pickle as _pickle

        from risingwave_tpu.cluster.scale.vnode import vnodes_of_ints

        if not base:
            return base
        pk0 = entry.mv_executor.pk_indices[0]
        keys = list(base)
        vals = np.asarray(
            [int(_pickle.loads(base[k])[pk0]) for k in keys], np.int64
        )
        vn = np.asarray(vnodes_of_ints(vals, entry.job.n_vnodes))
        own = {int(v) for v in entry.job.vnodes}
        return {k: base[k] for k, v in zip(keys, vn) if int(v) in own}

    def export_mv_deltas(self, job_name: str, epoch: int) -> list:
        """Cluster-mode per-barrier MV export: diff every MV riding
        ``job_name`` against its last export, seal the changes
        (upserts + tombstones) as ONE new SST uploaded to the shared
        store, and return its descriptor(s) for the meta to commit
        into the shared manifest with the round's cluster epoch — the
        meta stays the manifest's single writer; workers only upload
        objects under meta-allocated (vacuum-protected) keys."""
        from risingwave_tpu.storage.sst import (
            TOMBSTONE,
            build_sst_bytes,
        )

        store = self.shared_store if self.shared_store is not None \
            else (self.hummock.store if self.hummock is not None
                  else None)
        if store is None or self.sst_key_allocator is None:
            return []
        batch: list[tuple[bytes, bytes]] = []
        staged: list[tuple[str, dict, int]] = []
        for entry in self.catalog.list("mview"):
            if entry.job is None or entry.job.name != job_name \
                    or entry.mv_executor is None:
                continue
            pol = self._ttl_policy(entry, epoch)
            if pol is not None:
                self.pending_policies[entry.name] = pol.to_doc()
            new = self._mv_export_items(entry)
            prev = self._exported.get(entry.name)
            if prev is None:
                prev = self._seed_exported(store, entry.name)
            cut = self._ttl_cutoffs.get(entry.name)
            if cut:
                # the diff base forgets expired keys too: no
                # tombstones for rows the compactor will drop, and a
                # drop that already happened cannot resurrect
                prev = {k: v for k, v in prev.items() if k >= cut}
            if entry.name not in self._schema_published:
                # first export this process, or a CREATE/DROP INDEX
                # dirtied the doc (the index list changed)
                self._publish_mv_schema(store, entry,
                                        since_epoch=epoch)
                self._schema_published.add(entry.name)
            ups = [(k, v) for k, v in new.items()
                   if prev.get(k) != v]
            dels = [(k, TOMBSTONE) for k in prev if k not in new]
            batch += ups + dels
            staged.append((entry.name, new, len(ups)))
        if not batch:
            for name, new, _ in staged:
                self._exported[name] = new
            return []
        batch.sort()
        key = self.sst_key_allocator()
        data, meta = build_sst_bytes(
            [k for k, _ in batch], [v for _, v in batch]
        )
        store.put(key, data)
        # the diff base moves ONLY after the object landed: an export
        # whose upload dies keeps its rows in the next attempt's diff
        # instead of silently dropping them from the serving tier
        for name, new, n_ups in staged:
            self._exported[name] = new
            if n_ups:
                self.metrics.inc("storage_mv_export_rows_total",
                                 n_ups, job=name)
        return [{
            "key": key,
            "first_key": meta.first_key.hex(),
            "last_key": meta.last_key.hex(),
            "n_records": meta.n_records,
            "size": meta.size,
            "epoch": epoch,
        }]

    def reexport_job_mvs(self, job_name: str, exclude=()) -> list:
        """Integrity repair export: drop the export diff bases of every
        MV riding ``job_name`` and re-seed them from the shared
        manifest EXCLUDING the quarantined keys — the resulting SST
        carries upserts for every row the corrupt object held and
        tombstones for rows it shadowed, so swapping it in for the
        corrupt SST is byte-exact.  Returns the SST descriptors for the
        meta's atomic replace commit."""
        job = self._job_by_name(job_name)
        if job is None:
            return []
        for entry in self.catalog.list("mview"):
            if entry.job is not None and entry.job.name == job_name:
                self._exported.pop(entry.name, None)
        self._seed_exclude = frozenset(exclude or ())
        try:
            return self.export_mv_deltas(job_name, job.committed_epoch)
        finally:
            self._seed_exclude = frozenset()

    def take_pending_policies(self) -> dict:
        """Drain the policy docs staged by this round's exports
        (table → doc, None = DROP) — the cluster worker ships them in
        its barrier response and the meta folds them into the SAME
        manifest delta that commits the round's export SSTs."""
        out, self.pending_policies = self.pending_policies, {}
        return out

    def _tombstone_dropped_mv(self, entry: CatalogEntry) -> None:
        """DROP MATERIALIZED VIEW / DROP INDEX removes the MV from the
        SHARED serving keyspace too: one tombstone batch for every
        exported row plus the serve-schema doc deleted, so a serving
        replica answers "does not exist" instead of stale rows.  Only
        the manifest OWNER writes (single node / meta-owned storage);
        a cluster compute worker just forgets its export diff base —
        the meta, which owns the manifest over the same store, writes
        the tombstones when it unplaces the MV."""
        from risingwave_tpu.storage.hummock.object_store import (
            ObjectError,
        )

        import json as _json

        self._exported.pop(entry.name, None)
        self._schema_published.discard(entry.name)
        if entry.ttl is not None:
            # retire the expiry policy with the MV (cluster workers
            # stage the removal; the manifest owner commits it below)
            self._ttl_horizons.pop(entry.name, None)
            self._ttl_cutoffs.pop(entry.name, None)
            self.pending_policies[entry.name] = None
        if entry.index_on is not None:
            # the upstream's doc must stop advertising this index
            self._schema_published.discard(entry.index_on[0])
        if self.hummock is None:
            return
        from risingwave_tpu.serve.reader import schema_key

        if entry.index_on is not None:
            # rewrite the upstream doc BEFORE the tombstone delta: a
            # reader refreshing past the tombstones must not plan
            # through the dead index (readers pinned earlier still see
            # consistent doc+data)
            try:
                doc = _json.loads(
                    self.hummock.store.get(schema_key(entry.index_on[0]))
                )
                doc["indexes"] = [
                    e for e in doc.get("indexes", [])
                    if e.get("name") != entry.name
                ]
                if not doc["indexes"]:
                    doc.pop("indexes")
                self.hummock.store.put(
                    schema_key(entry.index_on[0]),
                    _json.dumps(doc).encode(),
                )
            except ObjectError:
                pass  # upstream never exported
        lo, hi = self._mv_storage_range(entry.name)
        keys = [k for k, _ in self.hummock.scan(lo, hi)]
        if keys:
            self.hummock.delete_batch(
                keys, epoch=self.hummock.versions.max_committed_epoch
            )
        if entry.ttl is not None:
            self.hummock.set_policy(entry.name, None)
        try:
            self.hummock.store.delete(schema_key(entry.name))
        except ObjectError:
            pass  # never exported

    def storage_serve_mv(self, name: str) -> list:
        """Serve an exported MV from the storage service through a
        PINNED version — a consistent SST set even while the compactor
        rewrites levels and vacuum deletes their inputs (the
        BatchTable-over-Hummock read, SURVEY §3.4)."""
        import pickle as _pickle

        if self.hummock is None:
            raise PlanError("storage serving needs a durable data_dir")
        lo, hi = self._mv_storage_range(name)
        with self.hummock.pin() as pv:
            return [_pickle.loads(v) for _, v in pv.scan(lo, hi)]

    def storage_vacuum(self) -> dict:
        """GC pass: delete SST objects unreferenced by any pinned
        version (checkpoint exports live outside the sst/ prefix and
        are never touched)."""
        if self.hummock is None:
            raise PlanError("storage vacuum needs a durable data_dir")
        deleted = self.hummock.vacuum()
        return {"deleted_objects": deleted,
                **{"remaining_objects": self.hummock.stats()["objects"]}}

    # -- serving reads ---------------------------------------------------
    @staticmethod
    def _host_col(bound, chunk, vis):
        """Materialize a bound expr over visible rows as host values
        (strings decoded, decimals descaled)."""
        from risingwave_tpu.common.chunk import StrCol, decode_strings

        col = bound.eval(chunk)
        if isinstance(col, StrCol):
            return decode_strings(
                np.asarray(col.data)[vis], np.asarray(col.lens)[vis]
            ).tolist(), True
        f = bound.return_field(chunk.schema)
        vals = np.asarray(col)[vis]
        if f.data_type == DataType.DECIMAL:
            vals = vals.astype(np.float64) / 10**f.decimal_scale
        return vals.tolist(), False

    def _mv_vnode_set(self, entry: CatalogEntry):
        """(vnode_set, n_vnodes) a read of this MV must narrow to, or
        (None, None).  An explicit per-read override (the meta passes
        the map AT THE PINNED ROUND) wins over the partition's current
        ownership."""
        n_vn = getattr(entry.job, "n_vnodes", None)
        if n_vn is None:
            return None, None
        override = getattr(self, "_serve_vnodes", None)
        return (override if override is not None
                else entry.job.vnodes), n_vn

    def _mv_rows(self, entry: CatalogEntry):
        from risingwave_tpu.stream.sharded import ShardedStreamingJob

        vn_set, n_vn = self._mv_vnode_set(entry)
        # time travel: SET query_epoch reads a retained historical
        # checkpoint (ref FOR SYSTEM_TIME AS OF over Hummock versions,
        # time_travel_version_cache.rs)
        qe = int(self.session_config.get("query_epoch"))
        if qe:
            if self.checkpoint_store is None:
                raise PlanError(
                    "query_epoch needs a durable data_dir"
                )
            # checkpoints live under the JOB's lineage key — an MV
            # attached to a shared DagJob (MV-on-MV) reads its job's
            # snapshot; a partitioned job reads its own partition's
            ckpt_name = getattr(entry.job, "ckpt_key", entry.job.name)
            epochs = self.checkpoint_store.epochs(ckpt_name)
            if qe not in epochs:
                raise PlanError(
                    f"epoch {qe} is not retained for {entry.name} "
                    f"(retained: {epochs})"
                )
            _, states, _ = self.checkpoint_store.load(ckpt_name, qe)
            st = states
            for i in entry.mv_state_index:
                st = st[i]
            if getattr(entry.job, "mesh", None) is not None:
                import jax as _jax
                rows = []
                for shard in range(entry.job.n_shards):
                    rows.extend(entry.mv_executor.to_host(
                        _jax.tree.map(lambda x: x[shard], st)
                    ))
                return rows
            if vn_set is not None:
                st = self._vnode_filtered_mv_state(st, vn_set, n_vn)
            return entry.mv_executor.to_host(st)

        idx = entry.mv_state_index
        if isinstance(entry.job, ShardedStreamingJob):
            return entry.job.mv_rows(entry.mv_executor, idx[0])
        if getattr(entry.job, "mesh", None) is not None:
            return entry.job.mv_rows(entry.mv_executor, idx)
        state = entry.job.states
        for i in idx:
            state = state[i]
        if vn_set is not None:
            state = self._vnode_filtered_mv_state(state, vn_set, n_vn)
        return entry.mv_executor.to_host(state)

    @staticmethod
    def _order_permutation(chunk, order_by, n_rows: int) -> list[int]:
        """Stable multi-key sort permutation over a host-built chunk.

        Keys evaluate in ORIGINAL row order (the permutation indexes
        original rows, so every pass stays aligned); NULLs sort last
        for ASC (pg default), first for DESC."""
        from risingwave_tpu.common.chunk import StrCol, decode_strings

        perm = list(range(n_rows))
        vis = np.asarray(chunk.valid)
        for e, desc in reversed(list(order_by)):
            vals, vals_null = split_col(e.eval(chunk))
            if isinstance(vals, StrCol):
                host = decode_strings(
                    np.asarray(vals.data)[vis], np.asarray(vals.lens)[vis]
                ).tolist()
            else:
                host = np.asarray(vals)[vis].tolist()
            if vals_null is not None:
                nulls = np.asarray(vals_null)[vis].tolist()
                z = type(host[0])() if host else 0
                host = [(True, z) if nul else (False, v)
                        for v, nul in zip(host, nulls)]
            perm.sort(key=lambda i: host[i], reverse=desc)
        return perm

    def _apply_serving_topn(self, entry: CatalogEntry, rows: list):
        """Global order+limit over a sharded TopN MV's merged bands.

        Each shard's band is a superset slice of the global top-k; the
        serving boundary is the singleton merge (ref top_n singleton
        fragments)."""
        spec = getattr(entry.mv_executor, "serving_topn", None)
        if spec is None or not rows:
            return rows
        order_by, limit, offset = spec
        schema = entry.mv_executor.in_schema
        arrays = [np.asarray([r[i] for r in rows])
                  for i in range(len(schema))]
        chunk = Chunk.from_numpy(schema, arrays, capacity=len(rows))
        perm = self._order_permutation(chunk, order_by, len(rows))
        rows = [rows[i] for i in perm]
        end = None if limit is None else offset + limit
        return rows[offset:end]

    def _needs_batch_exec(self, select: ast.Select) -> bool:
        """Fast path = plain projection/filter over one MV; everything
        else (aggs, GROUP BY, joins, derived tables, subqueries in
        WHERE, base-table scans) runs the batch executor pipeline."""
        if not isinstance(select.from_, ast.TableRef):
            return True
        if select.from_.name not in self.catalog:
            return False  # fast path raises the proper error
        if self.catalog.get(select.from_.name).kind != "mview":
            return True
        if select.group_by or select.having is not None \
                or self.planner._has_agg(select):
            return True

        def has_sub(e) -> bool:
            if isinstance(e, (ast.ScalarSubquery, ast.InSubquery,
                              ast.ExistsSubquery)):
                return True
            for a in ("left", "right", "operand"):
                v = getattr(e, a, None)
                if v is not None and has_sub(v):
                    return True
            return any(has_sub(x) for x in getattr(e, "args", ())
                       if not isinstance(x, ast.Star))

        return select.where is not None and has_sub(select.where)

    def _serve(self, select: ast.Select):
        """Batch read over a materialized view (local execution mode)."""
        if self._needs_batch_exec(select):
            return self._serve_batch(select)
        if not isinstance(select.from_, ast.TableRef):
            raise PlanError("serving reads support SELECT ... FROM <mv>")
        entry = self.catalog.get(select.from_.name)
        if entry.kind != "mview":
            raise PlanError("serving reads are over materialized views; "
                            "streaming queries use CREATE MATERIALIZED VIEW")
        rows = self._mv_rows(entry)
        rows = self._apply_serving_topn(entry, rows)
        schema = entry.schema
        # rebuild a host chunk and evaluate the residual query eagerly
        if rows:
            arrays = [np.asarray([r[i] for r in rows])
                      for i in range(len(schema))]
        else:
            arrays = [np.zeros((0,), np.int64) for _ in range(len(schema))]
        chunk = Chunk.from_numpy(schema, arrays, capacity=max(len(rows), 1))
        scope = Scope.of(schema, select.from_.alias or select.from_.name)
        if select.where is not None:
            keep = Binder(scope).bind(select.where).eval(chunk)
            chunk = chunk.mask(keep)
        # aggregates/GROUP BY route to _serve_batch before reaching
        # here (_needs_batch_exec); the interpreted host-agg path that
        # used to live at this dispatch is deleted — one SQL semantics,
        # one (compiled) implementation
        items = self.planner._expand_items(select.items, scope)
        b = Binder(scope)
        out_cols = []
        bound_fields = []
        for name, e in items:
            be = b.bind(e)
            out_cols.append(be.eval(chunk))
            f = be.return_field(schema)
            bound_fields.append(Field(
                name, f.data_type, str_width=f.str_width,
                decimal_scale=f.decimal_scale,
            ))
        self._last_columns = [f.name for f in bound_fields]
        self._last_fields = bound_fields
        out_chunk = chunk.with_columns(out_cols, Schema(tuple(bound_fields)))
        _, cols, _ = out_chunk.to_host()
        result = [tuple(c[i] for c in cols) for i in range(len(cols[0]))] \
            if cols else []
        # ORDER BY / LIMIT / OFFSET host-side (python sort: handles
        # strings and any comparable type, stable for multi-key)
        if select.order_by:
            out_scope = Scope.of(out_chunk.schema)
            ob = Binder(out_scope)
            order_by = [
                (self.planner._bind_order_key(
                    oi.expr, ob, out_chunk.schema
                ), oi.descending)
                for oi in select.order_by
            ]
            perm = self._order_permutation(
                out_chunk, order_by, len(result)
            )
            result = [result[i] for i in perm]
        if select.offset:
            result = result[select.offset:]
        if select.limit is not None:
            result = result[:select.limit]
        return result


class _SnapshotReader:
    """Bounded serving source: an MV's rows at read time, as one
    static-capacity all-inserts chunk (ref RowSeqScanExecutor reading a
    BatchTable at a pinned epoch, row_seq_scan.rs:44 — here the
    'table' is the MV's device state, snapshotted zero-copy)."""

    def __init__(self, engine, entry):
        self.engine = engine
        self.entry = entry
        self._chunks: list = []
        self._empty = None

    def reset(self) -> None:
        from risingwave_tpu.stream.sharded import ShardedStreamingJob
        import jax.numpy as jnp

        entry = self.entry
        if isinstance(entry.job, ShardedStreamingJob) \
                or getattr(entry.job, "mesh", None) is not None:
            # sharded upstream: host-gathered rows re-encoded at the
            # executor's static capacity
            rows = self.engine._mv_rows(entry)
            ex = entry.mv_executor
            cap = getattr(ex, "table_size", None) \
                or getattr(ex, "ring_size")
            schema = ex.in_schema
            if rows:
                arrays = [np.asarray([r[i] for r in rows])
                          for i in range(len(schema))]
            else:
                arrays = [np.zeros((0,), np.int64) for _ in schema]
            chunk = Chunk.from_numpy(schema, arrays, capacity=cap)
        else:
            chunk = self.engine._mv_snapshot_chunk(entry)
        self._chunks = [chunk]
        if self._empty is None:
            self._empty = Chunk(
                chunk.columns,
                jnp.zeros((chunk.capacity,), jnp.int8),
                jnp.zeros((chunk.capacity,), jnp.bool_),
                chunk.schema,
            )

    def pending(self) -> int:
        return len(self._chunks)

    def next_chunk(self):
        if self._chunks:
            return self._chunks.pop()
        return self._empty


def _const_value(e):
    """Evaluate a constant VALUES expression host-side."""
    if isinstance(e, ast.Literal):
        return e.value
    if isinstance(e, ast.IntervalLit):
        return e.micros
    if isinstance(e, ast.UnaryOp) and e.op == "neg":
        return -_const_value(e.operand)
    if isinstance(e, ast.Cast):
        v = _const_value(e.operand)
        t = DataType.from_sql(e.type_name)
        return _coerce_const(v, Field("?", t))
    raise ValueError(f"INSERT VALUES must be constants, got {e!r}")


def _coerce_const(v, field: Field):
    """Validate/convert one INSERT value to the column type at statement
    time — a bad constant must fail the INSERT, never poison the queue
    for every downstream job."""
    t = field.data_type
    if v is None:
        if not field.nullable:
            raise ValueError(
                f"NULL value for NOT NULL column {field.name} "
                "(declare the column `NULL` to allow NULLs)"
            )
        return None
    try:
        if t.is_string:
            return str(v)
        if t in (DataType.FLOAT32, DataType.FLOAT64, DataType.DECIMAL):
            return float(v)
        if t == DataType.BOOLEAN:
            if isinstance(v, str):
                raise ValueError(v)
            return bool(v)
        if isinstance(v, str) and t in (
            DataType.TIMESTAMP, DataType.TIMESTAMPTZ, DataType.DATE
        ):
            # '2015-07-15 00:00:00.005' literals (pg-style)
            from datetime import date, datetime, timezone

            if t == DataType.DATE:
                return (date.fromisoformat(v) - date(1970, 1, 1)).days
            dt = datetime.fromisoformat(v.replace("Z", "+00:00"))
            if dt.tzinfo is not None:
                dt = dt.astimezone(timezone.utc).replace(tzinfo=None)
            from datetime import timedelta
            # exact integer microseconds (float total_seconds() rounds)
            return (dt - datetime(1970, 1, 1)) // timedelta(microseconds=1)
        if isinstance(v, float):
            return int(round(v))  # SQL casts round, not truncate
        return int(v)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"invalid value {v!r} for column "
            f"{field.name} ({t.value})"
        ) from e


class _ProjectingReader:
    """Column-projecting wrapper over a source reader."""

    def __init__(self, inner, idxs: Sequence[int], schema: Schema):
        self.inner = inner
        self.idxs = list(idxs)
        self.schema = schema
        if hasattr(inner, "impl"):
            self.impl = lambda k0, cap: inner.impl(k0, cap).project(
                self.idxs
            )
            self.cap = inner.cap
            self.next_base = inner.next_base
        if hasattr(inner, "events_per_row"):
            self.events_per_row = inner.events_per_row

    def next_chunk(self) -> Chunk:
        return self.inner.next_chunk().project(self.idxs)

    @property
    def offset(self):
        return self.inner.offset

    @offset.setter
    def offset(self, v):
        self.inner.offset = v

    def state(self):
        return self.inner.state()


class _DatagenReader:
    """Deterministic generator for declared columns (ref datagen source)."""

    def __init__(self, schema: Schema, cap: int, split_id: int,
                 num_splits: int):
        self.schema = schema
        self.cap = cap
        self.split_id = split_id
        self.num_splits = num_splits
        self.offset = 0

    def next_chunk(self) -> Chunk:
        import jax.numpy as jnp

        base = self.offset * self.num_splits + self.split_id * self.cap
        k = base + np.arange(self.cap, dtype=np.int64)
        cols = []
        for f in self.schema:
            t = f.data_type
            if t.is_string:
                from risingwave_tpu.common.chunk import StrCol, encode_strings
                data, lens = encode_strings(
                    [f"{f.name}_{int(v) % 1000}" for v in k], f.str_width
                )
                cols.append(StrCol(jnp.asarray(data), jnp.asarray(lens)))
            elif t in (DataType.FLOAT32, DataType.FLOAT64):
                cols.append(jnp.asarray(
                    (k % 1000).astype(np.float64) / 10.0, t.physical_dtype
                ))
            else:
                cols.append(jnp.asarray(k, t.physical_dtype))
        self.offset += self.cap
        return Chunk(
            tuple(cols),
            jnp.zeros((self.cap,), jnp.int8),
            jnp.ones((self.cap,), jnp.bool_),
            self.schema,
        )

    def state(self):
        return {"offset": self.offset, "split_id": self.split_id}
