"""SQL frontend: parser → binder/planner → streaming jobs.

Reference counterparts: ``src/sqlparser`` (hand-written recursive-descent
Postgres-dialect parser), ``src/frontend`` (binder, planner, optimizer,
stream fragmenter).  This frontend targets the streaming-SQL surface the
benchmarks exercise (CREATE SOURCE/MV, windowed aggregation, joins,
TopN) and widens round over round.

``Engine`` resolves lazily (PEP 562): the engine imports jax, but the
engine-free serving tier uses only ``sql.parser``/``sql.ast`` (pure
Python) and must be able to import the package without loading jax.
"""

__all__ = ["Engine"]


def __getattr__(name):
    if name == "Engine":
        from risingwave_tpu.sql.engine import Engine

        globals()["Engine"] = Engine
        return Engine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
