"""SQL frontend: parser → binder/planner → streaming jobs.

Reference counterparts: ``src/sqlparser`` (hand-written recursive-descent
Postgres-dialect parser), ``src/frontend`` (binder, planner, optimizer,
stream fragmenter).  This frontend targets the streaming-SQL surface the
benchmarks exercise (CREATE SOURCE/MV, windowed aggregation, joins,
TopN) and widens round over round.
"""

from risingwave_tpu.sql.engine import Engine

__all__ = ["Engine"]
