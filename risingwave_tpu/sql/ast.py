"""SQL AST nodes.

Reference counterpart: ``src/sqlparser/src/ast/`` — pared down to the
streaming surface this frontend implements.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


# -- expressions ------------------------------------------------------------


@dataclass(frozen=True)
class ColumnRef:
    name: str
    table: str | None = None


@dataclass(frozen=True)
class Literal:
    value: Any
    #: "int" | "float" | "string" | "bool" | "null" | "date" (days
    #: since epoch) | "timestamp" (microseconds since epoch)
    type_name: str


@dataclass(frozen=True)
class IntervalLit:
    micros: int
    #: calendar months (INTERVAL 'n' MONTH/YEAR); not convertible to
    #: micros — consumed by bind-time date-arithmetic folding
    months: int = 0


@dataclass(frozen=True)
class BinaryOp:
    op: str
    left: Any
    right: Any


@dataclass(frozen=True)
class UnaryOp:
    op: str
    operand: Any


@dataclass(frozen=True)
class FuncCall:
    name: str
    args: tuple
    distinct: bool = False
    #: aggregate FILTER (WHERE <cond>) clause (ref agg filter exprs)
    filter_where: "object | None" = None


@dataclass(frozen=True)
class WindowCall:
    """fn(args) OVER (PARTITION BY ... ORDER BY ... [ROWS frame])."""

    name: str
    args: tuple
    partition_by: tuple
    order_by: tuple  # OrderItem
    #: (preceding_rows, following_rows) for ROWS BETWEEN frames;
    #: None = the default frame (unbounded preceding .. current row)
    frame: "tuple | None" = None


@dataclass(frozen=True)
class Cast:
    operand: Any
    type_name: str


@dataclass(frozen=True)
class Case:
    conditions: tuple  # (cond, result) pairs
    else_result: Any


@dataclass(frozen=True)
class Star:
    #: qualified star (``A.*``): expand only that table's columns
    table: "str | None" = None


# -- query ------------------------------------------------------------------


@dataclass(frozen=True)
class SelectItem:
    expr: Any
    alias: str | None


@dataclass(frozen=True)
class TableRef:
    name: str
    alias: str | None = None
    #: ``FOR SYSTEM_TIME AS OF PROCTIME()`` — the build side of a
    #: temporal join (ref temporal_join.rs)
    temporal: bool = False


@dataclass(frozen=True)
class SubqueryRef:
    """A derived table: ``FROM (SELECT ...) alias``."""

    select: "Select"
    alias: str | None = None


@dataclass(frozen=True)
class AlterParallelism:
    """ALTER MATERIALIZED VIEW <name> SET PARALLELISM <n> — online
    rescale at a barrier (ref scale.rs reschedule)."""

    name: str
    parallelism: int


@dataclass(frozen=True)
class CreateFunction:
    """CREATE FUNCTION ... LANGUAGE SQL — inlined at plan time (the
    reference compiles SQL UDFs by inlining too: expr/impl udf)."""

    name: str
    params: tuple           # parameter names, positional
    body_sql: str           # "SELECT <expr>"
    if_not_exists: bool = False


@dataclass(frozen=True)
class InSubquery:
    """``expr [NOT] IN (SELECT ...)`` — planned as a semi/anti join."""

    expr: object
    select: "Select"
    negated: bool = False


@dataclass(frozen=True)
class ExistsSubquery:
    """``EXISTS (SELECT ...)`` — planned as a semi join on the
    correlated equi predicates mined from the subquery's WHERE
    (NOT EXISTS → anti join)."""

    select: "Select"


@dataclass(frozen=True)
class ScalarSubquery:
    """``(SELECT <single aggregate row>)`` in a comparison — planned as
    a dynamic filter against the subquery's 1-row changelog."""

    select: "Select"


@dataclass(frozen=True)
class Tumble:
    """TUMBLE(table, time_col, interval) table function in FROM."""

    table: TableRef
    time_col: str
    size: IntervalLit
    alias: str | None = None


@dataclass(frozen=True)
class Hop:
    """HOP(table, time_col, slide, size)."""

    table: TableRef
    time_col: str
    slide: IntervalLit
    size: IntervalLit
    alias: str | None = None


@dataclass(frozen=True)
class Join:
    left: Any
    right: Any
    on: Any
    kind: str = "inner"


@dataclass(frozen=True)
class OrderItem:
    expr: Any
    descending: bool


@dataclass(frozen=True)
class Select:
    items: tuple[SelectItem, ...]
    from_: Any  # TableRef | Tumble | Hop | Join | None
    where: Any = None
    group_by: tuple = ()
    having: Any = None
    order_by: tuple[OrderItem, ...] = ()
    limit: int | None = None
    offset: int | None = None


# -- statements -------------------------------------------------------------


@dataclass(frozen=True)
class ColumnDef:
    name: str
    type_name: str
    #: columns are NOT NULL by default (deviation from the reference's
    #: nullable default: keeps the non-null fast path for generated
    #: sources); declare ``col type NULL`` to opt in
    nullable: bool = False


@dataclass(frozen=True)
class WatermarkDef:
    column: str
    delay: IntervalLit


@dataclass(frozen=True)
class CreateSource:
    name: str
    columns: tuple[ColumnDef, ...]
    watermark: WatermarkDef | None
    with_options: dict
    if_not_exists: bool = False
    is_table: bool = False
    #: declared PRIMARY KEY column names (metadata; DML tables use it
    #: as the stream key exposed to downstream plans)
    primary_key: tuple = ()


@dataclass(frozen=True)
class Insert:
    table: str
    columns: tuple[str, ...]  # () = positional
    rows: tuple               # tuples of literal AST exprs


@dataclass(frozen=True)
class Delete:
    """``DELETE FROM t VALUES (...)`` — exact-full-row retraction.

    The workload plane knows the full row it retracts (the generator
    keeps deterministic shadow state), so deletes ship the complete
    old row and the changelog simply emits it with ``OP_DELETE`` —
    no lookup path, and every downstream operator retracts by sign
    arithmetic exactly as for any other changelog source."""
    table: str
    columns: tuple[str, ...]  # () = positional
    rows: tuple               # tuples of literal AST exprs


@dataclass(frozen=True)
class Update:
    """``UPDATE t SET col = lit, ... WHERE <full-pk equality>`` —
    workload-plane sugar over the exact-full-row retraction pair: the
    engine resolves the live old row by pk, then desugars to the same
    DELETE+INSERT the generator would have shipped.  Only literal
    assignments and a full-pk equality WHERE are accepted (anything
    else still needs the explicit pair)."""
    table: str
    assignments: tuple  # ((col_name, literal AST expr), ...)
    where: Any = None


@dataclass(frozen=True)
class CreateMaterializedView:
    name: str
    query: Select
    if_not_exists: bool = False
    emit_on_window_close: bool = False
    #: WITH (k = v, ...) between the name and AS — carries the
    #: pushdown plane's ttl option (leading-pk retention horizon)
    with_options: dict = field(default_factory=dict)


@dataclass(frozen=True)
class CreateIndex:
    """``CREATE INDEX name ON mv(col, ...)`` — compiles to a small
    secondary-index MV (pk = (col..., upstream pk)) maintained through
    the MV-on-MV path and exported to the shared serving keyspace."""
    name: str
    table: str
    columns: tuple
    if_not_exists: bool = False


@dataclass(frozen=True)
class CreateSink:
    name: str
    query: Any          # Select (AS form) or None
    from_rel: str | None
    with_options: dict
    if_not_exists: bool = False


@dataclass(frozen=True)
class DropStatement:
    kind: str  # "source" | "materialized view" | "table" | "index"
    name: str
    if_exists: bool = False


@dataclass(frozen=True)
class ShowStatement:
    kind: str  # "sources" | "materialized views" | "tables"


@dataclass(frozen=True)
class FlushStatement:
    pass


@dataclass(frozen=True)
class SetStatement:
    name: str
    value: Any
    system: bool = False  # ALTER SYSTEM SET vs session SET


@dataclass(frozen=True)
class ShowParameters:
    pass


@dataclass(frozen=True)
class DescribeStatement:
    name: str


@dataclass(frozen=True)
class Explain:
    statement: Any
