"""Hand-written recursive-descent SQL parser (Postgres dialect subset).

Reference counterpart: ``src/sqlparser/src/parser.rs`` — same approach
(tokenizer + recursive descent with precedence climbing), scoped to the
streaming benchmark surface: CREATE SOURCE / CREATE MATERIALIZED VIEW /
SELECT with windows (TUMBLE/HOP), joins, aggregation, TopN, casts,
CASE, intervals.
"""

from __future__ import annotations

import re

from risingwave_tpu.sql import ast

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<number>\d+\.\d+|\.\d+|\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<dollar>\$(?P<dtag>[A-Za-z_]*)\$.*?\$(?P=dtag)\$)
  | (?P<cast>::)
  | (?P<op><=|>=|<>|!=|\|\||[-+*/%<>=(),.;\[\]])
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*|"[^"]+")
    """,
    re.VERBOSE | re.DOTALL,
)

_INTERVAL_UNITS = {
    "second": 1_000_000, "seconds": 1_000_000,
    "minute": 60_000_000, "minutes": 60_000_000,
    "hour": 3_600_000_000, "hours": 3_600_000_000,
    "day": 86_400_000_000, "days": 86_400_000_000,
    "millisecond": 1_000, "milliseconds": 1_000,
}

#: calendar units carried as a months count (ref Interval {months,
#: days, usecs}); consumed by bind-time date-arithmetic folding
_INTERVAL_MONTH_UNITS = {
    "month": 1, "months": 1, "year": 12, "years": 12,
}


class Token:
    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: str):
        self.kind = kind
        self.value = value

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> list[Token]:
    out = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise ParseError(f"unexpected character {sql[pos]!r} at {pos}")
        pos = m.end()
        if m.group("dollar") is not None:
            # dollar-quoted body: strip the $tag$ ... $tag$ delimiters
            raw = m.group("dollar")
            ntag = len(m.group("dtag")) + 2
            out.append(Token("dollar_string", raw[ntag:-ntag]))
            continue
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        text = m.group()
        if kind == "ident" and not text.startswith('"'):
            out.append(Token("word", text.lower()))
        elif kind == "ident":
            out.append(Token("word", text[1:-1]))
        else:
            out.append(Token(kind, text))
    return out


class ParseError(ValueError):
    pass


# operator precedence (higher binds tighter)
_PRECEDENCE = {
    "or": 1, "and": 2,
    "=": 4, "<>": 4, "!=": 4, "<": 4, "<=": 4, ">": 4, ">=": 4,
    "+": 6, "-": 6, "||": 6,
    "*": 7, "/": 7, "%": 7,
}

_BIN_NAMES = {
    "=": "equal", "<>": "not_equal", "!=": "not_equal",
    "<": "less_than", "<=": "less_than_or_equal",
    ">": "greater_than", ">=": "greater_than_or_equal",
    "+": "add", "-": "subtract", "*": "multiply", "/": "divide",
    "%": "modulus", "and": "and", "or": "or", "||": "concat",
}


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers --------------------------------------------------
    def peek(self, offset: int = 0) -> Token | None:
        j = self.i + offset
        return self.tokens[j] if j < len(self.tokens) else None

    def next(self) -> Token:
        t = self.peek()
        if t is None:
            raise ParseError("unexpected end of input")
        self.i += 1
        return t

    def accept_word(self, *words: str) -> bool:
        t = self.peek()
        if t and t.kind == "word" and t.value in words:
            self.i += 1
            return True
        return False

    def expect_word(self, word: str) -> None:
        t = self.next()
        if t.kind != "word" or t.value != word:
            raise ParseError(f"expected {word.upper()}, got {t.value!r}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t and t.kind in ("op", "cast") and t.value == op:
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        t = self.next()
        if t.value != op:
            raise ParseError(f"expected {op!r}, got {t.value!r}")

    def ident(self) -> str:
        t = self.next()
        if t.kind != "word":
            raise ParseError(f"expected identifier, got {t.value!r}")
        return t.value

    # -- entry ----------------------------------------------------------
    def parse_statement(self):
        if self.accept_word("explain"):
            return ast.Explain(self.parse_statement())
        if self.accept_word("create"):
            return self._create()
        if self.accept_word("drop"):
            return self._drop()
        if self.accept_word("describe"):
            return ast.DescribeStatement(self.ident())
        if self.accept_word("show"):
            if self.accept_word("parameters") or self.accept_word("all"):
                return ast.ShowParameters()
            if self.accept_word("columns"):
                self.expect_word("from")
                return ast.DescribeStatement(self.ident())
            kind = self.ident()
            if kind == "materialized":
                self.expect_word("views")
                kind = "materialized views"
            return ast.ShowStatement(kind)
        if self.accept_word("alter"):
            if self.accept_word("system"):
                self.expect_word("set")
                return self._set(system=True)
            self.expect_word("materialized")
            self.expect_word("view")
            name = self.ident()
            self.expect_word("set")
            self.expect_word("parallelism")
            self.accept_op("=") or self.accept_word("to")
            t = self.next()
            if t.kind != "number" or not t.value.isdigit():
                raise ParseError("SET PARALLELISM needs an integer")
            return ast.AlterParallelism(name, int(t.value))
        if self.accept_word("set"):
            return self._set(system=False)
        if self.accept_word("insert"):
            self.expect_word("into")
            name, cols, rows = self._dml_values()
            return ast.Insert(name, tuple(cols), tuple(rows))
        if self.accept_word("delete"):
            self.expect_word("from")
            name, cols, rows = self._dml_values()
            return ast.Delete(name, tuple(cols), tuple(rows))
        if self.accept_word("update"):
            # UPDATE t SET col = lit, ... WHERE <full-pk equality> —
            # sugar the engine desugars to the exact-full-row
            # DELETE+INSERT retraction pair
            name = self.ident()
            self.expect_word("set")
            assignments = []
            while True:
                col = self.ident()
                self.expect_op("=")
                assignments.append((col, self._expr()))
                if not self.accept_op(","):
                    break
            self.expect_word("where")
            return ast.Update(name, tuple(assignments), self._expr())
        if self.accept_word("flush"):
            return ast.FlushStatement()
        if self.peek() and self.peek().value == "select":
            return self._select()
        raise ParseError(f"unsupported statement at {self.peek()}")

    def _dml_values(self):
        """Shared INSERT/DELETE tail: ``t [(col,...)] VALUES (...), ...``
        (DELETE retracts by exact full row — see ast.Delete)."""
        name = self.ident()
        cols: list[str] = []
        if self.accept_op("("):
            while True:
                cols.append(self.ident())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        self.expect_word("values")
        rows = []
        while True:
            self.expect_op("(")
            row = [self._expr()]
            while self.accept_op(","):
                row.append(self._expr())
            self.expect_op(")")
            rows.append(tuple(row))
            if not self.accept_op(","):
                break
        return name, cols, rows

    def _set(self, system: bool):
        name = self.ident()
        while self.accept_op("."):
            name += "." + self.ident()
        if not self.accept_op("="):
            self.expect_word("to")
        t = self.next()
        if t.kind == "number":
            value = float(t.value) if "." in t.value else int(t.value)
        elif t.kind == "string":
            value = t.value[1:-1]
        elif t.kind == "word" and t.value in ("true", "false"):
            value = t.value == "true"
        else:
            value = t.value
        return ast.SetStatement(name, value, system)

    # -- DDL ------------------------------------------------------------
    def _if_not_exists(self) -> bool:
        if self.accept_word("if"):
            self.expect_word("not")
            self.expect_word("exists")
            return True
        return False

    def _create(self):
        is_table = False
        if self.peek() and self.peek().value == "table":
            is_table = True
        if self.accept_word("source") or self.accept_word("table"):
            ine = self._if_not_exists()
            name = self.ident()
            columns: list[ast.ColumnDef] = []
            watermark = None
            primary_key: tuple[str, ...] = ()
            if self.accept_op("("):
                while True:
                    if self.accept_word("watermark"):
                        self.expect_word("for")
                        wcol = self.ident()
                        self.expect_word("as")
                        expr = self._expr()
                        watermark = ast.WatermarkDef(
                            wcol, self._watermark_delay(expr, wcol)
                        )
                    elif self.accept_word("primary"):
                        # table constraint: PRIMARY KEY (col, ...)
                        self.expect_word("key")
                        self.expect_op("(")
                        pk = [self.ident()]
                        while self.accept_op(","):
                            pk.append(self.ident())
                        self.expect_op(")")
                        primary_key = tuple(pk)
                    else:
                        cname = self.ident()
                        ctype = self._type_name()
                        nullable = False
                        if self.accept_word("null"):
                            nullable = True
                        elif self.accept_word("not"):
                            self.expect_word("null")
                        if self.accept_word("primary"):
                            self.expect_word("key")
                            primary_key = (cname,)
                        columns.append(
                            ast.ColumnDef(cname, ctype, nullable)
                        )
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            options = self._with_options()
            return ast.CreateSource(name, tuple(columns), watermark, options,
                                    ine, is_table, primary_key)
        if self.accept_word("sink"):
            ine = self._if_not_exists()
            name = self.ident()
            query = None
            from_rel = None
            if self.accept_word("as"):
                query = self._select()
            else:
                self.expect_word("from")
                from_rel = self.ident()
            options = self._with_options()
            return ast.CreateSink(name, query, from_rel, options, ine)
        if self.accept_word("materialized"):
            self.expect_word("view")
            ine = self._if_not_exists()
            name = self.ident()
            # WITH (ttl = '<n>', ...) rides between the name and AS
            # (the pushdown plane's expiry-policy surface)
            options = self._with_options()
            self.expect_word("as")
            query = self._select()
            eowc = False
            if self.accept_word("emit"):
                self.expect_word("on")
                self.expect_word("window")
                self.expect_word("close")
                eowc = True
            return ast.CreateMaterializedView(name, query, ine, eowc,
                                              options)
        if self.accept_word("index"):
            # CREATE INDEX name ON mv(col, ...) — a secondary-index MV
            ine = self._if_not_exists()
            name = self.ident()
            self.expect_word("on")
            table = self.ident()
            self.expect_op("(")
            cols = [self.ident()]
            while self.accept_op(","):
                cols.append(self.ident())
            self.expect_op(")")
            return ast.CreateIndex(name, table, tuple(cols), ine)
        if self.accept_word("function"):
            # CREATE FUNCTION f(a type, b type) RETURNS type
            #   LANGUAGE SQL AS $$SELECT <expr>$$
            ine = self._if_not_exists()
            name = self.ident()
            params: list[str] = []
            self.expect_op("(")
            if not (self.peek() and self.peek().value == ")"):
                while True:
                    params.append(self.ident())
                    self._type_name()  # param types are documentation
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            self.expect_word("returns")
            self._type_name()
            self.expect_word("language")
            lang = self.ident()
            if lang != "sql":
                raise ParseError(
                    f"LANGUAGE {lang} not supported (SQL UDFs only)"
                )
            self.expect_word("as")
            t = self.next()
            if t.kind == "dollar_string":
                body_sql = t.value
            elif t.kind == "string":
                body_sql = t.value[1:-1].replace("''", "'")
            else:
                raise ParseError("expected a quoted function body")
            return ast.CreateFunction(name, tuple(params), body_sql, ine)
        raise ParseError(
            "expected SOURCE, TABLE, INDEX or MATERIALIZED VIEW"
        )

    def _with_options(self) -> dict:
        options: dict = {}
        if self.accept_word("with"):
            self.expect_op("(")
            while True:
                k = self.ident()
                while self.accept_op("."):  # dotted option keys
                    k += "." + self.ident()
                self.expect_op("=")
                v = self.next()
                if v.kind == "string":
                    options[k] = v.value[1:-1].replace("''", "'")
                else:
                    options[k] = v.value
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return options

    def _watermark_delay(self, expr, wcol: str) -> ast.IntervalLit:
        """WATERMARK FOR c AS c - INTERVAL 'x' => the delay interval."""
        if isinstance(expr, ast.ColumnRef) and expr.name == wcol:
            return ast.IntervalLit(0)
        if (isinstance(expr, ast.BinaryOp) and expr.op == "subtract"
                and isinstance(expr.left, ast.ColumnRef)
                and expr.left.name == wcol
                and isinstance(expr.right, ast.IntervalLit)):
            return expr.right
        raise ParseError("watermark must be `col` or `col - INTERVAL '...'`")

    def _type_name(self) -> str:
        parts = [self.ident()]
        # multi-word types: double precision, timestamp with time zone, …
        while True:
            t = self.peek()
            if t and t.kind == "word" and t.value in (
                "precision", "varying", "with", "without", "time", "zone",
            ):
                parts.append(self.next().value)
            else:
                break
        name = " ".join(parts)
        # parameterized types: VARCHAR(100), NUMERIC(10, 2)
        if name in ("varchar", "char", "character", "character varying",
                    "decimal", "numeric") and self.accept_op("("):
            args = [self._type_param()]
            while self.accept_op(","):
                args.append(self._type_param())
            self.expect_op(")")
            name += "(" + ",".join(args) + ")"
        return name

    def _type_param(self) -> str:
        t = self.next()
        if t.kind != "number" or not t.value.lstrip("-").isdigit():
            raise ParseError(f"expected integer type parameter, got "
                             f"{t.value!r}")
        return t.value

    def _drop(self):
        # source | table | sink | index | materialized view
        kind = self.ident()
        if kind == "materialized":
            self.expect_word("view")
            kind = "materialized view"
        if_exists = False
        if self.accept_word("if"):
            self.expect_word("exists")
            if_exists = True
        return ast.DropStatement(kind, self.ident(), if_exists)

    # -- SELECT ---------------------------------------------------------
    def _select(self) -> ast.Select:
        if self.accept_word("with"):
            # WITH name [(col,...)] AS (select) [, ...] select — CTEs
            # inline as derived tables (the reference's share/DAG dedup
            # merges repeated uses back into one plan; here the DAG
            # builder's shared-source merge plays that role)
            ctes: dict[str, ast.Select] = {}
            while True:
                name = self.ident()
                cols: list[str] = []
                if self.accept_op("("):
                    while True:
                        cols.append(self.ident())
                        if not self.accept_op(","):
                            break
                    self.expect_op(")")
                self.expect_word("as")
                self.expect_op("(")
                sub = self._select()
                self.expect_op(")")
                if cols:
                    sub = _realias(sub, cols)
                ctes[name] = sub
                if not self.accept_op(","):
                    break
            body = self._select()
            return _substitute_ctes(body, ctes)
        self.expect_word("select")
        items = []
        while True:
            if self.accept_op("*"):
                items.append(ast.SelectItem(ast.Star(), None))
            else:
                e = self._expr()
                alias = None
                if self.accept_word("as"):
                    alias = self.ident()
                elif (self.peek() and self.peek().kind == "word"
                      and self.peek().value not in (
                          "from", "where", "group", "having", "order",
                          "limit", "offset", "emit",
                      )):
                    alias = self.ident()
                items.append(ast.SelectItem(e, alias))
            if not self.accept_op(","):
                break
        from_ = None
        if self.accept_word("from"):
            from_ = self._table_expr()
        where = self._expr() if self.accept_word("where") else None
        group_by: list = []
        if self.accept_word("group"):
            self.expect_word("by")
            while True:
                group_by.append(self._expr())
                if not self.accept_op(","):
                    break
        having = self._expr() if self.accept_word("having") else None
        order_by: list[ast.OrderItem] = []
        if self.accept_word("order"):
            self.expect_word("by")
            while True:
                e = self._expr()
                desc = False
                if self.accept_word("desc"):
                    desc = True
                elif self.accept_word("asc"):
                    pass
                order_by.append(ast.OrderItem(e, desc))
                if not self.accept_op(","):
                    break
        limit = offset = None
        if self.accept_word("limit"):
            limit = int(self.next().value)
        if self.accept_word("offset"):
            offset = int(self.next().value)
        return ast.Select(
            tuple(items), from_, where, tuple(group_by), having,
            tuple(order_by), limit, offset,
        )

    def _table_expr(self):
        left = self._table_factor()
        while True:
            kind = None
            if self.accept_op(","):
                # comma join: equi-conditions live in WHERE (the
                # planner mines them — classic implicit-join rewrite)
                right = self._table_factor()
                left = ast.Join(left, right, None, "cross")
                continue
            if self.accept_word("join") or self.accept_word("inner"):
                if self.peek() and self.peek().value == "join":
                    self.next()
                kind = "inner"
            elif self.accept_word("left"):
                self.accept_word("outer")
                self.expect_word("join")
                kind = "left"
            elif self.accept_word("right"):
                self.accept_word("outer")
                self.expect_word("join")
                kind = "right"
            elif self.accept_word("full"):
                self.accept_word("outer")
                self.expect_word("join")
                kind = "full"
            else:
                break
            right = self._table_factor()
            self.expect_word("on")
            on = self._expr()
            if getattr(right, "temporal", False):
                if kind not in ("inner", "left"):
                    raise ParseError(
                        "FOR SYSTEM_TIME joins support INNER/LEFT"
                    )
                kind = "temporal" if kind == "inner" else "temporal_left"
            left = ast.Join(left, right, on, kind)
        return left

    def _table_factor(self):
        t = self.peek()
        if t and t.kind == "op" and t.value == "(":
            # derived table: ( SELECT ... ) [AS] alias [(col, ...)]
            self.expect_op("(")
            select = self._select()
            self.expect_op(")")
            alias = None
            if self.accept_word("as"):
                alias = self.ident()
            elif (self.peek() and self.peek().kind == "word"
                  and self.peek().value not in (
                      "join", "inner", "left", "right", "full", "on",
                      "where", "group", "having", "order", "limit",
                      "offset", "emit",
                  )):
                alias = self.ident()
            if alias is not None and self.accept_op("("):
                # column alias list renames the derived table's output
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                select = _realias(select, cols)
            return ast.SubqueryRef(select, alias)
        if t and t.value in ("tumble", "hop"):
            fn = self.next().value
            self.expect_op("(")
            table = ast.TableRef(self.ident())
            self.expect_op(",")
            col = self.ident()
            self.expect_op(",")
            iv1 = self._expr()
            iv2 = None
            if fn == "hop":
                self.expect_op(",")
                iv2 = self._expr()
            self.expect_op(")")
            alias = None
            if self.accept_word("as"):
                alias = self.ident()
            elif (self.peek() and self.peek().kind == "word"
                  and self.peek().value not in (
                      "join", "inner", "left", "right", "full", "on",
                      "where", "group", "having", "order", "limit",
                      "offset", "emit",
                  )):
                alias = self.ident()
            if fn == "tumble":
                return ast.Tumble(table, col, iv1, alias)
            return ast.Hop(table, col, iv1, iv2, alias)
        name = self.ident()
        temporal = False
        if (self.peek() and self.peek().value == "for"
                and self.peek(1) and self.peek(1).value == "system_time"):
            # t FOR SYSTEM_TIME AS OF PROCTIME(): temporal-join build
            self.next()
            self.next()
            self.expect_word("as")
            self.expect_word("of")
            self.expect_word("proctime")
            self.expect_op("(")
            self.expect_op(")")
            temporal = True
        alias = None
        if self.accept_word("as"):
            alias = self.ident()
        elif (self.peek() and self.peek().kind == "word"
              and self.peek().value not in (
                  "join", "inner", "left", "right", "full", "on", "where",
                  "group", "having", "order", "limit", "offset", "emit",
                  "for",
              )):
            alias = self.ident()
        return ast.TableRef(name, alias, temporal)

    # -- expressions ----------------------------------------------------
    def _expr(self, min_prec: int = 0):
        left = self._unary()
        while True:
            t = self.peek()
            if t is None:
                break
            if t.kind == "word" and t.value in ("like", "between", "in",
                                                "is", "not") \
                    and min_prec <= 4:
                parsed = self._word_op(left)
                if parsed is None:
                    break
                left = parsed
                continue
            op = t.value if t.kind == "op" else (
                t.value if t.kind == "word" and t.value in ("and", "or")
                else None
            )
            if op is None or op not in _PRECEDENCE:
                break
            prec = _PRECEDENCE[op]
            if prec < min_prec:
                break
            self.next()
            right = self._expr(prec + 1)
            left = ast.BinaryOp(_BIN_NAMES[op], left, right)
        return left

    def _word_op(self, left):
        """LIKE / BETWEEN / IN / IS [NOT] NULL postfix operators."""
        negate = False
        if self.peek().value == "not":
            nxt = self.peek(1)
            if not (nxt and nxt.kind == "word"
                    and nxt.value in ("like", "between", "in")):
                return None
            self.next()
            negate = True
        w = self.next().value
        if w == "like":
            pat = self._expr(5)
            out = ast.FuncCall("like", (left, pat))
        elif w == "between":
            lo = self._expr(3)  # stop before AND
            self.expect_word("and")
            hi = self._expr(3)
            out = ast.BinaryOp(
                "and",
                ast.BinaryOp("greater_than_or_equal", left, lo),
                ast.BinaryOp("less_than_or_equal", left, hi),
            )
        elif w == "in":
            self.expect_op("(")
            t = self.peek()
            if t and t.kind == "word" and t.value == "select":
                sub = self._select()
                self.expect_op(")")
                return ast.InSubquery(left, sub, negated=negate)
            items = [self._expr()]
            while self.accept_op(","):
                items.append(self._expr())
            self.expect_op(")")
            out = None
            for it in items:
                eq = ast.BinaryOp("equal", left, it)
                out = eq if out is None else ast.BinaryOp("or", out, eq)
        elif w == "is":
            neg_is = self.accept_word("not")
            self.expect_word("null")
            out = ast.FuncCall(
                "is_not_null" if neg_is else "is_null", (left,)
            )
        else:
            raise ParseError(f"unexpected {w}")
        if negate:
            out = ast.UnaryOp("not", out)
        return out

    def _unary(self):
        if self.accept_op("-"):
            return ast.UnaryOp("neg", self._unary())
        if self.accept_word("not"):
            # postgres: NOT binds LOOSER than LIKE/BETWEEN/IN/comparisons
            return ast.UnaryOp("not", self._expr(3))
        return self._postfix(self._primary())

    def _postfix(self, e):
        while True:
            if self.accept_op("::"):
                e = ast.Cast(e, self._type_name())
                continue
            if self.accept_op("["):
                t = self.next()
                if t.kind != "number" or not t.value.isdigit():
                    raise ParseError(
                        "only literal integer array subscripts are "
                        "supported"
                    )
                self.expect_op("]")
                e = ast.FuncCall(
                    "array_index", (e, ast.Literal(int(t.value), "int"))
                )
                continue
            return e

    def _primary(self):
        t = self.next()
        if t.kind == "number":
            if "." in t.value:
                return ast.Literal(float(t.value), "float")
            return ast.Literal(int(t.value), "int")
        if t.kind == "string":
            return ast.Literal(t.value[1:-1].replace("''", "'"), "string")
        if t.kind == "op" and t.value == "(":
            nxt = self.peek()
            if nxt and nxt.kind == "word" and nxt.value == "select":
                sub = self._select()
                self.expect_op(")")
                return ast.ScalarSubquery(sub)
            e = self._expr()
            self.expect_op(")")
            return e
        if t.kind != "word":
            raise ParseError(f"unexpected token {t.value!r}")
        w = t.value
        if w == "interval":
            s = self.next()
            if s.kind != "string":
                raise ParseError("expected INTERVAL 'value'")
            return self._interval(s.value[1:-1])
        if w in ("date", "timestamp") and self.peek() \
                and self.peek().kind == "string":
            # typed literal: DATE '1994-01-01' / TIMESTAMP '… …'
            raw = self.next().value[1:-1]
            return self._datetime_literal(w, raw)
        if w == "exists" and self.peek() \
                and self.peek().value == "(":
            self.expect_op("(")
            sub = self._select()
            self.expect_op(")")
            return ast.ExistsSubquery(sub)
        if w == "substring" and self.accept_op("("):
            # substring(s FROM a [FOR n]) — also accept the plain
            # comma form through the generic call path below is NOT
            # possible once '(' is consumed, so handle both here
            e = self._expr()
            if self.accept_word("from"):
                start = self._expr()
                count = None
                if self.accept_word("for"):
                    count = self._expr()
                self.expect_op(")")
                args = (e, start) if count is None else (e, start, count)
                return ast.FuncCall("substr", args)
            args = [e]
            while self.accept_op(","):
                args.append(self._expr())
            self.expect_op(")")
            return ast.FuncCall("substr", tuple(args))
        if w in ("true", "false"):
            return ast.Literal(w == "true", "bool")
        if w == "null":
            return ast.Literal(None, "null")
        if w == "case":
            conds = []
            while self.accept_word("when"):
                c = self._expr()
                self.expect_word("then")
                r = self._expr()
                conds.append((c, r))
            els = None
            if self.accept_word("else"):
                els = self._expr()
            self.expect_word("end")
            return ast.Case(tuple(conds), els)
        if w == "extract":
            self.expect_op("(")
            part = self.ident()
            self.expect_word("from")
            e = self._expr()
            self.expect_op(")")
            return ast.FuncCall(f"extract_{part}", (e,))
        if w == "cast":
            self.expect_op("(")
            e = self._expr()
            self.expect_word("as")
            tn = self._type_name()
            self.expect_op(")")
            return ast.Cast(e, tn)
        if self.accept_op("("):
            distinct = bool(self.accept_word("distinct"))
            args: list = []
            if self.accept_op("*"):
                args.append(ast.Star())
            elif not (self.peek() and self.peek().value == ")"):
                while True:
                    args.append(self._expr())
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            if self.accept_word("over"):
                if distinct:
                    raise ParseError(
                        "DISTINCT in window functions is not supported"
                    )
                self.expect_op("(")
                part: list = []
                if self.accept_word("partition"):
                    self.expect_word("by")
                    while True:
                        part.append(self._expr())
                        if not self.accept_op(","):
                            break
                ob: list = []
                if self.accept_word("order"):
                    self.expect_word("by")
                    while True:
                        e = self._expr()
                        desc = bool(self.accept_word("desc"))
                        if not desc:
                            self.accept_word("asc")
                        ob.append(ast.OrderItem(e, desc))
                        if not self.accept_op(","):
                            break
                frame = self._window_frame()
                self.expect_op(")")
                return ast.WindowCall(w, tuple(args), tuple(part),
                                      tuple(ob), frame=frame)
            fc = ast.FuncCall(w, tuple(args), distinct)
            if self.accept_word("filter"):
                self.expect_op("(")
                self.expect_word("where")
                cond = self._expr()
                self.expect_op(")")
                fc = ast.FuncCall(w, tuple(args), distinct,
                                  filter_where=cond)
            return fc
        if self.accept_op("."):
            if self.accept_op("*"):
                return ast.Star(table=w)
            return ast.ColumnRef(self.ident(), table=w)
        return ast.ColumnRef(w)

    def _window_frame(self):
        """ROWS BETWEEN <n> PRECEDING AND CURRENT ROW (the benchmark
        frame shape); returns (preceding, following) or None."""
        if not self.accept_word("rows"):
            return None

        def bound(start: bool) -> int:
            if self.accept_word("current"):
                self.expect_word("row")
                return 0
            if self.accept_word("unbounded"):
                self.expect_word("preceding" if start else "following")
                return -1  # unbounded sentinel
            t = self.next()
            if t.kind != "number":
                raise ParseError(f"expected frame bound, got {t.value!r}")
            n = int(t.value)
            self.expect_word("preceding" if start else "following")
            return n

        self.expect_word("between")
        pre = bound(True)
        self.expect_word("and")
        fol = bound(False)
        return (pre, fol)

    def _datetime_literal(self, kind: str, raw: str):
        """DATE 'Y-m-d' → days since epoch; TIMESTAMP → microseconds."""
        import datetime as _dt
        try:
            if kind == "date":
                d = _dt.date.fromisoformat(raw.strip())
                return ast.Literal(
                    (d - _dt.date(1970, 1, 1)).days, "date"
                )
            ts = _dt.datetime.fromisoformat(raw.strip())
            epoch = _dt.datetime(1970, 1, 1)
            # exact integer microseconds (float total_seconds() rounds)
            return ast.Literal(
                (ts - epoch) // _dt.timedelta(microseconds=1),
                "timestamp",
            )
        except ValueError as e:
            raise ParseError(f"bad {kind} literal {raw!r}: {e}")

    def _interval(self, text: str) -> ast.IntervalLit:
        m = re.match(r"^\s*(\d+)\s*([a-zA-Z]+)?\s*$", text)
        if not m:
            raise ParseError(f"bad interval {text!r}")
        n = int(m.group(1))
        unit = (m.group(2) or "second").lower()
        # also accept the unit as the next word: INTERVAL '10' SECOND
        if m.group(2) is None and self.peek() and self.peek().kind == "word" \
                and self.peek().value in (_INTERVAL_UNITS.keys()
                                          | _INTERVAL_MONTH_UNITS.keys()):
            unit = self.next().value
        if unit in _INTERVAL_MONTH_UNITS:
            return ast.IntervalLit(0, months=n * _INTERVAL_MONTH_UNITS[unit])
        if unit not in _INTERVAL_UNITS:
            raise ParseError(f"unsupported interval unit {unit!r}")
        return ast.IntervalLit(n * _INTERVAL_UNITS[unit])


def _realias(select: ast.Select, cols: list[str]) -> ast.Select:
    """Apply a column alias list to a SELECT's output items."""
    import dataclasses
    items = select.items
    if len(cols) != len(items) or any(
            isinstance(i.expr, ast.Star) for i in items):
        raise ParseError(
            f"column alias list has {len(cols)} names for "
            f"{len(items)} output columns"
        )
    return dataclasses.replace(select, items=tuple(
        ast.SelectItem(i.expr, c) for i, c in zip(items, cols)
    ))


def _substitute_ctes(node, ctes: dict):
    """Deep-rewrite TableRefs naming a CTE into derived tables.

    Covers FROM trees and subqueries inside expressions (IN / EXISTS /
    scalar subqueries) — e.g. TPC-H q15 uses its CTE both in FROM and
    in a scalar subquery."""
    import dataclasses

    def walk(x):
        if isinstance(x, ast.TableRef) and x.name in ctes:
            return ast.SubqueryRef(ctes[x.name], x.alias or x.name)
        if isinstance(x, (ast.Tumble, ast.Hop)):
            return dataclasses.replace(x, table=walk(x.table))
        if isinstance(x, ast.Join):
            return dataclasses.replace(
                x, left=walk(x.left), right=walk(x.right),
                on=walk(x.on) if x.on is not None else None,
            )
        if isinstance(x, ast.Select):
            return dataclasses.replace(
                x,
                items=tuple(
                    ast.SelectItem(walk(i.expr), i.alias)
                    if not isinstance(i.expr, ast.Star) else i
                    for i in x.items
                ),
                from_=walk(x.from_) if x.from_ is not None else None,
                where=walk(x.where) if x.where is not None else None,
                group_by=tuple(walk(g) for g in x.group_by),
                having=walk(x.having) if x.having is not None else None,
                order_by=tuple(
                    ast.OrderItem(walk(o.expr), o.descending)
                    for o in x.order_by
                ),
            )
        if isinstance(x, ast.ScalarSubquery):
            return ast.ScalarSubquery(walk(x.select))
        if isinstance(x, ast.ExistsSubquery):
            return ast.ExistsSubquery(walk(x.select))
        if isinstance(x, ast.InSubquery):
            return ast.InSubquery(walk(x.expr), walk(x.select),
                                  x.negated)
        if isinstance(x, ast.BinaryOp):
            return ast.BinaryOp(x.op, walk(x.left), walk(x.right))
        if isinstance(x, ast.UnaryOp):
            return ast.UnaryOp(x.op, walk(x.operand))
        if isinstance(x, ast.Case):
            return ast.Case(
                tuple((walk(c), walk(r)) for c, r in x.conditions),
                walk(x.else_result) if x.else_result is not None
                else None,
            )
        if isinstance(x, ast.FuncCall):
            return dataclasses.replace(x, args=tuple(
                a if isinstance(a, ast.Star) else walk(a)
                for a in x.args
            ), filter_where=(walk(x.filter_where)
                             if x.filter_where is not None else None))
        if isinstance(x, ast.Cast):
            return dataclasses.replace(x, operand=walk(x.operand))
        return x

    return walk(node)


def parse(sql: str):
    """Parse one or more ;-separated statements."""
    return [stmt for _, stmt in parse_with_text(sql)]


def parse_with_text(sql: str):
    """Parse statements keeping each one's raw SQL text (the durable
    DDL log records the text, not the AST)."""
    out = []
    for part in _split_statements(sql):
        p = Parser(part)
        stmt = p.parse_statement()
        if p.peek() is not None:
            raise ParseError(f"trailing tokens at {p.peek()}")
        out.append((part, stmt))
    return out


def _split_statements(sql: str) -> list[str]:
    # split on ; outside string literals and -- comments
    out: list[str] = []
    cur: list[str] = []
    i, n = 0, len(sql)
    in_str = in_comment = False
    while i < n:
        ch = sql[i]
        if in_comment:
            if ch == "\n":
                in_comment = False
            cur.append(ch)
        elif in_str:
            if ch == "'":
                in_str = False
            cur.append(ch)
        elif ch == "'":
            in_str = True
            cur.append(ch)
        elif ch == "-" and i + 1 < n and sql[i + 1] == "-":
            in_comment = True
            cur.append(ch)
        elif ch == ";":
            stmt = "".join(cur).strip()
            if stmt:
                out.append(stmt)
            cur = []
        else:
            cur.append(ch)
        i += 1
    stmt = "".join(cur).strip()
    if stmt:
        out.append(stmt)
    return out
