"""Planner: bound SELECT → streaming executor pipelines.

Reference counterpart: ``src/frontend/src/planner`` + ``optimizer`` +
``stream_fragmenter`` — collapsed into direct executor-pipeline
construction for the supported plan shapes:

- stateless:   source → [wm filter] → project/filter → ring MV
- aggregation: source → [wm filter] → [window] → hash agg → project → MV
- TopN:        ... → group/plain TopN → MV
- join:        two sources → per-side prep → hash join → project → MV

The reference's Distribution property (distribution.rs:68) maps to the
vnode/shard axis; this planner emits single-mesh pipelines and the
sharded runtime applies the hash exchange at the agg/join boundary.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from risingwave_tpu.common.types import Schema
from risingwave_tpu.expr.node import Expr, FuncCall as EFuncCall, InputRef
from risingwave_tpu.meta.catalog import Catalog
from risingwave_tpu.sql import ast
from risingwave_tpu.expr.agg import AggCall
from risingwave_tpu.sql.binder import AGG_NAMES, AggRef, BindError, Binder, Scope
from risingwave_tpu.stream.executor import (
    Executor,
    FilterExecutor,
    HopWindowExecutor,
    ProjectExecutor,
)
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.hash_agg import HashAggExecutor
from risingwave_tpu.stream.hash_join import HashJoinExecutor
from risingwave_tpu.stream.materialize import (
    AppendOnlyMaterialize,
    MaterializeExecutor,
)
from risingwave_tpu.stream.top_n import GroupTopNExecutor
from risingwave_tpu.stream.watermark import WatermarkFilterExecutor


class PlanError(ValueError):
    pass


@dataclass
class PlannedInput:
    """One stream input after FROM resolution."""

    reader: Any                  # source reader (next_chunk())
    executors: list[Executor]    # prep chain (wm filter, window, ...)
    scope: Scope
    schema: Schema
    watermark_col: int | None    # col idx in `schema` carrying event time
    window_size: int | None      # tumble/hop size (for cleaning lag)
    append_only: bool
    #: hop slide (== window_size for tumble; None when unwindowed)
    window_slide: "int | None" = None
    #: column positions uniquely identifying a row of this input's
    #: changelog (the reference's *stream key*) — required to key the
    #: materialization of retractable non-agg plans
    stream_key: "list[int] | None" = None


@dataclass
class UnaryPlan:
    reader: Any
    fragment: Fragment
    mv_index: int                # executor index of the MV in the fragment
    #: the source stream never retracts (gates the two-phase rewrite)
    append_only: bool = True


@dataclass
class MvTap:
    """A FROM item that is an existing MV: the plan consumes that MV's
    output changelog (ref: MV-on-MV via the upstream materialize
    fragment's dispatcher).  The engine resolves the tap to the running
    job's materialize node at CREATE time."""

    name: str


@dataclass
class DagPlan:
    """A dataflow graph plan: joins (possibly nested), cascades, shared
    inputs (ref stream_fragmenter/mod.rs:388 building a fragment graph).

    ``nodes`` uses the runtime's FragNode/JoinNode with plan-local refs:
    ("source", name) keys into ``sources`` (a reader or an MvTap);
    ("node", i) indexes ``nodes``.
    """

    sources: dict[str, Any]
    nodes: list
    mv_node: int                 # node holding the terminal executor
    mv_index: int                # executor index within that node


@dataclass
class GroupTopNSpec:
    """A row_number-in-subquery TopN rewrite in flight.

    Ref: the reference plans ``SELECT .. FROM (SELECT *, ROW_NUMBER()
    OVER (PARTITION BY p ORDER BY o) rn FROM t) WHERE rn <= k`` as a
    StreamGroupTopN (optimizer/rule/over_window_to_topn_rule.rs); this
    carries the pieces through the inner plan's construction."""

    partition: tuple        # ast exprs, inner FROM scope
    order: tuple            # ast OrderItems, inner FROM scope
    limit: int
    offset: int
    outer_items: tuple      # outer SELECT items (inner-output scope)
    outer_where: tuple      # residual outer conjuncts
    alias: "str | None"     # subquery alias
    rank_alias: "str | None" = None  # emit the in-band row_number as this


@dataclass
class PlannerConfig:
    agg_table_size: int = 1 << 16
    agg_emit_capacity: int = 4096
    join_table_size: int = 1 << 14
    join_bucket_cap: int = 64
    join_out_capacity: int = 1 << 15
    join_left_table_size: int | None = None
    join_right_table_size: int | None = None
    join_left_bucket_cap: int | None = None
    join_right_bucket_cap: int | None = None
    #: shared row-pool capacity for degree-adaptive (append-only) join
    #: sides — replaces dense [size, bucket] buckets so hot keys have
    #: no per-key cap (ref JoinHashMap's unbounded per-key rows)
    join_pool_size: int = 1 << 16
    #: force dense per-key bucket storage even for append-only sides.
    #: Pool sides bound emission drains by the POOL size, which makes
    #: `max_windows` large; on deep multiway plans (TPC-H q8/q9) the
    #: drain while_loop bodies then embed the downstream subgraph and
    #: XLA:CPU compile memory explodes.  Dense buckets bound drains by
    #: bucket_cap — with out_capacity >= chunk*2*bucket_cap the plan
    #: compiles FLAT (no drain loops).  Conformance runs set this.
    join_force_dense: bool = False
    topn_pool_size: int = 4096
    topn_emit_capacity: int = 1024
    mv_table_size: int = 1 << 16
    mv_ring_size: int = 1 << 20
    chunk_capacity: int = 4096
    #: per-group value capacity for retractable min/max (ref minput.rs)
    minput_bucket_cap: int = 64
    #: dedup-table size per DISTINCT agg call (None = agg_table_size);
    #: sized for groups x distinct values, not groups
    distinct_table_size: "int | None" = None
    #: overflow-row ring capacity for non-windowed aggs (None = 4x
    #: chunk_capacity; 0 disables spill-to-host — overflow is then a
    #: loud error)
    agg_spill_ring: "int | None" = None
    #: host-tier table size (None = 8x agg_table_size)
    agg_spill_table_size: "int | None" = None


class Planner:
    def __init__(self, catalog: Catalog,
                 config: PlannerConfig | None = None):
        self.catalog = catalog
        self.config = config or PlannerConfig()
        #: session streaming_parallelism at plan time (engine-set):
        #: >1 keeps plans in shapes the sharded runtime can take over
        #: (the pane rewrite produces a 2-agg chain it can't, yet)
        self.parallel_hint = 1

    # ------------------------------------------------------------------
    def plan(self, select: ast.Select, sink=None, eowc: bool = False,
             group_topn: "GroupTopNSpec | None" = None
             ) -> "UnaryPlan | DagPlan":
        """``sink`` replaces the MV terminal; ``eowc`` = EMIT ON WINDOW
        CLOSE (final append-only rows when windows close)."""
        def has_subquery(f) -> bool:
            if isinstance(f, ast.SubqueryRef):
                return True
            if isinstance(f, ast.Join):
                return has_subquery(f.left) or has_subquery(f.right)
            return False

        if group_topn is None:
            rewritten = self._match_group_topn(select)
            if rewritten is not None:
                inner, spec = rewritten
                return self.plan(inner, sink=sink, eowc=eowc,
                                 group_topn=spec)
        select = self._factor_where(select)
        select = self._rewrite_in_subqueries(select)
        select = self._rewrite_exists_subqueries(select)
        select = self._rewrite_correlated_scalar(select)

        if isinstance(select.from_, ast.Join) or has_subquery(select.from_):
            if eowc:
                raise PlanError(
                    "EMIT ON WINDOW CLOSE on joins/subqueries: next round"
                )
            return self._plan_join(select, sink, group_topn=group_topn)
        plan = self._plan_unary(select, sink, eowc, group_topn=group_topn)
        if isinstance(plan.reader, MvTap):
            # cascade: a single fragment node tapping the upstream MV
            from risingwave_tpu.stream.dag import FragNode
            return DagPlan(
                sources={plan.reader.name: plan.reader},
                nodes=[FragNode(plan.fragment,
                                ("source", plan.reader.name))],
                mv_node=0, mv_index=plan.mv_index,
            )
        return plan

    # -- IN (SELECT ...) rewrite ----------------------------------------
    def _rewrite_in_subqueries(self, select: ast.Select) -> ast.Select:
        """``x [NOT] IN (SELECT c FROM ...)`` conjuncts become semi/anti
        joins against the subquery (ref: the reference's apply-to-join
        subquery unnesting, optimizer/rule/ — RisingWave plans the same
        shape as StreamHashJoin LeftSemi/LeftAnti).

        NOTE NULL semantics: ``NOT IN`` with NULLs in the subquery is
        three-valued in SQL (never true); the anti join here treats
        NULL keys as non-matching.  The benchmark columns are NOT NULL.
        """
        if select.where is None:
            return select
        conjs = self._conjuncts(select.where)
        ins = [c for c in conjs if isinstance(c, ast.InSubquery)]
        if not ins:
            return select
        rest = [c for c in conjs if not isinstance(c, ast.InSubquery)]
        from_ = select.from_
        for k, c in enumerate(ins):
            sub = c.select
            if len(sub.items) != 1 or isinstance(sub.items[0].expr,
                                                 ast.Star):
                raise PlanError(
                    "IN subquery must select exactly one column"
                )
            alias = f"_in_sq{k}"
            col_name = sub.items[0].alias or self._default_name(
                sub.items[0].expr, 0
            )
            from_ = ast.Join(
                left=from_,
                right=ast.SubqueryRef(sub, alias),
                on=ast.BinaryOp("equal", c.expr,
                                ast.ColumnRef(col_name, alias)),
                kind="anti" if c.negated else "semi",
            )
        where = None
        for r in rest:
            where = r if where is None else ast.BinaryOp("and", where, r)
        import dataclasses
        return dataclasses.replace(select, from_=from_, where=where)

    # -- OR common-conjunct factoring -----------------------------------
    def _factor_where(self, select: ast.Select) -> ast.Select:
        if select.where is None:
            return select
        new = self._factor_or(select.where)
        if new is select.where:
            return select
        import dataclasses
        return dataclasses.replace(select, where=new)

    def _factor_or(self, e):
        """``(A AND e) OR (B AND e) → e AND (A OR B)``: lifts
        predicates duplicated across every OR branch — notably the
        equi-join conditions TPC-H q19 repeats per branch — up to the
        conjunct level where comma-join mining can consume them (ref:
        the reference optimizer's common-factor extraction in
        condition rewriting)."""
        if isinstance(e, ast.BinaryOp) and e.op == "and":
            lf = self._factor_or(e.left)
            rf = self._factor_or(e.right)
            if lf is e.left and rf is e.right:
                return e
            return ast.BinaryOp("and", lf, rf)
        if not (isinstance(e, ast.BinaryOp) and e.op == "or"):
            return e
        branches: list = []

        def collect(x) -> None:
            if isinstance(x, ast.BinaryOp) and x.op == "or":
                collect(x.left)
                collect(x.right)
            else:
                branches.append(self._factor_or(x))

        collect(e)
        conj_lists = [self._conjuncts(b) for b in branches]
        common: list = []
        for c in conj_lists[0]:
            if any(c == x for x in common):
                continue
            if all(any(c == d for d in cl) for cl in conj_lists[1:]):
                common.append(c)
        if not common:
            return e

        def and_fold(parts):
            out = None
            for p in parts:
                out = p if out is None else ast.BinaryOp("and", out, p)
            return out

        residues: list = []
        some_branch_empty = False
        for cl in conj_lists:
            rem = list(cl)
            for c in common:
                for j, d in enumerate(rem):
                    if d == c:
                        rem.pop(j)
                        break
            if not rem:
                # this branch is exactly the common part: the OR of
                # residues is vacuously true
                some_branch_empty = True
                break
            residues.append(and_fold(rem))
        parts = list(common)
        if not some_branch_empty:
            out = None
            for r in residues:
                out = r if out is None else ast.BinaryOp("or", out, r)
            parts.append(out)
        return and_fold(parts)

    # -- EXISTS rewrite -------------------------------------------------
    def _from_name_sets(self, from_):
        """(names, (qual, name) pairs) visible from a FROM tree — used
        to split an EXISTS subquery's predicates into local vs
        correlated (outer) references."""
        names: set = set()
        quals: set = set()
        if isinstance(from_, ast.Join):
            for side in (from_.left, from_.right):
                n, q = self._from_name_sets(side)
                names |= n
                quals |= q
            return names, quals
        if isinstance(from_, ast.SubqueryRef):
            for i, it in enumerate(from_.select.items):
                if isinstance(it.expr, ast.Star):
                    n, q = self._from_name_sets(from_.select.from_)
                    names |= n
                    continue
                nm = it.alias or self._default_name(it.expr, i)
                names.add(nm)
                if from_.alias:
                    quals.add((from_.alias, nm))
            return names, quals
        if isinstance(from_, (ast.Tumble, ast.Hop)):
            n, q = self._from_name_sets(from_.table)
            names |= n | {"window_start", "window_end"}
            return names, quals
        # TableRef
        try:
            entry = self.catalog.get(from_.name)
        except Exception:
            return names, quals
        qual = from_.alias or from_.name
        for f in entry.schema:
            names.add(f.name)
            quals.add((qual, f.name))
        return names, quals

    def _rewrite_exists_subqueries(self, select: ast.Select) -> ast.Select:
        """``[NOT] EXISTS (SELECT .. FROM u WHERE u.k = outer.k AND
        <local>)`` conjuncts become semi/anti joins on the correlated
        equi keys, with local predicates pushed into the subquery
        (ref: the reference's correlated-subquery unnesting to
        StreamHashJoin LeftSemi/LeftAnti, optimizer/rule/
        apply_join_transpose_rule.rs and kin)."""
        if select.where is None:
            return select
        conjs = self._conjuncts(select.where)
        hits = []
        for c in conjs:
            if isinstance(c, ast.ExistsSubquery):
                hits.append((c, c.select, False))
            elif (isinstance(c, ast.UnaryOp) and c.op == "not"
                    and isinstance(c.operand, ast.ExistsSubquery)):
                hits.append((c, c.operand.select, True))
        if not hits:
            return select
        rest = [c for c in conjs
                if not any(c is h[0] for h in hits)]
        from_ = select.from_
        for k, (_, sub, negated) in enumerate(hits):
            sub_names, sub_quals = self._from_name_sets(sub.from_)

            def is_local(e) -> bool:
                if not isinstance(e, ast.ColumnRef):
                    return False
                if e.table is not None:
                    return (e.table, e.name) in sub_quals
                return e.name in sub_names

            local: list = []
            join_keys: list = []  # (sub_col: ColumnRef, outer_expr)
            neq: list = []        # (sub_col: ColumnRef, outer_expr)
            sub_conjs = self._conjuncts(sub.where) \
                if sub.where is not None else []
            for sc in sub_conjs:
                refs = self._column_refs(sc)
                if refs and all(is_local(r) for r in refs):
                    local.append(sc)
                    continue
                if (isinstance(sc, ast.BinaryOp)
                        and sc.op in ("equal", "not_equal")):
                    a, b = sc.left, sc.right
                    bucket = join_keys if sc.op == "equal" else neq
                    if isinstance(a, ast.ColumnRef) \
                            and isinstance(b, ast.ColumnRef):
                        if is_local(a) and not is_local(b):
                            bucket.append((a, b))
                            continue
                        if is_local(b) and not is_local(a):
                            bucket.append((b, a))
                            continue
                raise PlanError(
                    "EXISTS supports correlated equality predicates "
                    f"only (got {sc!r})"
                )
            if not join_keys:
                raise PlanError(
                    "EXISTS subquery must correlate on at least one "
                    "equality with the outer query"
                )
            if len(neq) > 1:
                raise PlanError(
                    "EXISTS supports at most ONE correlated "
                    "non-equality predicate (the min/max "
                    "decorrelation does not compose across columns)"
                )
            alias = f"_ex_sq{k}"
            import dataclasses
            lwhere = None
            for c2 in local:
                lwhere = c2 if lwhere is None \
                    else ast.BinaryOp("and", lwhere, c2)
            items = tuple(
                ast.SelectItem(sc_col, f"_exk{j}")
                for j, (sc_col, _) in enumerate(join_keys)
            )
            if not neq:
                sub2 = dataclasses.replace(
                    sub, items=items, where=lwhere, group_by=(),
                    having=None, order_by=(), limit=None, offset=None,
                )
                on = None
                for j, (_, outer_e) in enumerate(join_keys):
                    eq = ast.BinaryOp(
                        "equal", outer_e,
                        ast.ColumnRef(f"_exk{j}", alias),
                    )
                    on = eq if on is None else ast.BinaryOp("and", on, eq)
                from_ = ast.Join(
                    left=from_, right=ast.SubqueryRef(sub2, alias),
                    on=on, kind="anti" if negated else "semi",
                )
                continue
            # ONE correlated non-equality (q21's ``l2.l_suppkey <>
            # l1.l_suppkey``): decorrelate through min/max.  Group the
            # subquery by its equi keys carrying min/max/count of the
            # non-equality column; "some row with n_col <> e exists" is
            # exactly ``min <> e OR max <> e`` over the group's
            # non-NULL values, evaluated as a residual filter after an
            # ordinary equi join — so the hash join stays pure equi
            # and its per-key degree bookkeeping untouched.
            n_col, outer_e = neq[0]
            items = items + (
                ast.SelectItem(
                    ast.FuncCall("min", (n_col,)), "_exmn"),
                ast.SelectItem(
                    ast.FuncCall("max", (n_col,)), "_exmx"),
                ast.SelectItem(
                    ast.FuncCall("count", (n_col,)), "_exct"),
            )
            sub2 = dataclasses.replace(
                sub, items=items, where=lwhere,
                group_by=tuple(sc_col for sc_col, _ in join_keys),
                having=None, order_by=(), limit=None, offset=None,
            )
            on = None
            for j, (_, oe) in enumerate(join_keys):
                eq = ast.BinaryOp(
                    "equal", oe, ast.ColumnRef(f"_exk{j}", alias)
                )
                on = eq if on is None else ast.BinaryOp("and", on, eq)
            mn = ast.ColumnRef("_exmn", alias)
            mx = ast.ColumnRef("_exmx", alias)
            if not negated:
                # EXISTS: inner join (grouped sub has ≤1 row per key,
                # no duplication); all-NULL groups or a NULL outer
                # expression make the residual NULL → filtered, which
                # matches ``n_col <> e`` never being true there
                from_ = ast.Join(
                    left=from_, right=ast.SubqueryRef(sub2, alias),
                    on=on, kind="inner",
                )
                rest.append(ast.BinaryOp(
                    "or",
                    ast.BinaryOp("not_equal", mn, outer_e),
                    ast.BinaryOp("not_equal", mx, outer_e),
                ))
            else:
                # NOT EXISTS holds when: no key-group at all (left
                # outer join produced NULLs), or the group has no
                # non-NULL n_col (count = 0), or the outer expression
                # is NULL (<> never true), or every non-NULL value
                # equals it (min = e AND max = e)
                from_ = ast.Join(
                    left=from_, right=ast.SubqueryRef(sub2, alias),
                    on=on, kind="left",
                )
                no_group = ast.FuncCall(
                    "is_null", (ast.ColumnRef(f"_exk0", alias),))
                all_null = ast.BinaryOp(
                    "equal", ast.ColumnRef("_exct", alias),
                    ast.Literal(0, "int"))
                outer_null = ast.FuncCall("is_null", (outer_e,))
                all_eq = ast.BinaryOp(
                    "and",
                    ast.BinaryOp("equal", mn, outer_e),
                    ast.BinaryOp("equal", mx, outer_e),
                )
                rest.append(ast.BinaryOp(
                    "or", no_group, ast.BinaryOp(
                        "or", all_null, ast.BinaryOp(
                            "or", outer_null, all_eq))))
        where = None
        for r in rest:
            where = r if where is None else ast.BinaryOp("and", where, r)
        import dataclasses
        return dataclasses.replace(select, from_=from_, where=where)

    def _column_refs(self, e) -> list:
        """All ColumnRefs in an AST expression."""
        out: list = []
        stack = [e]
        while stack:
            x = stack.pop()
            if isinstance(x, ast.ColumnRef):
                out.append(x)
            elif isinstance(x, ast.Case):
                for c, r in x.conditions:
                    stack += [c, r]
                if x.else_result is not None:
                    stack.append(x.else_result)
            else:
                for a in ("left", "right", "operand", "expr",
                          "filter_where"):
                    v = getattr(x, a, None)
                    if v is not None and not isinstance(v, str):
                        stack.append(v)
                stack.extend(
                    a for a in getattr(x, "args", ())
                    if not isinstance(a, ast.Star)
                )
        return out

    def _rewrite_correlated_scalar(self, select: ast.Select) -> ast.Select:
        """``lhs CMP (SELECT agg(..) FROM .. WHERE sub_col = outer_col
        AND <local>)`` decorrelates into a join against the subquery
        grouped by its correlation keys, with ``lhs CMP agg_out`` as a
        residual predicate (the reference's Apply→Join unnesting,
        optimizer/rule/apply_agg_transpose_rule.rs and kin).

        Empty-group semantics: the scalar subquery yields NULL over an
        empty set, making the comparison never-true — the inner join
        dropping missing keys is equivalent (count/count_star would
        yield 0, NOT NULL, so those stay unsupported here)."""
        if select.where is None:
            return select
        conjs = self._conjuncts(select.where)
        hits = []
        for c in conjs:
            m = self._match_scalar_sub_cmp(c)
            if m is None or self._is_uncorrelated(m[2]):
                continue
            hits.append((c, m))
        if not hits:
            return select
        new_conjs = list(conjs)
        from_ = select.from_
        for k, (c, (lhs, cmp, sub)) in enumerate(hits):
            if (sub.group_by or sub.having is not None
                    or len(sub.items) != 1
                    or isinstance(sub.items[0].expr, ast.Star)):
                raise PlanError(
                    "correlated scalar subquery must be a single "
                    "ungrouped aggregate"
                )
            item = sub.items[0].expr
            if any(f.name == "count"
                   for f in self._column_refs_funcs(item)):
                raise PlanError(
                    "correlated scalar COUNT subquery (0 vs NULL over "
                    "empty groups) is not supported"
                )
            sub_names, sub_quals = self._from_name_sets(sub.from_)

            def is_local(e) -> bool:
                if not isinstance(e, ast.ColumnRef):
                    return False
                if e.table is not None:
                    return (e.table, e.name) in sub_quals
                return e.name in sub_names

            local: list = []
            corr: list = []  # (sub_col, outer_col)
            for sc in (self._conjuncts(sub.where)
                       if sub.where is not None else []):
                refs = self._column_refs(sc)
                if refs and all(is_local(r) for r in refs):
                    local.append(sc)
                    continue
                if isinstance(sc, ast.BinaryOp) and sc.op == "equal":
                    a, b = sc.left, sc.right
                    if isinstance(a, ast.ColumnRef) \
                            and isinstance(b, ast.ColumnRef):
                        if is_local(a) and not is_local(b):
                            corr.append((a, b))
                            continue
                        if is_local(b) and not is_local(a):
                            corr.append((b, a))
                            continue
                raise PlanError(
                    "correlated scalar subquery supports equality "
                    f"correlation only (got {sc!r})"
                )
            if not corr:
                raise PlanError(
                    "correlated scalar subquery lost its correlation"
                )
            alias = f"_cs_sq{k}"
            import dataclasses
            lwhere = None
            for c2 in local:
                lwhere = c2 if lwhere is None \
                    else ast.BinaryOp("and", lwhere, c2)
            items = tuple(
                ast.SelectItem(sc_col, f"_ck{j}")
                for j, (sc_col, _) in enumerate(corr)
            ) + (ast.SelectItem(item, "_cv"),)
            sub2 = dataclasses.replace(
                sub, items=items, where=lwhere,
                group_by=tuple(sc_col for sc_col, _ in corr),
                having=None, order_by=(), limit=None, offset=None,
            )
            on = None
            for j, (_, outer_c) in enumerate(corr):
                eq = ast.BinaryOp(
                    "equal", outer_c, ast.ColumnRef(f"_ck{j}", alias)
                )
                on = eq if on is None else ast.BinaryOp("and", on, eq)
            from_ = ast.Join(
                left=from_, right=ast.SubqueryRef(sub2, alias),
                on=on, kind="inner",
            )
            # replace the conjunct with lhs CMP <agg out>
            inv = {"gt": "greater_than", "ge": "greater_than_or_equal",
                   "lt": "less_than", "le": "less_than_or_equal",
                   "eq": "equal"}
            new_conjs[new_conjs.index(c)] = ast.BinaryOp(
                inv[cmp], lhs, ast.ColumnRef("_cv", alias)
            )
        where = None
        for r in new_conjs:
            where = r if where is None else ast.BinaryOp("and", where, r)
        import dataclasses
        return dataclasses.replace(select, from_=from_, where=where)

    def _column_refs_funcs(self, e) -> list:
        """All FuncCalls in an AST expression."""
        out: list = []
        stack = [e]
        while stack:
            x = stack.pop()
            if isinstance(x, ast.FuncCall):
                out.append(x)
                stack.extend(a for a in x.args
                             if not isinstance(a, ast.Star))
            elif isinstance(x, ast.Case):
                for c, r in x.conditions:
                    stack += [c, r]
                if x.else_result is not None:
                    stack.append(x.else_result)
            else:
                for a in ("left", "right", "operand", "expr"):
                    v = getattr(x, a, None)
                    if v is not None and not isinstance(v, str):
                        stack.append(v)
                stack.extend(
                    a for a in getattr(x, "args", ())
                    if not isinstance(a, ast.Star)
                )
        return out

    def _is_uncorrelated(self, sub: ast.Select) -> bool:
        """Every column the subquery references resolves in its own
        FROM — safe to plan as an independent 1-row changelog."""
        names, quals = self._from_name_sets(sub.from_)

        def local(r) -> bool:
            if r.table is not None:
                return (r.table, r.name) in quals
            return r.name in names

        exprs = [it.expr for it in sub.items
                 if not isinstance(it.expr, ast.Star)]
        if sub.where is not None:
            exprs.append(sub.where)
        exprs.extend(sub.group_by)
        if sub.having is not None:
            exprs.append(sub.having)
        return all(local(r) for e in exprs for r in self._column_refs(e))

    # -- GroupTopN (row_number-in-subquery) rewrite ---------------------
    def _match_group_topn(self, select: ast.Select):
        """Detect SELECT .. FROM (SELECT *, ROW_NUMBER() OVER (..) rn
        FROM ..) WHERE rn <= k and return (inner-sans-window, spec)."""
        f = select.from_
        if not isinstance(f, ast.SubqueryRef):
            return None
        inner = f.select
        if (inner.order_by or inner.limit is not None or inner.offset
                or inner.group_by or inner.having is not None):
            return None
        wins = [(i, it) for i, it in enumerate(inner.items)
                if isinstance(it.expr, ast.WindowCall)]
        if len(wins) != 1:
            return None
        wi, witem = wins[0]
        w = witem.expr
        if w.name != "row_number" or w.frame is not None or not w.order_by:
            return None
        rank_name = witem.alias or "row_number"
        if select.where is None:
            return None
        limit = offset = None
        rest: list = []
        for c in self._conjuncts(select.where):
            lo = self._rank_bound(c, rank_name, f.alias)
            if lo is not None and limit is None:
                limit, offset = lo
            else:
                rest.append(c)
        if limit is None:
            return None
        if select.order_by or select.limit is not None or select.offset:
            return None  # outer ORDER/LIMIT over group topn: next round

        # does the outer query use the rank column (selected by name or
        # via *)?  If so the TopN must emit its in-band row_number.
        def refs_rank(e) -> bool:
            if isinstance(e, ast.ColumnRef):
                return e.name == rank_name
            if isinstance(e, ast.Case):
                return any(refs_rank(c) or refs_rank(r)
                           for c, r in e.conditions) or (
                    e.else_result is not None
                    and refs_rank(e.else_result)
                )
            return any(
                refs_rank(x) for x in getattr(e, "args", ())
                if not isinstance(x, ast.Star)
            ) or any(
                refs_rank(getattr(e, a)) for a in ("left", "right",
                                                   "operand")
                if getattr(e, a, None) is not None
            )
        has_star = any(isinstance(it.expr, ast.Star)
                       for it in select.items)
        with_rank = has_star or any(
            not isinstance(it.expr, ast.Star) and refs_rank(it.expr)
            for it in select.items
        ) or any(refs_rank(c) for c in rest)
        if has_star and wi != len(inner.items) - 1:
            # the rank column is appended LAST by the rewrite; a * over
            # a mid-list window item would reorder columns
            return None
        import dataclasses
        inner2 = dataclasses.replace(
            inner, items=tuple(it for i, it in enumerate(inner.items)
                               if i != wi),
        )
        spec = GroupTopNSpec(
            partition=tuple(w.partition_by), order=tuple(w.order_by),
            limit=limit, offset=offset,
            outer_items=tuple(select.items), outer_where=tuple(rest),
            alias=f.alias,
            rank_alias=rank_name if with_rank else None,
        )
        return inner2, spec

    @staticmethod
    def _rank_bound(c, rank_name: str, alias: "str | None" = None):
        """rn <= k / rn < k / rn = k / k >= rn → (limit, offset)."""
        def is_rank(e) -> bool:
            return (isinstance(e, ast.ColumnRef) and e.name == rank_name
                    and e.table in (None, alias))

        if not isinstance(c, ast.BinaryOp):
            return None
        op, left, right = c.op, c.left, c.right
        if is_rank(right):
            flip = {"greater_than_or_equal": "less_than_or_equal",
                    "greater_than": "less_than",
                    "equal": "equal"}.get(op)
            if flip is None:
                return None
            op, left, right = flip, right, left
        if not (is_rank(left)
                and isinstance(right, ast.Literal)
                and right.type_name == "int"):
            return None
        k = right.value
        if op == "less_than_or_equal" and k >= 1:
            return (k, 0)
        if op == "less_than" and k >= 2:
            return (k - 1, 0)
        if op == "equal" and k >= 1:
            return (1, k - 1)
        return None

    def _resolve_group_topn(self, spec: GroupTopNSpec, scope: Scope,
                            proj: list):
        """Bind the partition/order keys in the INNER scope and locate
        them in the projection (appending hidden columns as needed);
        returns (group_positions, [(position, desc)], spec)."""
        b = Binder(scope)

        def locate(bexpr) -> int:
            for pi, (_, pe) in enumerate(proj):
                if self._expr_eq(pe, bexpr):
                    return pi
            proj.append((f"_hidden_gtn{len(proj)}", bexpr))
            return len(proj) - 1

        group_pos = [locate(b.bind(e)) for e in spec.partition]
        order_pos = [(locate(b.bind(oi.expr)), oi.descending)
                     for oi in spec.order]
        return (group_pos, order_pos, spec)

    # -- FROM resolution ------------------------------------------------
    def _resolve_input(self, from_) -> PlannedInput:
        if isinstance(from_, ast.TableRef):
            entry = self.catalog.get(from_.name)
            if entry.kind == "mview":
                # MV-on-MV: consume the upstream MV's output changelog
                qual = from_.alias or from_.name
                return PlannedInput(
                    MvTap(from_.name), [],
                    Scope.of(entry.schema, qual), entry.schema,
                    None, None, entry.append_only,
                    stream_key=entry.stream_key,
                )
            if entry.kind != "source":
                raise PlanError(
                    f"{from_.name} is not a streaming source or "
                    "materialized view"
                )
            reader = entry.reader_factory()
            qual = from_.alias or from_.name
            execs: list[Executor] = []
            wm_col = None
            if entry.watermark is not None:
                col, delay = entry.watermark
                execs.append(
                    WatermarkFilterExecutor(entry.schema, col, delay)
                )
                wm_col = col
            return PlannedInput(
                reader, execs, Scope.of(entry.schema, qual), entry.schema,
                wm_col, None, entry.append_only,
                stream_key=list(entry.stream_key)
                if entry.stream_key else None,
            )
        if isinstance(from_, (ast.Tumble, ast.Hop)):
            inner = self._resolve_input(from_.table)
            ts_idx = inner.scope.resolve(from_.time_col, None)
            if isinstance(from_, ast.Tumble):
                size = from_.size.micros
                slide = size
            else:
                size = from_.size.micros
                slide = from_.slide.micros
            hop = HopWindowExecutor(inner.schema, ts_idx, slide, size)
            qual = from_.alias or from_.table.name
            if from_.alias:
                # an aliased window table re-qualifies EVERY column
                quals = tuple(qual for _ in hop.out_schema)
            else:
                quals = tuple(inner.scope.qualifiers) + (qual, qual)
            scope = Scope(hop.out_schema, quals)
            # window_start is addressable by the window alias OR the
            # underlying table name (postgres-ish leniency)
            return PlannedInput(
                inner.reader, inner.executors + [hop], scope,
                hop.out_schema, inner.watermark_col, size,
                inner.append_only, window_slide=slide,
            )
        raise PlanError(f"unsupported FROM clause {from_!r}")

    # -- unary pipelines -------------------------------------------------
    @staticmethod
    def _stream_key_projection(proj: list, schema: Schema,
                               stream_key) -> list[int]:
        """Ensure the stream-key columns survive a projection (hidden if
        unselected); returns their output positions (the materialize
        pk).  Ref: stream-key derivation through project nodes."""
        pk_positions: list[int] = []
        for ki in stream_key:
            pos = next(
                (pi for pi, (_, e) in enumerate(proj)
                 if isinstance(e, InputRef) and e.index == ki),
                None,
            )
            if pos is None:
                proj.append((f"_hidden_{schema[ki].name}", InputRef(ki)))
                pos = len(proj) - 1
            pk_positions.append(pos)
        return pk_positions

    def _plan_unary(self, select: ast.Select, sink=None,
                    eowc: bool = False,
                    group_topn: "GroupTopNSpec | None" = None
                    ) -> UnaryPlan:
        if select.from_ is None:
            raise PlanError("SELECT without FROM is not a streaming job")
        pin = self._resolve_input(select.from_)
        execs = list(pin.executors)
        scope = pin.scope

        if select.where is not None:
            b = Binder(scope)
            execs.append(FilterExecutor(scope.schema, b.bind(select.where)))

        has_window = any(
            isinstance(i.expr, ast.WindowCall) for i in select.items
        )
        if has_window:
            if sink is not None or eowc:
                raise PlanError(
                    "window functions with sinks/EOWC: next round"
                )
            return self._plan_over_window(select, pin, execs, scope)

        has_agg = bool(select.group_by) or self._has_agg(select)
        if has_agg and group_topn is not None:
            raise PlanError(
                "row_number subquery over an aggregation: next round"
            )
        if eowc and not has_agg:
            raise PlanError(
                "EMIT ON WINDOW CLOSE needs GROUP BY window_start over a "
                "watermarked windowed source"
            )
        pk_positions: list[int] = []
        gtn = None
        if has_agg:
            pane = self._try_pane_agg(select, scope, pin, execs, eowc)
            if pane is not None:
                execs2, out_schema, pk_positions = pane
            else:
                execs2, out_schema, pk_positions = self._plan_agg(
                    select, scope, pin, eowc
                )
            execs.extend(execs2)
        else:
            items = self._expand_items(select.items, scope)
            b = Binder(scope)
            proj = [(name, b.bind(e)) for name, e in items]
            if not pin.append_only:
                # retractable input without aggregation: the output must
                # stay keyed by the upstream STREAM KEY so deletes hit
                # the right MV row — append the key columns (hidden if
                # unselected) and remember their positions as the pk
                if pin.stream_key is None:
                    raise PlanError(
                        "retractable input without a stream key cannot "
                        "be materialized"
                    )
                pk_positions = self._stream_key_projection(
                    proj, scope.schema, pin.stream_key
                )
            if group_topn is not None:
                gtn = self._resolve_group_topn(group_topn, scope, proj)
            execs.append(ProjectExecutor(scope.schema, proj))
            out_schema = execs[-1].out_schema

        self._append_terminal(
            execs, out_schema, select,
            input_append_only=pin.append_only, has_agg=has_agg,
            pk_positions=pk_positions, sink=sink, eowc=eowc,
            group_topn=gtn,
        )
        return UnaryPlan(pin.reader, Fragment(execs), len(execs) - 1,
                         append_only=pin.append_only)

    def _build_over_window(self, items, scope: Scope, execs: list):
        """Append an OverWindowExecutor + post-projection for SELECT
        items containing fn() OVER (...) calls (one shared OVER clause).
        Returns the projected out_schema."""
        from risingwave_tpu.stream.over_window import (
            OverWindowExecutor,
            WindowFuncCall,
        )

        witems = [(item, item.expr) for item in items
                  if isinstance(item.expr, ast.WindowCall)]
        spec = (witems[0][1].partition_by, witems[0][1].order_by,
                witems[0][1].frame)
        for _, w in witems[1:]:
            if (w.partition_by, w.order_by, w.frame) != spec:
                raise PlanError(
                    "all window calls must share one OVER clause "
                    "(multi-spec plans: next round)"
                )
        b = Binder(scope)
        partition = [b.bind(e) for e in spec[0]]
        order = [(b.bind(oi.expr), oi.descending) for oi in spec[1]]
        for e in partition + [oe for oe, _ in order]:
            if e.return_field(scope.schema).nullable:
                raise PlanError(
                    "OVER (...) on nullable partition/order columns: "
                    "next round"
                )
        calls = []
        supported = {"row_number", "rank", "dense_rank", "lag", "lead",
                     "sum", "count", "avg", "min", "max"}
        needs_arg = {"lag", "lead", "sum", "avg", "min", "max"}
        framable = {"sum", "count", "avg"}
        for idx, (item, w) in enumerate(witems):
            if w.name not in supported:
                raise PlanError(f"window function {w.name} not supported")
            if w.frame is not None:
                if w.name not in framable:
                    raise PlanError(
                        f"ROWS frames on {w.name}() OVER: next round"
                    )
                if w.frame[1] != 0 or w.frame[0] < 0:
                    raise PlanError(
                        "only ROWS BETWEEN n PRECEDING AND CURRENT ROW "
                        "frames are supported"
                    )
            if w.name in needs_arg and (
                not w.args or isinstance(w.args[0], ast.Star)
            ):
                raise PlanError(f"{w.name}() OVER needs an argument")
            if w.name in ("lag", "lead") and len(w.args) > 2:
                raise PlanError(
                    "lag/lead default values are not yet supported"
                )
            arg = b.bind(w.args[0]) if w.args and not isinstance(
                w.args[0], ast.Star
            ) else None
            offset = 1
            if w.name in ("lag", "lead") and len(w.args) > 1:
                off_ast = w.args[1]
                if not (isinstance(off_ast, ast.Literal)
                        and off_ast.type_name == "int"):
                    raise PlanError("lag/lead offset must be an integer")
                offset = off_ast.value
            calls.append(WindowFuncCall(
                w.name, arg, offset,
                item.alias or f"{w.name}{idx}",
                frame=w.frame,
            ))
        ow = OverWindowExecutor(
            scope.schema, partition, order, calls,
            pool_size=max(self.config.topn_pool_size,
                          2 * self.config.chunk_capacity),
            emit_capacity=self.config.topn_emit_capacity,
        )
        execs.append(ow)
        # post-projection: inputs by name, window outputs by position
        out_schema = ow.out_schema
        n_in = len(scope.schema)
        proj = []
        wi = 0
        post_b = Binder(Scope(out_schema,
                              tuple(scope.qualifiers)
                              + tuple(None for _ in calls)))
        for idx, item in enumerate(items):
            if isinstance(item.expr, ast.WindowCall):
                name = item.alias or calls[wi].alias
                proj.append((name, InputRef(n_in + wi)))
                wi += 1
            elif isinstance(item.expr, ast.Star):
                for ci, f in enumerate(scope.schema):
                    if f.name.startswith("_hidden_"):
                        continue
                    proj.append((f.name, InputRef(ci)))
            else:
                name = item.alias or self._default_name(item.expr, idx)
                proj.append((name, post_b.bind(item.expr)))
        execs.append(ProjectExecutor(out_schema, proj))
        return execs[-1].out_schema

    def _plan_over_window(self, select: ast.Select, pin, execs,
                          scope) -> UnaryPlan:
        """SELECT items with fn() OVER (...): one OverWindowExecutor.

        All window calls must share one OVER clause this round (the
        reference groups calls per window spec the same way)."""
        if (select.group_by or select.having is not None
                or select.order_by or select.limit is not None
                or select.offset):
            raise PlanError(
                "window functions with GROUP BY/HAVING/ORDER BY/LIMIT "
                "in one SELECT: next round"
            )
        out_schema = self._build_over_window(select.items, scope, execs)
        execs.append(MaterializeExecutor(
            out_schema, pk_indices=list(range(len(out_schema))),
            table_size=self.config.mv_table_size,
        ))
        return UnaryPlan(pin.reader, Fragment(execs), len(execs) - 1,
                         append_only=False)

    def _append_terminal(self, execs, out_schema, select, *,
                         input_append_only: bool, has_agg: bool,
                         pk_positions, sink, eowc: bool,
                         group_topn=None) -> None:
        """Shared plan tail: optional (group) TopN, then sink or
        materialize."""
        has_topn = bool(select.order_by and select.limit is not None)
        if group_topn is not None:
            group_pos, order_pos, spec = group_topn
            for pos, _ in order_pos:
                if out_schema[pos].nullable:
                    raise PlanError(
                        "row_number ORDER BY on a nullable column: "
                        "next round"
                    )
            pool = max(self.config.topn_pool_size,
                       2 * self.config.chunk_capacity)
            execs.append(GroupTopNExecutor(
                out_schema,
                group_by=[InputRef(i) for i in group_pos],
                order_by=[(InputRef(i), d) for i, d in order_pos],
                limit=spec.limit, offset=spec.offset,
                pool_size=pool,
                emit_capacity=self.config.topn_emit_capacity,
                append_only=input_append_only,
                rank_alias=spec.rank_alias,
            ))
            out_schema = execs[-1].out_schema
            scope2 = Scope.of(out_schema, spec.alias)
            for c in spec.outer_where:
                execs.append(FilterExecutor(
                    out_schema, Binder(scope2).bind(c)
                ))
            if any(isinstance(it.expr, ast.WindowCall)
                   for it in spec.outer_items):
                # q6 shape: fn() OVER (...) over the group-topn output
                out_schema = self._build_over_window(
                    spec.outer_items, scope2, execs
                )
            else:
                items = self._expand_items(spec.outer_items, scope2)
                proj2 = [(nm, Binder(scope2).bind(e))
                         for nm, e in items]
                execs.append(ProjectExecutor(out_schema, proj2))
                out_schema = execs[-1].out_schema
            # group-topn output is retractable, keyed by the whole row
            input_append_only = False
            pk_positions = list(range(len(out_schema)))
        if has_topn:
            if eowc:
                raise PlanError(
                    "ORDER BY ... LIMIT with EMIT ON WINDOW CLOSE: "
                    "next round"
                )
            ob = []
            b = Binder(Scope.of(out_schema))
            for oi in select.order_by:
                ke = self._bind_order_key(oi.expr, b, out_schema)
                if ke.return_field(out_schema).nullable:
                    raise PlanError(
                        "ORDER BY on a nullable column in TopN "
                        "(NULLS FIRST/LAST ordering): next round"
                    )
                ob.append((ke, oi.descending))
            # append-only up to here ⇒ the TopN can evict non-band rows
            pool = max(self.config.topn_pool_size,
                       2 * self.config.chunk_capacity)
            execs.append(GroupTopNExecutor(
                out_schema, group_by=[], order_by=ob, limit=select.limit,
                offset=select.offset or 0,
                pool_size=pool,
                emit_capacity=self.config.topn_emit_capacity,
                append_only=input_append_only and not has_agg,
            ))

        if sink is not None:
            from risingwave_tpu.stream.sink import SinkExecutor
            # hidden MV-pk bookkeeping columns must not leak externally
            visible = [i for i, f in enumerate(out_schema)
                       if not f.name.startswith("_hidden_")]
            if len(visible) != len(out_schema):
                execs.append(ProjectExecutor(
                    out_schema,
                    [(out_schema[i].name, InputRef(i)) for i in visible],
                ))
                out_schema = execs[-1].out_schema
            execs.append(SinkExecutor(
                out_schema, sink, ring_size=self.config.mv_ring_size
            ))
            return

        # materialize (EOWC output is final append-only rows)
        retractable = (has_agg or has_topn or not input_append_only) \
            and not eowc
        if retractable:
            # pk: group keys for aggs; the propagated stream key for
            # retractable projections; whole row for TopN output.
            # KNOWN GAP (advisor r1, low): two identical rows in a TopN
            # band collapse into one MV slot — multiset parity needs a
            # rank column from the TopN state appended to the pk.
            if has_topn:
                pk = list(range(len(out_schema)))
            elif pk_positions:
                pk = pk_positions
            else:
                pk = list(range(len(out_schema)))
            execs.append(MaterializeExecutor(
                out_schema, pk_indices=pk,
                table_size=self.config.mv_table_size,
            ))
        else:
            execs.append(AppendOnlyMaterialize(
                out_schema, ring_size=self.config.mv_ring_size
            ))

    # -- aggregation ------------------------------------------------------
    def _has_agg(self, select: ast.Select) -> bool:
        def walk(e) -> bool:
            if isinstance(e, ast.FuncCall):
                if e.name in AGG_NAMES:
                    return True
                return any(walk(a) for a in e.args
                           if not isinstance(a, ast.Star))
            if isinstance(e, ast.BinaryOp):
                return walk(e.left) or walk(e.right)
            if isinstance(e, ast.UnaryOp):
                return walk(e.operand)
            if isinstance(e, ast.Cast):
                return walk(e.operand)
            if isinstance(e, ast.Case):
                return any(walk(c) or walk(r) for c, r in e.conditions) or (
                    e.else_result is not None and walk(e.else_result)
                )
            return False

        return any(walk(i.expr) for i in select.items
                   if not isinstance(i.expr, ast.Star))

    def _plan_agg(self, select: ast.Select, scope: Scope,
                  pin: PlannedInput, eowc: bool = False,
                  extra_out: "list | None" = None):
        """Plan the aggregation chain; with ``extra_out`` (AST exprs in
        the input scope, aggregates allowed) their values are appended
        to the output as hidden columns and their positions returned
        as a 4th element (the dynamic-filter LHS hook)."""
        cfg = self.config
        group_asts = list(select.group_by)
        in_binder = Binder(scope)
        group_by = []
        for gi, ga in enumerate(group_asts):
            name = ga.name if isinstance(ga, ast.ColumnRef) else f"_key{gi}"
            group_by.append((name, in_binder.bind(ga)))
        if not group_by:
            # global aggregation = one hidden constant group (the
            # reference's simple agg / Distribution::Single)
            from risingwave_tpu.expr.node import as_expr
            group_by.append(("_global", as_expr(0)))

        # bind select items collecting agg calls
        item_binder = Binder(scope, allow_aggs=True)
        bound_items: list[tuple[str, Expr]] = []
        for idx, item in enumerate(select.items):
            if isinstance(item.expr, ast.Star):
                raise PlanError("SELECT * with GROUP BY is not valid")
            name = item.alias or self._default_name(item.expr, idx)
            bound_items.append((name, item_binder.bind(item.expr)))
        agg_calls = item_binder.agg_calls

        having_expr = None
        if select.having is not None:
            having_expr = item_binder.bind(select.having)
            agg_calls = item_binder.agg_calls
        extra_bound: list[Expr] = []
        for e_ast in (extra_out or []):
            extra_bound.append(item_binder.bind(e_ast))
            agg_calls = item_binder.agg_calls

        # watermark-driven cleaning when a group key is the window start
        wm_idx = None
        lag = 0
        if pin.window_size is not None and pin.watermark_col is not None:
            for ki, ga in enumerate(group_asts):
                if (isinstance(ga, ast.ColumnRef)
                        and ga.name == "window_start"):
                    wm_idx, lag = ki, pin.window_size
                elif (isinstance(ga, ast.ColumnRef)
                        and ga.name == "window_end"):
                    wm_idx, lag = ki, 0  # closes when wm >= window_end
        if eowc and wm_idx is None:
            raise PlanError(
                "EMIT ON WINDOW CLOSE needs GROUP BY window_start over a "
                "watermarked windowed source"
            )
        execs: list[Executor] = []
        if any(a.distinct for a in agg_calls):
            # DISTINCT is native in the agg executor (per-call counted
            # dedup tables, ref distinct.rs) — mixing with plain calls,
            # per-call FILTERs, multiple distinct args, and retractable
            # inputs all compose.  min/max are distinct-insensitive.
            import dataclasses
            agg_calls = [
                dataclasses.replace(a, distinct=False)
                if a.distinct and a.kind in ("min", "max") else a
                for a in agg_calls
            ]
        # min/max over short strings: packed-uint64 monoid (agg.py
        # _pack_str8); wider strings need a materialized-input string
        # state — not yet built
        for ci, a in enumerate(agg_calls):
            if a.kind in ("min", "max") and a.arg is not None:
                f = a.arg.return_field(scope.schema)
                if f.data_type.is_string:
                    if f.str_width > 8:
                        raise PlanError(
                            f"{a.kind} over strings wider than 8 device "
                            "bytes: next round"
                        )
                    if not pin.append_only:
                        # packed monoid can't retract; a string minput
                        # state hasn't been built
                        raise PlanError(
                            f"{a.kind} over strings on a retractable "
                            "input: next round"
                        )
                    import dataclasses
                    agg_calls[ci] = dataclasses.replace(
                        a, kind=f"{a.kind}_str"
                    )
        agg = HashAggExecutor(
            scope.schema, group_by, agg_calls,
            table_size=cfg.agg_table_size,
            emit_capacity=cfg.agg_emit_capacity,
            watermark_group_idx=wm_idx,
            watermark_lag=lag,
            watermark_src_col=pin.watermark_col,
            emit_on_window_close=eowc,
            # retractable inputs (join outputs, cascades over
            # retractable MVs) switch min/max to materialized-input
            # state (ref minput.rs) instead of crash-on-delete
            retractable_input=not pin.append_only,
            minput_bucket_cap=cfg.minput_bucket_cap,
            distinct_table_size=cfg.distinct_table_size,
            # spill-to-host for UNBOUNDED key spaces (no watermark
            # cleaning): overflow rows divert to the host tier instead
            # of erroring.  Windowed aggs keep overflow-as-error — their
            # state is bounded by cleaning, and freed slots would break
            # the tier's structural group ownership (stream/spill.py).
            spill_ring=((cfg.agg_spill_ring
                         if cfg.agg_spill_ring is not None
                         else 4 * cfg.chunk_capacity)
                        if wm_idx is None and not eowc else 0),
        )
        agg.spill_table_size = (cfg.agg_spill_table_size
                                or cfg.agg_table_size * 8)
        execs.append(agg)

        # post-projection over agg output: group keys + agg results
        agg_scope = Scope.of(agg.out_schema)
        rewritten = []
        for (name, e) in bound_items:
            rewritten.append((name, self._rewrite_post_agg(
                e, group_by, len(group_by)
            )))
        # append hidden group keys that weren't selected (MV pk needs them)
        selected_keys = set()
        for name, e in rewritten:
            if isinstance(e, InputRef) and e.index < len(group_by):
                selected_keys.add(e.index)
        hidden = [
            (f"_hidden_{agg.out_schema[ki].name}", InputRef(ki))
            for ki in range(len(group_by)) if ki not in selected_keys
        ]
        proj_items = rewritten + hidden
        extra_pos: list[int] = []
        for xi, xb in enumerate(extra_bound):
            proj_items.append((
                f"_hidden_dynf{xi}",
                self._rewrite_post_agg(xb, group_by, len(group_by)),
            ))
            extra_pos.append(len(proj_items) - 1)
        if having_expr is not None:
            hv = self._rewrite_post_agg(having_expr, group_by, len(group_by))
            execs.append(FilterExecutor(agg.out_schema, hv))
        post = ProjectExecutor(agg.out_schema, proj_items)
        execs.append(post)
        # pk = positions of the group keys inside the projection
        pk_pos = []
        for ki in range(len(group_by)):
            for pi, (n, e) in enumerate(proj_items):
                if isinstance(e, InputRef) and e.index == ki:
                    pk_pos.append(pi)
                    break
        if extra_out is not None:
            return execs, post.out_schema, pk_pos, extra_pos
        return execs, post.out_schema, pk_pos

    def _try_pane_agg(self, select: ast.Select, scope: Scope,
                      pin: PlannedInput, execs: list, eowc: bool):
        """Sliding-window (HOP) aggregation via PANES — stream slicing.

        The naive hop plan expands every event into size/slide window
        rows BEFORE aggregating (ref hop_window.rs row expansion) — a
        k-fold tax on the agg's scatter path.  Panes aggregate ONCE per
        event into tumbling slide-width panes, then expand only the
        aggregated PANE DELTAS (tiny) into their k covering windows and
        combine with translated partial-agg calls — the classic
        pane/stream-slicing optimization, done with the two-phase
        machinery (partial_agg.translated_global_calls).

        Eligible: append-only hop input, GROUP BY window_start + keys,
        two-phase-decomposable calls without DISTINCT/FILTER, linear
        (unsharded) plans.  Returns None when ineligible."""
        from risingwave_tpu.stream.partial_agg import (
            TWO_PHASE_KINDS,
            translated_global_calls,
        )

        if eowc or not pin.append_only or self.parallel_hint > 1:
            return None
        size, slide = pin.window_size, pin.window_slide
        if size is None or slide is None or slide >= size \
                or size % slide != 0 or pin.watermark_col is None:
            return None
        hop_pos = next(
            (i for i, ex in enumerate(execs)
             if isinstance(ex, HopWindowExecutor)), None,
        )
        if hop_pos is None:
            return None
        hop = execs[hop_pos]
        ws_idx = len(hop.in_schema)  # window_start position (appended)

        def touches_window(e: Expr) -> bool:
            if isinstance(e, InputRef):
                return e.index >= ws_idx
            if isinstance(e, AggRef):
                return e.call.arg is not None \
                    and touches_window(e.call.arg)
            if isinstance(e, EFuncCall):
                return any(touches_window(a) for a in e.args)
            return False

        # bind group keys + items exactly as _plan_agg would
        group_asts = list(select.group_by)
        in_binder = Binder(scope)
        group_by: list = []
        ws_key_pos = None
        for gi, ga in enumerate(group_asts):
            name = ga.name if isinstance(ga, ast.ColumnRef) else f"_key{gi}"
            ge = in_binder.bind(ga)
            if isinstance(ge, InputRef) and ge.index == ws_idx:
                ws_key_pos = gi
            elif touches_window(ge):
                return None  # window_end/ts-derived keys: no pane form
            group_by.append((name, ge))
        if ws_key_pos is None:
            return None
        item_binder = Binder(scope, allow_aggs=True)
        bound_items = []
        for idx, item in enumerate(select.items):
            if isinstance(item.expr, ast.Star):
                raise PlanError("SELECT * with GROUP BY is not valid")
            name = item.alias or self._default_name(item.expr, idx)
            bound_items.append((name, item_binder.bind(item.expr)))
        agg_calls = item_binder.agg_calls
        having_expr = None
        if select.having is not None:
            having_expr = item_binder.bind(select.having)
            agg_calls = item_binder.agg_calls
        if any(a.kind not in TWO_PHASE_KINDS or a.distinct
               or a.filter is not None for a in agg_calls):
            return None
        if any(a.arg is not None and touches_window(a.arg)
               for a in agg_calls):
            return None
        # min/max over strings: the pane combine phase is retractable,
        # which the packed-string monoid can't support — fall back to
        # _plan_agg (which plans min_str/max_str or raises a clear
        # PlanError) instead of crashing at executor build
        for a in agg_calls:
            if a.kind in ("min", "max") and a.arg is not None \
                    and a.arg.return_field(scope.schema) \
                           .data_type.is_string:
                return None
        # the WHERE filter (already in execs) must not read window cols
        for ex in execs:
            if isinstance(ex, FilterExecutor) \
                    and touches_window(ex.predicate):
                return None

        cfg = self.config
        n_keys = len(group_by)
        k = size // slide
        # 1. panes: tumble by slide (same schema/positions as the hop)
        execs[hop_pos] = HopWindowExecutor(
            hop.in_schema, hop.ts_col, slide, slide
        )
        # 2. per-pane partial agg (append-only, cleans when the pane's
        # LAST covering window closes: wm >= pane_start + size)
        pane_agg = HashAggExecutor(
            execs[hop_pos].out_schema, group_by, agg_calls,
            table_size=cfg.agg_table_size,
            emit_capacity=cfg.agg_emit_capacity,
            watermark_group_idx=ws_key_pos,
            watermark_lag=size,
            watermark_src_col=pin.watermark_col,
        )
        # 3. expand PANE DELTAS to their k covering windows
        expand = HopWindowExecutor(
            pane_agg.out_schema, ws_key_pos, slide, size
        )
        n_pane_out = len(pane_agg.out_schema)
        # 4. combine partials per (keys..., window_start) — pane updates
        # retract, so the global phase runs retractable (minput holds up
        # to k live pane-partials per window for min/max)
        final_group = [
            (nm, InputRef(n_pane_out) if gi == ws_key_pos
             else InputRef(gi))
            for gi, (nm, _) in enumerate(group_by)
        ]
        final_agg = HashAggExecutor(
            expand.out_schema, final_group,
            translated_global_calls(agg_calls, n_keys),
            table_size=cfg.agg_table_size,
            emit_capacity=cfg.agg_emit_capacity,
            watermark_group_idx=ws_key_pos,
            watermark_lag=size,
            watermark_src_col=pin.watermark_col,
            retractable_input=True,
            minput_bucket_cap=max(cfg.minput_bucket_cap, 2 * k),
        )
        execs2: list = [pane_agg, expand, final_agg]

        # post projection / having / pk — identical to _plan_agg's tail
        # (final agg output = [keys..., agg outs...] in original order)
        rewritten = [
            (name, self._rewrite_post_agg(e, group_by, n_keys))
            for name, e in bound_items
        ]
        selected_keys = {
            e.index for _, e in rewritten
            if isinstance(e, InputRef) and e.index < n_keys
        }
        hidden = [
            (f"_hidden_{final_agg.out_schema[ki].name}", InputRef(ki))
            for ki in range(n_keys) if ki not in selected_keys
        ]
        proj_items = rewritten + hidden
        if having_expr is not None:
            hv = self._rewrite_post_agg(having_expr, group_by, n_keys)
            execs2.append(FilterExecutor(final_agg.out_schema, hv))
        post = ProjectExecutor(final_agg.out_schema, proj_items)
        execs2.append(post)
        pk_pos = []
        for ki in range(n_keys):
            for pi, (nm, e) in enumerate(proj_items):
                if isinstance(e, InputRef) and e.index == ki:
                    pk_pos.append(pi)
                    break
        return execs2, post.out_schema, pk_pos

    def _rewrite_post_agg(self, e: Expr, group_by, n_keys: int) -> Expr:
        """Rewrite a bound select expr to read the agg output schema."""
        if isinstance(e, AggRef):
            return InputRef(n_keys + e.index)
        for ki, (_, ge) in enumerate(group_by):
            if self._expr_eq(e, ge):
                return InputRef(ki)
        if isinstance(e, InputRef):
            raise PlanError(
                "column referenced outside aggregates must appear in "
                "GROUP BY"
            )
        if isinstance(e, EFuncCall):
            return EFuncCall(
                e.name,
                tuple(self._rewrite_post_agg(a, group_by, n_keys)
                      for a in e.args),
            )
        from risingwave_tpu.expr.scalar import RegexpGroup, ToChar
        if isinstance(e, ToChar):
            return ToChar(
                self._rewrite_post_agg(e.arg, group_by, n_keys), e.fmt
            )
        if isinstance(e, RegexpGroup):
            return RegexpGroup(
                self._rewrite_post_agg(e.arg, group_by, n_keys),
                e.pattern, 2,
            )
        return e  # literals

    @staticmethod
    def _expr_eq(a: Expr, b: Expr) -> bool:
        if type(a) is not type(b):
            return False
        if isinstance(a, InputRef):
            return a.index == b.index
        if isinstance(a, EFuncCall):
            return a.name == b.name and len(a.args) == len(b.args) and all(
                Planner._expr_eq(x, y) for x, y in zip(a.args, b.args)
            )
        from risingwave_tpu.expr.node import Literal as ELit
        from risingwave_tpu.expr.scalar import RegexpGroup, ToChar
        if isinstance(a, ELit):
            return a.value == b.value and a.data_type == b.data_type
        if isinstance(a, ToChar):
            return a.fmt == b.fmt and Planner._expr_eq(a.arg, b.arg)
        if isinstance(a, RegexpGroup):
            return a.pattern == b.pattern \
                and Planner._expr_eq(a.arg, b.arg)
        return False

    # -- join pipelines ---------------------------------------------------
    def _plan_join(self, select: ast.Select, sink=None,
                   group_topn: "GroupTopNSpec | None" = None) -> DagPlan:
        """Joins — including nested (multi-way) trees — as a DagPlan.

        Each base input becomes a source (+ optional prep fragment
        node); each ast.Join becomes a JoinNode over the resolved
        refs (ref: the fragmenter cutting a join plan into exchange-
        separated fragments, stream_fragmenter/mod.rs:388)."""
        from risingwave_tpu.stream.dag import FragNode, JoinNode

        cfg = self.config
        sources: dict[str, Any] = {}
        nodes: list = []

        def reorder_cross(jn: ast.Join) -> ast.Join:
            """Greedy connectivity ordering of a comma-join chain: each
            next factor must share a WHERE equi-conjunct with the
            already-joined set (the reference optimizer's join
            reordering; TPC-H q2 lists part, supplier, partsupp —
            part×supplier has no direct edge, part×partsupp does)."""
            factors: list = []

            def flatten(x) -> None:
                if isinstance(x, ast.Join) and x.kind == "cross":
                    flatten(x.left)
                    flatten(x.right)
                else:
                    factors.append(x)

            flatten(jn)
            if len(factors) <= 2:
                return jn
            fsets = [self._from_name_sets(f) for f in factors]

            def owners(ref) -> list[int]:
                out = []
                for fi, (names, quals) in enumerate(fsets):
                    ok = (ref.table, ref.name) in quals if ref.table \
                        else ref.name in names
                    if ok:
                        out.append(fi)
                return out

            edges: list[tuple[int, int]] = []
            for conj in where_conjs:
                if not (isinstance(conj, ast.BinaryOp)
                        and conj.op == "equal"):
                    continue
                lo = {o for r in self._column_refs(conj.left)
                      for o in owners(r)}
                ro = {o for r in self._column_refs(conj.right)
                      for o in owners(r)}
                if len(lo) == 1 and len(ro) == 1 and lo != ro:
                    edges.append((lo.pop(), ro.pop()))
            order = [0]
            remaining = set(range(1, len(factors)))
            while remaining:
                pick = next(
                    (j for j in sorted(remaining)
                     if any((a in order and b == j)
                            or (b in order and a == j)
                            for a, b in edges)),
                    None,
                )
                if pick is None:
                    pick = min(remaining)  # disconnected: keep order,
                    # resolve_join raises its usual clear error
                order.append(pick)
                remaining.discard(pick)
            out = factors[order[0]]
            for j in order[1:]:
                out = ast.Join(out, factors[j], None, "cross")
            return out

        def resolve(from_):
            if isinstance(from_, ast.Join):
                if from_.kind == "cross":
                    from_ = reorder_cross(from_)
                return resolve_join(from_)
            if isinstance(from_, ast.SubqueryRef):
                return resolve_subquery(from_)
            pin = self._resolve_input(from_)
            if isinstance(from_, ast.TableRef):
                base = from_.alias or from_.name
            else:
                base = from_.alias or from_.table.name
            name = base
            i = 1
            while name in sources:
                name = f"{base}_{i}"
                i += 1
            sources[name] = pin.reader
            ref = ("source", name)
            if pin.executors:
                # window columns shift stream-key positions? no — hop
                # APPENDS columns, existing indices hold
                nodes.append(FragNode(Fragment(pin.executors), ref))
                ref = ("node", len(nodes) - 1)
            return ref, pin

        def resolve_subquery(sq: ast.SubqueryRef):
            """A derived table becomes its own fragment node chain —
            structurally an anonymous inlined MV (ref: the optimizer
            plans subqueries as shared sub-plans)."""
            nonlocal where_conjs
            inner = sq.select
            # subquery bodies get the same unnesting rewrites as the
            # top level (IN / EXISTS → semi/anti joins, correlated
            # scalar aggs → grouped joins)
            inner = self._factor_where(inner)
            inner = self._rewrite_in_subqueries(inner)
            inner = self._rewrite_exists_subqueries(inner)
            inner = self._rewrite_correlated_scalar(inner)
            if inner.order_by or inner.limit is not None or inner.offset:
                raise PlanError(
                    "ORDER BY/LIMIT in FROM subqueries: next round"
                )
            if any(isinstance(i.expr, ast.WindowCall)
                   for i in inner.items):
                raise PlanError(
                    "window functions in FROM subqueries: next round"
                )
            # WHERE conjuncts are scoped per SELECT: the subquery's own
            # comma-joins mine the subquery's WHERE, not the outer one
            saved_conjs = where_conjs
            where_conjs = (
                self._conjuncts(inner.where)
                if inner.where is not None else []
            )
            iref, iinfo = resolve(inner.from_)
            scope = iinfo.scope
            # scalar-subquery comparisons peel into dynamic filters
            # (same rewrite as the top level; q22's derived table)
            inner_dyn: list = []
            for conj in list(where_conjs):
                m = self._match_scalar_sub_cmp(conj)
                if m is not None and isinstance(m[0], ast.ColumnRef) \
                        and self._is_uncorrelated(m[2]):
                    inner_dyn.append(m)
                    where_conjs.remove(conj)
            execs: list[Executor] = []
            for conj in where_conjs:  # filters not consumed by joins
                execs.append(FilterExecutor(
                    scope.schema, Binder(scope).bind(conj)
                ))
            where_conjs = saved_conjs
            ref = iref
            append_only_in = iinfo.append_only
            if inner_dyn:
                from risingwave_tpu.stream.dynamic_filter import (
                    DynamicFilterExecutor,
                )
                if execs:
                    nodes.append(FragNode(Fragment(execs), ref))
                    ref = ("node", len(nodes) - 1)
                    execs = []
                for lhs, cmp, s2 in inner_dyn:
                    if len(s2.items) != 1 or isinstance(
                            s2.items[0].expr, ast.Star):
                        raise PlanError(
                            "scalar subquery must select exactly one "
                            "column"
                        )
                    sref, _si = resolve_subquery(
                        ast.SubqueryRef(s2, f"_sc_sq{len(nodes)}")
                    )
                    nodes.append(JoinNode(DynamicFilterExecutor(
                        scope.schema,
                        filter_col=scope.resolve(lhs.name, lhs.table),
                        cmp=cmp,
                        pool_size=max(cfg.topn_pool_size,
                                      2 * cfg.chunk_capacity),
                    ), ref, sref))
                    ref = ("node", len(nodes) - 1)
                append_only_in = False
                import dataclasses as _dc
                iinfo = _dc.replace(iinfo, append_only=False)
            has_agg = bool(inner.group_by) or self._has_agg(inner)
            pk_positions: list[int] = []
            if has_agg:
                execs2, out_schema, pk_positions = self._plan_agg(
                    inner, scope, iinfo
                )
                execs.extend(execs2)
                append_only = False
            else:
                items = self._expand_items(inner.items, scope)
                b = Binder(scope)
                proj = [(nm, b.bind(e)) for nm, e in items]
                if not append_only_in:
                    if iinfo.stream_key is None:
                        raise PlanError(
                            "retractable subquery input without a "
                            "stream key"
                        )
                    pk_positions = self._stream_key_projection(
                        proj, scope.schema, iinfo.stream_key
                    )
                execs.append(ProjectExecutor(scope.schema, proj))
                out_schema = execs[-1].out_schema
                append_only = append_only_in
            if execs:
                nodes.append(FragNode(Fragment(execs), ref))
                ref = ("node", len(nodes) - 1)
            info = PlannedInput(
                None, [], Scope.of(out_schema, sq.alias), out_schema,
                None, None, append_only,
                stream_key=pk_positions or None,
            )
            return ref, info

        KIND_MAP = {"inner": "inner", "left": "left_outer",
                    "right": "right_outer", "full": "full_outer",
                    "cross": "inner",
                    "semi": "left_semi", "anti": "left_anti"}
        #: WHERE conjuncts; comma-joins mine their equi-conditions from
        #: here (the classic implicit-join rewrite), the rest become
        #: post-join filters
        where_conjs: list = (
            self._conjuncts(select.where)
            if select.where is not None else []
        )

        def resolve_temporal(jn: ast.Join):
            """stream JOIN t FOR SYSTEM_TIME AS OF PROCTIME(): probe
            the build table's pk index at process time (ref
            temporal_join.rs; planner requires key == build pk like
            the reference's index-lookup form)."""
            from risingwave_tpu.stream.temporal_join import (
                TemporalJoinExecutor,
            )

            join_type = "inner" if jn.kind == "temporal" else "left_outer"
            lref, left = resolve(jn.left)
            rref, right = resolve(jn.right)
            n_left = len(left.schema)
            if not right.stream_key:
                raise PlanError(
                    "temporal join build side needs a PRIMARY KEY"
                )
            lkeys: list = []
            ridx: list[int] = []
            residual: list = []
            for conj in (self._conjuncts(jn.on) if jn.on is not None
                         else []):
                kp = self._equi_pair(
                    conj, left.scope, right.scope, n_left
                )
                if kp is None:
                    residual.append(conj)
                    continue
                lk, rk = kp
                if not isinstance(rk, InputRef):
                    raise PlanError(
                        "temporal join keys must be build-side columns"
                    )
                lkeys.append(lk)
                ridx.append(rk.index)
            if set(ridx) != set(right.stream_key):
                raise PlanError(
                    "temporal join requires equality keys covering the "
                    "build side's PRIMARY KEY exactly "
                    f"(got cols {sorted(ridx)}, pk "
                    f"{sorted(right.stream_key)})"
                )
            order = [ridx.index(pk) for pk in right.stream_key]
            join = TemporalJoinExecutor(
                left.schema, right.schema,
                [lkeys[i] for i in order], list(right.stream_key),
                table_size=cfg.join_table_size, join_type=join_type,
            )
            nodes.append(JoinNode(join, lref, rref))
            ref = ("node", len(nodes) - 1)
            both = Scope(
                join.out_schema,
                tuple(left.scope.qualifiers)
                + tuple(right.scope.qualifiers),
            )
            if residual:
                b = Binder(both)
                nodes.append(FragNode(Fragment([
                    FilterExecutor(both.schema, b.bind(c))
                    for c in residual
                ]), ref))
                ref = ("node", len(nodes) - 1)
            # build-side changes never retract outputs: append-only
            # follows the PROBE side alone
            info = PlannedInput(
                None, [], both, both.schema, None, None,
                left.append_only, stream_key=left.stream_key,
            )
            return ref, info

        def resolve_join(jn: ast.Join):
            if jn.kind in ("temporal", "temporal_left"):
                return resolve_temporal(jn)
            join_type = KIND_MAP.get(jn.kind)
            if join_type is None:
                raise PlanError(f"unsupported join kind {jn.kind!r}")
            lref, left = resolve(jn.left)
            rref, right = resolve(jn.right)
            n_left = len(left.schema)

            # split ON into equi-conjuncts and residual filters; a
            # comma join (no ON) pulls its equi-conditions out of WHERE
            if jn.on is not None:
                candidates = self._conjuncts(jn.on)
                from_where = False
            else:
                candidates = list(where_conjs)
                from_where = True
            left_keys: list[Expr] = []
            right_keys: list[Expr] = []
            residual: list = []
            for conj in candidates:
                keypair = self._equi_pair(
                    conj, left.scope, right.scope, n_left
                )
                if keypair is not None:
                    lk, rk = keypair
                    left_keys.append(lk)
                    right_keys.append(rk)
                    if from_where:
                        where_conjs.remove(conj)
                elif not from_where:
                    residual.append(conj)
            if not left_keys:
                raise PlanError(
                    "JOIN requires at least one equality condition"
                )
            if residual and join_type != "inner":
                # an ON predicate touching ONLY the null-padded side
                # pushes below the join as a filter on that input —
                # semantically exact for one-sided outer joins (rows
                # failing it simply don't match, and the preserved side
                # still pads).  TPC-H q13's `LEFT JOIN ... ON k AND
                # o_comment NOT LIKE ...` is this shape.
                pushable_side = None
                if join_type == "left_outer":
                    pushable_side = "right"
                elif join_type == "right_outer":
                    pushable_side = "left"
                if pushable_side is not None:
                    pin = right if pushable_side == "right" else left
                    other = left if pushable_side == "right" else right
                    kept: list = []
                    pushed: list = []
                    for conj in residual:
                        # pushable iff every column ref resolves on the
                        # padded side and NO unqualified ref also
                        # resolves on the preserved side (ambiguous —
                        # keep it so the full-scope bind raises instead
                        # of silently filtering the wrong side)
                        refs = self._column_refs(conj)
                        ok = bool(refs)
                        for r in refs:
                            try:
                                pin.scope.resolve(r.name, r.table)
                            except Exception:
                                ok = False
                                break
                            if r.table is None:
                                try:
                                    other.scope.resolve(r.name, None)
                                    ok = False  # ambiguous
                                    break
                                except Exception:
                                    pass
                        if not ok:
                            kept.append(conj)
                            continue
                        try:
                            pushed.append(FilterExecutor(
                                pin.scope.schema,
                                Binder(pin.scope).bind(conj),
                            ))
                        except Exception:
                            kept.append(conj)
                    if pushed:
                        src_ref = rref if pushable_side == "right" \
                            else lref
                        nodes.append(FragNode(Fragment(pushed), src_ref))
                        if pushable_side == "right":
                            rref = ("node", len(nodes) - 1)
                        else:
                            lref = ("node", len(nodes) - 1)
                    residual = kept
            if residual and join_type != "inner":
                # the count-based degree design assumes match == key
                # equality; a residual predicate would need in-executor
                # filtering (ref non-equi join conditions)
                raise PlanError(
                    "outer joins with non-equality ON conditions: "
                    "next round"
                )

            join = HashJoinExecutor(
                left.schema, right.schema, left_keys, right_keys,
                table_size=cfg.join_table_size,
                bucket_cap=cfg.join_bucket_cap,
                out_capacity=cfg.join_out_capacity,
                left_table_size=cfg.join_left_table_size,
                right_table_size=cfg.join_right_table_size,
                left_bucket_cap=cfg.join_left_bucket_cap,
                right_bucket_cap=cfg.join_right_bucket_cap,
                join_type=join_type,
                # append-only sides take the degree-adaptive shared
                # pool (no per-key cap for hot-skew keys); retractable
                # sides need delete-by-value and keep dense buckets
                left_storage="pool" if left.append_only
                and not cfg.join_force_dense else "dense",
                right_storage="pool" if right.append_only
                and not cfg.join_force_dense else "dense",
                left_pool_size=cfg.join_pool_size,
                right_pool_size=cfg.join_pool_size,
            )
            # the join's OUTPUT schema carries the pad nullability;
            # semi/anti joins emit only the preserved side's columns
            if join.is_semi or join.is_anti:
                pres = left if join.preserve_left else right
                both = Scope(join.out_schema,
                             tuple(pres.scope.qualifiers))
            else:
                both = Scope(
                    join.out_schema,
                    tuple(left.scope.qualifiers)
                    + tuple(right.scope.qualifiers),
                )
            # window-keyed joins over watermarked sources clean closed
            # windows at barriers (bounded state, ref q8 pattern)
            for side_name, pin, keys in (("left", left, left_keys),
                                         ("right", right, right_keys)):
                if pin.window_size is None or pin.watermark_col is None:
                    continue
                window_idxs = [
                    i for i, f in enumerate(pin.schema)
                    if f.name in ("window_start", "window_end")
                ]
                for ki, ke in enumerate(keys):
                    if isinstance(ke, InputRef) and ke.index in window_idxs:
                        setattr(join, f"{side_name}_clean",
                                (ki, pin.window_size, pin.watermark_col))
                        break
            nodes.append(JoinNode(join, lref, rref))
            ref = ("node", len(nodes) - 1)
            if residual:
                b = Binder(both)
                nodes.append(FragNode(Fragment([
                    FilterExecutor(both.schema, b.bind(c))
                    for c in residual
                ]), ref))
                ref = ("node", len(nodes) - 1)
            # outer-join transitions retract pads even over append-only
            # inputs, so only an inner join preserves append-only-ness
            if join.emit_pairs:
                skey = None
                if left.stream_key is not None \
                        and right.stream_key is not None:
                    skey = list(left.stream_key) + [
                        n_left + k for k in right.stream_key
                    ]
            else:
                skey = (left if join.preserve_left else right).stream_key
            info = PlannedInput(
                None, [], both, both.schema, None, None,
                left.append_only and right.append_only
                and join_type == "inner",
                stream_key=skey,
            )
            return ref, info

        root_ref, root = resolve(select.from_)
        both = root.scope
        post_execs: list[Executor] = []
        b = Binder(both)
        # WHERE conjuncts comparing a column against an uncorrelated
        # scalar subquery peel off into dynamic-filter nodes (ref
        # dynamic_filter.rs); the rest become post-join filters
        where_dyn: list = []
        for conj in list(where_conjs):
            m = self._match_scalar_sub_cmp(conj)
            if m is not None and isinstance(m[0], ast.ColumnRef) \
                    and self._is_uncorrelated(m[2]):
                where_dyn.append(m)
                where_conjs.remove(conj)
        for conj in where_conjs:
            post_execs.append(
                FilterExecutor(both.schema, b.bind(conj))
            )
        if where_dyn:
            from risingwave_tpu.stream.dynamic_filter import (
                DynamicFilterExecutor,
            )
            ref = root_ref
            if post_execs:
                nodes.append(FragNode(Fragment(post_execs), ref))
                ref = ("node", len(nodes) - 1)
                post_execs = []
            for lhs, cmp, sub in where_dyn:
                if len(sub.items) != 1 or isinstance(
                        sub.items[0].expr, ast.Star):
                    raise PlanError(
                        "scalar subquery must select exactly one column"
                    )
                sref, _sinfo = resolve_subquery(
                    ast.SubqueryRef(sub, f"_sc_sq{len(nodes)}")
                )
                nodes.append(JoinNode(DynamicFilterExecutor(
                    both.schema,
                    filter_col=both.resolve(lhs.name, lhs.table),
                    cmp=cmp,
                    pool_size=max(cfg.topn_pool_size,
                                  2 * cfg.chunk_capacity),
                ), ref, sref))
                ref = ("node", len(nodes) - 1)
            root_ref = ref
            # the dynamic filter's output retracts when the threshold
            # moves, even over append-only inputs
            import dataclasses as _dc
            root = _dc.replace(root, append_only=False)

        has_agg = bool(select.group_by) or self._has_agg(select)
        # HAVING conjuncts comparing an aggregate against a scalar
        # subquery peel off into dynamic-filter nodes (ref
        # dynamic_filter.rs — `HAVING agg >= (SELECT ...)`)
        having_subs: list = []
        if has_agg and select.having is not None:
            plain_hv: list = []
            for c in self._conjuncts(select.having):
                m = self._match_scalar_sub_cmp(c)
                if m is not None:
                    having_subs.append(m)
                else:
                    plain_hv.append(c)
            if having_subs:
                import dataclasses
                new_hv = None
                for r in plain_hv:
                    new_hv = r if new_hv is None \
                        else ast.BinaryOp("and", new_hv, r)
                select = dataclasses.replace(select, having=new_hv)
        if has_agg and having_subs:
            from risingwave_tpu.stream.dynamic_filter import (
                DynamicFilterExecutor,
            )
            execs2, out_schema, pk_pos, extra_pos = self._plan_agg(
                select, both, root,
                extra_out=[lhs for lhs, _, _ in having_subs],
            )
            post_execs.extend(execs2)
            nodes.append(FragNode(Fragment(post_execs), root_ref))
            ref = ("node", len(nodes) - 1)
            for (lhs, cmp, sub), pos in zip(having_subs, extra_pos):
                if len(sub.items) != 1 or isinstance(sub.items[0].expr,
                                                     ast.Star):
                    raise PlanError(
                        "scalar subquery must select exactly one column"
                    )
                sref, _sinfo = resolve_subquery(
                    ast.SubqueryRef(sub, f"_sc_sq{len(nodes)}")
                )
                nodes.append(JoinNode(DynamicFilterExecutor(
                    out_schema, filter_col=pos, cmp=cmp,
                    pool_size=max(cfg.topn_pool_size,
                                  2 * cfg.chunk_capacity),
                ), ref, sref))
                ref = ("node", len(nodes) - 1)
            tail: list[Executor] = []
            self._append_terminal(
                tail, out_schema, select,
                input_append_only=False, has_agg=True,
                pk_positions=pk_pos, sink=sink, eowc=False,
            )
            nodes.append(FragNode(Fragment(tail), ref))
            return DagPlan(
                sources, nodes, len(nodes) - 1, len(tail) - 1
            )
        if has_agg:
            if group_topn is not None:
                raise PlanError(
                    "row_number subquery over an aggregation: next round"
                )
            # aggregation over the joined stream (TPC-H/q4 shape): the
            # join's retractions flow into the agg, which handles them
            execs2, out_schema, pk_pos = self._plan_agg(
                select, both, root
            )
            post_execs.extend(execs2)
            self._append_terminal(
                post_execs, out_schema, select,
                input_append_only=False, has_agg=True,
                pk_positions=pk_pos, sink=sink, eowc=False,
            )
        else:
            items = self._expand_items(select.items, both)
            proj = [(name, b.bind(e)) for name, e in items]
            pk_positions: list[int] = []
            if sink is None and not root.append_only \
                    and root.stream_key is not None:
                # keyed by the join output's stream key (left ++ right
                # input keys) so duplicate projected rows keep multiset
                # semantics (e.g. nexmark q5: identical (auction, num)
                # rows from different windows)
                pk_positions = self._stream_key_projection(
                    proj, both.schema, root.stream_key
                )
            gtn = None
            if group_topn is not None:
                gtn = self._resolve_group_topn(group_topn, both, proj)
            post_execs.append(ProjectExecutor(both.schema, proj))
            out_schema = post_execs[-1].out_schema
            self._append_terminal(
                post_execs, out_schema, select,
                input_append_only=root.append_only, has_agg=False,
                pk_positions=pk_positions, sink=sink, eowc=False,
                group_topn=gtn,
            )
        nodes.append(FragNode(Fragment(post_execs), root_ref))
        return DagPlan(
            sources, nodes, len(nodes) - 1, len(post_execs) - 1
        )

    def _conjuncts(self, e) -> list:
        if isinstance(e, ast.BinaryOp) and e.op == "and":
            return self._conjuncts(e.left) + self._conjuncts(e.right)
        return [e]

    _SUB_CMPS = {"greater_than": "gt", "greater_than_or_equal": "ge",
                 "less_than": "lt", "less_than_or_equal": "le",
                 "equal": "eq"}
    _SUB_FLIP = {"gt": "lt", "ge": "le", "lt": "gt", "le": "ge",
                 "eq": "eq"}

    def _match_scalar_sub_cmp(self, c):
        """``lhs CMP (SELECT ...)`` → (lhs_ast, cmp, sub_select)."""
        if not (isinstance(c, ast.BinaryOp)
                and c.op in self._SUB_CMPS):
            return None
        cmp = self._SUB_CMPS[c.op]
        if isinstance(c.right, ast.ScalarSubquery) \
                and not isinstance(c.left, ast.ScalarSubquery):
            return (c.left, cmp, c.right.select)
        if isinstance(c.left, ast.ScalarSubquery) \
                and not isinstance(c.right, ast.ScalarSubquery):
            return (c.right, self._SUB_FLIP[cmp], c.left.select)
        return None

    def _equi_pair(self, e, lscope: Scope, rscope: Scope, n_left: int):
        if not (isinstance(e, ast.BinaryOp) and e.op == "equal"):
            return None
        sides = []
        for operand in (e.left, e.right):
            try:
                lb = Binder(lscope).bind(operand)
                sides.append(("l", lb))
                continue
            except BindError:
                pass
            try:
                rb = Binder(rscope).bind(operand)
                sides.append(("r", rb))
            except BindError:
                return None
        if len(sides) != 2 or {s[0] for s in sides} != {"l", "r"}:
            return None
        l = next(x for t, x in sides if t == "l")
        r = next(x for t, x in sides if t == "r")
        return l, r

    # -- misc -------------------------------------------------------------
    def _expand_items(self, items, scope: Scope):
        out = []
        for idx, item in enumerate(items):
            if isinstance(item.expr, ast.Star):
                want = item.expr.table
                if want is not None and want not in scope.qualifiers:
                    raise PlanError(
                        f"table {want!r} in {want}.* not found in FROM"
                    )
                for ci, f in enumerate(scope.schema):
                    # pk bookkeeping columns of an upstream MV are not
                    # user-visible (each plan re-derives its own)
                    if f.name.startswith("_hidden_"):
                        continue
                    if want is not None and scope.qualifiers[ci] != want:
                        continue
                    out.append((f.name, ast.ColumnRef(f.name,
                                                      scope.qualifiers[ci])))
                continue
            out.append(
                (item.alias or self._default_name(item.expr, idx), item.expr)
            )
        return out

    @staticmethod
    def _bind_order_key(e, binder: Binder, schema: Schema) -> Expr:
        """ORDER BY <n> is positional (postgres); otherwise bind."""
        if isinstance(e, ast.Literal) and e.type_name == "int":
            if not (1 <= e.value <= len(schema)):
                raise PlanError(f"ORDER BY position {e.value} out of range")
            return InputRef(e.value - 1)
        return binder.bind(e)

    @staticmethod
    def _default_name(e, idx: int) -> str:
        if isinstance(e, ast.ColumnRef):
            return e.name
        if isinstance(e, ast.FuncCall):
            return e.name
        return f"col{idx}"
