"""Binder: SQL AST expressions → typed engine expressions.

Reference counterpart: ``src/frontend/src/binder/`` — name resolution
against the in-scope schema, type derivation, agg-call extraction.
"""

from __future__ import annotations

from dataclasses import dataclass

from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr import agg as agg_mod
from risingwave_tpu.expr.node import (
    Expr,
    FuncCall as EFuncCall,
    InputRef,
    Literal as ELiteral,
    as_expr,
)
from risingwave_tpu.sql import ast

AGG_NAMES = {"count", "sum", "avg", "min", "max"}


class BindError(ValueError):
    pass


@dataclass
class Scope:
    """Visible columns: (qualifier, name) -> input position."""

    schema: Schema
    qualifiers: tuple  # per-column table qualifier (or None)

    @staticmethod
    def of(schema: Schema, qualifier: str | None = None) -> "Scope":
        return Scope(schema, tuple(qualifier for _ in schema))

    def concat(self, other: "Scope") -> "Scope":
        return Scope(
            self.schema.concat(other.schema),
            self.qualifiers + other.qualifiers,
        )

    def resolve(self, name: str, table: str | None) -> int:
        hits = [
            i for i, (f, q) in enumerate(zip(self.schema, self.qualifiers))
            if f.name == name and (table is None or q == table)
        ]
        if not hits:
            raise BindError(f"column {table + '.' if table else ''}{name} "
                            "not found")
        if len(hits) > 1:
            raise BindError(f"column {name} is ambiguous")
        return hits[0]


class Binder:
    """Binds scalar expressions; collects aggregate calls when allowed."""

    def __init__(self, scope: Scope, allow_aggs: bool = False):
        self.scope = scope
        self.allow_aggs = allow_aggs
        self.agg_calls: list[agg_mod.AggCall] = []

    def bind(self, e) -> Expr:
        if isinstance(e, ast.ColumnRef):
            return InputRef(self.scope.resolve(e.name, e.table))
        if isinstance(e, ast.Literal):
            if e.type_name == "string":
                return ELiteral(e.value, DataType.VARCHAR)
            if e.type_name == "bool":
                return ELiteral(e.value, DataType.BOOLEAN)
            if e.type_name == "float":
                # PG: a decimal-point literal is NUMERIC, not float —
                # exact arithmetic/comparisons against DECIMAL columns
                # (0.08 - 0.01 must equal 0.07 exactly); float contexts
                # promote it back to float via the numeric lattice.
                # Literals the scaled-int64 representation cannot hold
                # exactly (needs >6 dp, or overflows) stay FLOAT64.
                v = e.value
                if abs(v) < 9e12 and round(v * 10**6) / 10**6 == v:
                    return ELiteral(v, DataType.DECIMAL)
                return ELiteral(v, DataType.FLOAT64)
            if e.type_name == "int":
                return as_expr(e.value)
            if e.type_name == "date":
                return ELiteral(e.value, DataType.DATE)
            if e.type_name == "timestamp":
                return ELiteral(e.value, DataType.TIMESTAMP)
            if e.type_name == "null":
                # untyped NULL defaults to int64; casts/CASE re-type it
                return ELiteral(None, DataType.INT64)
            raise BindError(f"unsupported literal {e}")
        if isinstance(e, ast.IntervalLit):
            if e.months:
                raise BindError(
                    "month/year intervals are supported only in "
                    "date/timestamp literal arithmetic (folded at bind "
                    "time)"
                )
            return ELiteral(e.micros, DataType.INTERVAL)
        if isinstance(e, ast.UnaryOp):
            return EFuncCall(e.op, (self.bind(e.operand),))
        if isinstance(e, ast.BinaryOp):
            folded = self._fold_datetime_arith(e)
            if folded is not None:
                return folded
            return EFuncCall(e.op, (self.bind(e.left), self.bind(e.right)))
        if isinstance(e, ast.Cast):
            t = DataType.from_sql(e.type_name)
            return EFuncCall(f"cast_{t.name.lower()}", (self.bind(e.operand),))
        if isinstance(e, ast.Case):
            if e.else_result is None:
                # CASE without ELSE yields NULL (SQL); type follows the
                # first THEN branch
                then0 = self.bind(e.conditions[0][1])
                t = then0.return_field(self.scope.schema).data_type
                out: Expr = ELiteral(None, t)
            else:
                out = self.bind(e.else_result)
            for c, r in reversed(e.conditions):
                out = EFuncCall("case", (self.bind(c), self.bind(r), out))
            return out
        if isinstance(e, ast.FuncCall):
            if e.name in AGG_NAMES:
                return self._bind_agg(e)
            if e.filter_where is not None:
                # postgres: "FILTER specified, but <fn> is not an
                # aggregate function"
                raise BindError(
                    f"FILTER specified, but {e.name} is not an "
                    "aggregate function"
                )
            if e.name == "like":
                return self._bind_like(e)
            if e.name == "to_char":
                return self._bind_to_char(e)
            if e.name == "array_index":
                return self._bind_array_index(e)
            if e.name == "regexp_match":
                raise BindError(
                    "regexp_match is supported only as "
                    "(regexp_match(s, 'pat'))[n]"
                )
            if e.name == "split_part" and len(e.args) == 3 \
                    and isinstance(e.args[2], ast.Literal) \
                    and e.args[2].type_name == "int" \
                    and e.args[2].value == 0:
                # ref split_part.rs: position 0 is an error, not empty;
                # the argument is almost always a literal so reject at
                # bind time (the device kernel cannot raise per-row)
                raise BindError("field position must not be zero")
            args = tuple(self.bind(a) for a in e.args)
            # untyped NULL literals adopt the type of a typed sibling
            # (COALESCE(x, NULL), CASE branches, IS NULL over NULL...)
            typed = [a for a in args
                     if not (isinstance(a, ELiteral) and a.value is None)]
            if typed and len(typed) != len(args):
                t_field = typed[0].return_field(self.scope.schema)
                args = tuple(
                    ELiteral(None, t_field.data_type)
                    if isinstance(a, ELiteral) and a.value is None else a
                    for a in args
                )
            return EFuncCall(e.name, args)
        raise BindError(f"cannot bind {e!r}")

    def _fold_datetime_arith(self, e: ast.BinaryOp):
        """Constant-fold ``DATE/TIMESTAMP literal ± INTERVAL`` at bind
        time (the only supported home of month/year intervals: calendar
        months have no fixed micros — ref Interval {months,days,usecs}
        arithmetic, src/common/src/types/interval.rs)."""
        import datetime as _dt

        if e.op not in ("add", "subtract"):
            return None
        lit, iv = e.left, e.right
        if not (isinstance(lit, ast.Literal)
                and lit.type_name in ("date", "timestamp")
                and isinstance(iv, ast.IntervalLit)):
            return None
        sign = 1 if e.op == "add" else -1
        if lit.type_name == "date":
            base = _dt.datetime(1970, 1, 1) + _dt.timedelta(days=lit.value)
        else:
            base = _dt.datetime(1970, 1, 1) \
                + _dt.timedelta(microseconds=lit.value)
        if iv.months:
            total = base.year * 12 + (base.month - 1) + sign * iv.months
            y, m = divmod(total, 12)
            # clamp the day into the target month (PG: Jan 31 + 1 mon
            # = Feb 28)
            for day in (base.day, 30, 29, 28):
                try:
                    base = base.replace(year=y, month=m + 1, day=day)
                    break
                except ValueError:
                    continue
        base = base + _dt.timedelta(microseconds=sign * iv.micros)
        if lit.type_name == "date" and base.time() == _dt.time(0, 0):
            days = (base.date() - _dt.date(1970, 1, 1)).days
            return ELiteral(days, DataType.DATE)
        # exact integer microseconds (float total_seconds() rounds)
        us = (base - _dt.datetime(1970, 1, 1)) \
            // _dt.timedelta(microseconds=1)
        return ELiteral(us, DataType.TIMESTAMP)

    def _bind_like(self, e: ast.FuncCall) -> Expr:
        """LIKE with literal %-only patterns: single-segment forms
        compile to prefix/suffix/substring kernels; multi-segment
        interior-% patterns compile to the sequential-scan LikePattern
        kernel ('_' wildcards remain unsupported)."""
        target, pat = e.args
        if not (isinstance(pat, ast.Literal) and pat.type_name == "string"):
            raise BindError("LIKE requires a string literal pattern")
        p = pat.value
        if "_" in p:
            raise BindError("LIKE '_' wildcards not yet supported")
        lhs = self.bind(target)
        body = p.strip("%")
        if "%" in body:
            from risingwave_tpu.expr.scalar import LikePattern
            return LikePattern(lhs, p)
        lit_body = ELiteral(body, DataType.VARCHAR)
        if p.startswith("%") and p.endswith("%"):
            return EFuncCall("contains", (lhs, lit_body))
        if p.endswith("%"):
            return EFuncCall("starts_with", (lhs, lit_body))
        if p.startswith("%"):
            return EFuncCall("ends_with", (lhs, lit_body))
        return EFuncCall("equal", (lhs, lit_body))

    def _bind_to_char(self, e: ast.FuncCall) -> Expr:
        """to_char(ts, 'fmt'): the PG pattern compiles at bind time into
        a fixed-width device kernel (ref to_char.rs ChronoPattern —
        there compiled per call via an LRU, here once per plan)."""
        from risingwave_tpu.expr.scalar import ToChar

        if len(e.args) != 2:
            raise BindError("to_char takes (timestamp, format)")
        fmt = e.args[1]
        if not (isinstance(fmt, ast.Literal) and fmt.type_name == "string"):
            raise BindError("to_char requires a literal format string")
        arg = self.bind(e.args[0])
        t = arg.return_field(self.scope.schema).data_type
        if t == DataType.DATE:
            # DATE is i32 days; the formatter consumes i64 microseconds
            arg = EFuncCall("cast_timestamp", (arg,))
        elif t not in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
            raise BindError(f"to_char over {t.name} not supported")
        return ToChar(arg, fmt.value)

    def _bind_array_index(self, e: ast.FuncCall) -> Expr:
        """Array subscripts exist only for regexp_match captures this
        round: ``(regexp_match(s, 'pat'))[n]`` compiles to a bounded
        byte kernel (scalar.RegexpGroup)."""
        from risingwave_tpu.expr.scalar import RegexpGroup

        target, idx = e.args
        if not (isinstance(target, ast.FuncCall)
                and target.name == "regexp_match"):
            raise BindError(
                "array subscripts are supported on regexp_match only"
            )
        if len(target.args) != 2:
            raise BindError("regexp_match takes (string, pattern)")
        pat = target.args[1]
        if not (isinstance(pat, ast.Literal)
                and pat.type_name == "string"):
            raise BindError("regexp_match requires a literal pattern")
        arg = self.bind(target.args[0])
        try:
            return RegexpGroup(arg, pat.value, idx.value)
        except ValueError as err:
            raise BindError(str(err))

    def _bind_agg(self, e: ast.FuncCall) -> Expr:
        if not self.allow_aggs:
            raise BindError(f"aggregate {e.name} not allowed here")
        # DISTINCT composes for every kind: count/sum/avg states update
        # on dedup transitions; min/max are distinct-insensitive
        filt = None
        if e.filter_where is not None:
            # the filter predicate binds against the agg INPUT scope
            # (no aggregates inside it)
            filt = Binder(self.scope).bind(e.filter_where)
        if e.name == "count" and (not e.args or
                                  isinstance(e.args[0], ast.Star)):
            if e.distinct:
                raise BindError("COUNT(DISTINCT *) is not valid")
            call = agg_mod.AggCall("count_star", None, filter=filt)
        else:
            arg = self.bind(e.args[0])
            call = agg_mod.AggCall(e.name, arg, distinct=e.distinct,
                                   filter=filt)
        self.agg_calls.append(call)
        # placeholder referencing the agg output (resolved by the planner:
        # agg outputs are appended after the group keys)
        return AggRef(len(self.agg_calls) - 1, call)


@dataclass(frozen=True, eq=False)
class AggRef(Expr):
    """A reference to the i-th aggregate output (planner placeholder)."""

    index: int
    call: agg_mod.AggCall

    def return_field(self, schema):
        return self.call.out_field(schema)

    def eval(self, chunk):  # pragma: no cover - replaced by planner
        raise RuntimeError("AggRef must be rewritten by the planner")
